"""FL006: whole-program lock-order discipline.

The reference's flow runtime never deadlocks on mutexes because actors
do not hold them across waits; the Python port holds real
``threading`` locks across real calls, so the classic failure mode is
ABBA — thread 1 acquires ``A`` then ``B``, thread 2 acquires ``B``
then ``A``. This rule extracts every lock/Condition acquisition site
from the shared :class:`~foundationdb_tpu.analysis.model.ProgramModel`,
builds the inter-procedural acquisition graph (lexical ``with``
nesting plus locks transitively acquired by resolvable callees), and:

* on ANY scan: fails on a potential cycle in the graph (an ABBA pair
  or longer ring), unless the participating edges are sanctioned as a
  reviewed ``A <> B`` pair in ``analysis/lockorder.txt``;
* on a FULL-TREE scan: additionally requires the computed edge set to
  match the checked-in ``lockorder.txt`` witness exactly — an edge the
  file does not declare is an undeclared ordering (review it, then
  ``--fix-lockorder``), and a declared edge the tree no longer
  produces is stale, exactly like a stale baseline entry.

Lock identity is class-based (``"BatchingCommitProxy._lock"``), the
same names the runtime lockdep witness (``utils/lockdep.py``) records,
so the static graph and the dynamic witness cross-check byte-for-byte.
``threading.Condition(self._lock)`` aliases the wrapped lock: the
condition and its mutex are ONE node, which is what makes the
``with self._wake: ... with self._lock:`` re-entry idiom clean rather
than a self-edge.

Call resolution is deliberately conservative: ``self.m()`` resolves
through the class and its bases; bare names resolve to same-file (or
globally unique) module functions; ``obj.m()`` resolves through a
global method-name index only when at most ``_METHOD_CAP`` classes
define ``m`` — ubiquitous names (``close``, ``get``) resolve nowhere
rather than everywhere, which keeps the graph honest enough that the
runtime witness's observed edges stay a subset of this rule's edges
(pinned by ``tests/test_flowlint_v2.py``).

lockorder.txt format::

    # comments and blanks ignored
    LockA -> LockB          # LockB acquired while LockA held
    LockA <> LockB          # reviewed pair: cycles through A/B sanctioned

Format of the lines is exact (one edge per line, names as emitted);
``python -m foundationdb_tpu.analysis.flowlint --fix-lockorder``
regenerates the ``->`` section and preserves still-live ``<>`` lines.
"""

import ast
import os

from foundationdb_tpu.analysis.base import Finding, dotted_name

RULE = "FL006"
TITLE = "lock-order"
PROGRAM = True

LOCKORDER_RELPATH = "analysis/lockorder.txt"

# obj.m() resolves through the global method index only when <= this
# many classes define m — generic names resolve nowhere, not everywhere
_METHOD_CAP = 5
# x.attr resolves to a lock via the attr-name index only when <= this
# many classes declare a lock under that attribute name
_ATTR_CAP = 3

# a bare builtin name is the builtin unless the SAME file shadows it —
# the package's top-level ``open()`` (the fdb API entry point) must not
# swallow every ``open(path)`` file call in the tree
import builtins as _builtins

_BUILTIN_NAMES = frozenset(dir(_builtins))

# dict/list/set method names never resolve through the method index:
# ``self._queue.pop()`` is a container op, not ``SomeClass.pop`` —
# matching it cross-class would wire container calls into the call
# graph of whichever classes happen to define the name
_CONTAINER_METHODS = frozenset({
    "append", "extend", "insert", "remove", "pop", "clear", "copy",
    "sort", "reverse", "index", "count", "add", "discard", "update",
    "get", "setdefault", "keys", "values", "items", "popitem",
    "join", "split", "strip", "encode", "decode", "format",
    "startswith", "endswith", "read", "write", "flush", "seek",
    "tell", "readline", "readlines",
})


def applies(relpath):
    return True


class _FuncInfo:
    __slots__ = ("fm", "cm", "node", "name", "locks", "entry_locks",
                 "calls", "edges")

    def __init__(self, fm, cm, node):
        self.fm = fm
        self.cm = cm
        self.node = node
        self.name = (f"{cm.name}.{node.name}" if cm else node.name)
        self.locks = set()        # every lock id acquired lexically
        self.entry_locks = set()  # ids acquired while holding NOTHING
        self.calls = []           # (call, top_ids, outer_ids, line)
        self.edges = {}       # (a, b) -> (relpath, line) lexical edges


def _iter_functions(model):
    for fm in model.files.values():
        if fm.tree is None:
            continue
        for cm in fm.classes.values():
            for node in cm.methods.values():
                yield _FuncInfo(fm, cm, node)
        for node in fm.module_funcs.values():
            yield _FuncInfo(fm, None, node)


class _Analyzer:
    def __init__(self, model, info):
        self.model = model
        self.info = info
        self.aliases = {}      # local name -> frozenset of lock ids
        self.local_locks = {}  # local name -> lock id (constructed here)
        self._collect_locals()

    def _collect_locals(self):
        from foundationdb_tpu.analysis.model import _lock_ctor

        cm = self.info.cm
        fname = self.info.node.name
        owner = cm.name if cm else self.info.fm.module_stem()
        for sub in ast.walk(self.info.node):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name):
                ctor = _lock_ctor(sub.value)
                if ctor is not None:
                    kind, literal, wrapped = ctor
                    lock_id = literal
                    if lock_id is None and wrapped is not None:
                        ids = self.resolve(wrapped)
                        lock_id = min(ids) if ids else None
                    if lock_id is None:
                        lock_id = f"{owner}.{fname}.{sub.targets[0].id}"
                    self.local_locks[sub.targets[0].id] = lock_id
        # two passes so alias-of-alias assignments settle regardless of
        # walk order (the tree only ever needs one hop)
        for _ in range(2):
            for sub in ast.walk(self.info.node):
                if isinstance(sub, ast.Assign) and \
                        len(sub.targets) == 1 and \
                        isinstance(sub.targets[0], ast.Name) and \
                        _lock_ctor(sub.value) is None:
                    ids = self.resolve(sub.value)
                    if ids:
                        self.aliases[sub.targets[0].id] = ids

    def resolve(self, expr):
        """Lock ids an expression may denote (frozenset, possibly
        empty). Conservative: unresolvable means no ids, not all."""
        model, cm = self.model, self.info.cm
        if isinstance(expr, ast.Name):
            if expr.id in self.local_locks:
                return frozenset((self.local_locks[expr.id],))
            if expr.id in self.aliases:
                return self.aliases[expr.id]
            if expr.id in self.info.fm.module_locks:
                return frozenset((self.info.fm.module_locks[expr.id],))
            return frozenset()
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name) and base.id == "self" and \
                    cm is not None:
                lock_id = model.lock_attr(cm, expr.attr)
                return frozenset((lock_id,)) if lock_id else frozenset()
            # mod.X through an import binding: another tree module's
            # module-level lock, or nothing if the module is external
            if isinstance(base, ast.Name) and \
                    base.id in self.info.fm.import_files:
                rp = self.info.fm.import_files[base.id]
                f2 = model.files.get(rp) if rp else None
                if f2 is not None and expr.attr in f2.module_locks:
                    return frozenset((f2.module_locks[expr.attr],))
                return frozenset()
            # self.f.X through a known field type (None = external
            # class: typed, but definitely owns no tree lock)
            if isinstance(base, ast.Attribute) and \
                    isinstance(base.value, ast.Name) and \
                    base.value.id == "self" and cm is not None and \
                    base.attr in cm.field_types:
                ftype = cm.field_types[base.attr]
                if ftype:
                    c2 = model.resolve_class(ftype)
                    if c2 is not None:
                        lock_id = model.lock_attr(c2, expr.attr)
                        if lock_id:
                            return frozenset((lock_id,))
                return frozenset()
            # cross-object by attribute name, capped so ubiquitous
            # names ("_lock") resolve nowhere rather than everywhere
            ids = model.lock_attr_index.get(expr.attr)
            if ids and len(ids) <= _ATTR_CAP:
                return frozenset(ids)
        return frozenset()

    def resolve_call(self, call):
        """AST nodes of the callables this call may reach."""
        model, fm, cm = self.model, self.info.fm, self.info.cm
        fn = call.func
        if isinstance(fn, ast.Name):
            if fn.id in fm.module_funcs:
                return [fm.module_funcs[fn.id]]
            if fn.id in _BUILTIN_NAMES:
                return []
            hits = model.func_index.get(fn.id, [])
            if len(hits) == 1:
                return [hits[0][1]]
            # ClassName(...) runs __init__
            target_cm = model.resolve_class(fn.id)
            if target_cm is not None:
                hit = model.lookup_method(target_cm, "__init__")
                if hit is not None:
                    return [hit[1]]
            return []
        if not isinstance(fn, ast.Attribute):
            return []
        name = fn.attr
        base = fn.value
        if isinstance(base, ast.Name) and base.id == "self" and \
                cm is not None:
            hit = model.lookup_method(cm, name)
            if hit is not None:
                return [hit[1]]
            # self.<callable-field>() — untypable; fall through to the
            # capped index only if the field has a known class type
            return []
        if isinstance(base, ast.Name) and base.id in fm.import_files:
            # mod.f() / mod.Class() through an import binding: precise
            # for tree modules, nothing for external ones (os.path,
            # threading, ... must never hit the name index)
            rp = fm.import_files[base.id]
            f2 = model.files.get(rp) if rp else None
            if f2 is not None:
                if name in f2.module_funcs:
                    return [f2.module_funcs[name]]
                c2 = f2.classes.get(name)
                if c2 is not None:
                    hit = model.lookup_method(c2, "__init__")
                    if hit is not None:
                        return [hit[1]]
            return []
        if isinstance(base, ast.Attribute) and not (
                isinstance(base.value, ast.Name)
                and base.value.id == "self"):
            root = base
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) and \
                    root.id in fm.import_files:
                # dotted module chain (os.path.exists, pkg.mod.fn):
                # never a tree-object method call
                return []
        if isinstance(base, ast.Call) and \
                isinstance(base.func, ast.Name) and \
                base.func.id == "super" and cm is not None:
            for c in self.model.class_and_bases(cm)[1:]:
                if name in c.methods:
                    return [c.methods[name]]
            return []
        if isinstance(base, ast.Attribute) and \
                isinstance(base.value, ast.Name) and \
                base.value.id == "self" and cm is not None and \
                base.attr in cm.field_types:
            ftype = cm.field_types[base.attr]
            if ftype:
                c2 = model.resolve_class(ftype)
                if c2 is not None:
                    hit = model.lookup_method(c2, name)
                    if hit is not None:
                        return [hit[1]]
            # typed field (tree class without the method, or external
            # like threading.Thread): never guess via the name index
            return []
        if name in _CONTAINER_METHODS:
            return []
        hits = model.method_index.get(name, [])
        if 0 < len(hits) <= _METHOD_CAP:
            return [h[2] for h in hits]
        return []

    # ── the held-stack walk ──
    def run(self):
        self._stmts(self.info.node.body, [])

    def _stmts(self, stmts, held):
        for st in stmts:
            self._stmt(st, held)

    def _stmt(self, st, held):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return  # nested defs run later, not here
        if isinstance(st, (ast.With, ast.AsyncWith)):
            ids = frozenset()
            for item in st.items:
                self._expr(item.context_expr, held)
                ids |= self.resolve(item.context_expr)
                if item.optional_vars is not None and \
                        isinstance(item.optional_vars, ast.Name) and ids:
                    self.aliases[item.optional_vars.id] = ids
            outer = set().union(*held) if held else set()
            new = ids - outer
            if held and new:
                site = (self.info.fm.relpath, st.lineno)
                for a in sorted(held[-1]):
                    for b in sorted(new):
                        self.info.edges.setdefault((a, b), site)
            elif new:
                self.info.entry_locks |= new
            self._stmts(st.body, held + [new] if new else held)
            if ids:
                self.info.locks |= ids
            return
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.stmt):
                self._stmt(child, held)
            elif isinstance(child, ast.expr):
                self._expr(child, held)
            elif isinstance(child, (ast.excepthandler,)):
                self._stmts(child.body, held)
            elif isinstance(child, ast.withitem):
                self._expr(child.context_expr, held)
        # orelse/finalbody/body lists reached via iter_child_nodes

    def _expr(self, expr, held):
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call):
                top = frozenset(held[-1]) if held else frozenset()
                outer = frozenset().union(*held) if held else frozenset()
                self.info.calls.append(
                    (sub, top, outer, getattr(sub, "lineno", 0)))


def compute_graph(model):
    """(edges, funcs): edges maps (a, b) -> first (relpath, line) site.

    Edges mirror the runtime witness's ADJACENCY semantics: lexical
    ``with`` nesting, plus — for a call made while holding a lock —
    the callee's ENTRY locks (locks it may acquire while its own held
    stack is empty, transitively through calls it makes unlocked).
    Deeper nesting inside the callee produces its own edges at its own
    sites, so transitive ordering shows as a path A -> B -> C, not a
    flattened closure — which keeps lockorder.txt reviewable and
    matches exactly what the dynamic lockdep records."""
    funcs = []
    for info in _iter_functions(model):
        an = _Analyzer(model, info)
        an.run()
        funcs.append((info, an))

    # entry summaries: locks a function may acquire with nothing held
    entry = {info.node: set(info.entry_locks) for info, _ in funcs}
    resolved_calls = {}
    for info, an in funcs:
        rc = []
        for call, top, outer, line in info.calls:
            callees = [c for c in an.resolve_call(call) if c in entry]
            if callees:
                rc.append((callees, top, outer, line))
        resolved_calls[info.node] = rc
    changed = True
    while changed:
        changed = False
        for info, _ in funcs:
            s = entry[info.node]
            before = len(s)
            for callees, top, _, _ in resolved_calls[info.node]:
                if top:
                    continue  # held-call acquisitions are not entry
                for c in callees:
                    s |= entry[c]
            if len(s) != before:
                changed = True

    edges = {}
    for info, _ in funcs:
        for key, site in sorted(info.edges.items()):
            edges.setdefault(key, site)
        for callees, top, outer, line in resolved_calls[info.node]:
            if not top:
                continue
            reach = set()
            for c in callees:
                reach |= entry[c]
            site = (info.fm.relpath, line)
            for a in sorted(top):
                for b in sorted(reach - set(outer)):
                    if a != b:
                        edges.setdefault((a, b), site)
    return edges, funcs


# ── lockorder.txt ──
def load_lockorder(text):
    """(declared_edges {(a,b): line}, sanctioned_pairs
    {frozenset({a,b}): line})."""
    declared, pairs = {}, {}
    for i, line in enumerate(text.splitlines(), 1):
        body = line.split("#", 1)[0].strip()
        if not body:
            continue
        if "<>" in body:
            a, _, b = body.partition("<>")
            pairs[frozenset((a.strip(), b.strip()))] = i
        elif "->" in body:
            a, _, b = body.partition("->")
            declared[(a.strip(), b.strip())] = i
    return declared, pairs


def _lockorder_path(model):
    if model.package_root:
        return os.path.join(model.package_root, "analysis",
                            "lockorder.txt")
    return None


def _read_lockorder(model):
    path = _lockorder_path(model)
    if path and os.path.exists(path):
        with open(path, encoding="utf-8") as f:
            return f.read()
    return ""


def format_lockorder(edges, pairs):
    """The witness file: preserved sanctioned pairs, then every
    computed edge not covered by a pair, sorted."""
    header = (
        "# flowlint FL006 lock-order witness — the tree's complete\n"
        "# inter-procedural lock-acquisition graph, one edge per "
        "line:\n"
        "#   A -> B    B is acquired while A is held\n"
        "#   A <> B    reviewed pair: cycles through A/B are "
        "sanctioned\n"
        "# Regenerate the '->' section: python -m "
        "foundationdb_tpu.analysis.flowlint --fix-lockorder\n"
        "# An edge here the tree no longer produces is STALE and "
        "fails the\n"
        "# lint (like a stale baseline entry); a new edge fails until "
        "it is\n"
        "# reviewed and recorded here.\n"
    )
    lines = [header]
    for pair in sorted(pairs, key=sorted):
        a, b = sorted(pair)
        lines.append(f"{a} <> {b}\n")
    covered = {tuple(sorted(p)) for p in pairs}
    for a, b in sorted(edges):
        if tuple(sorted((a, b))) in covered:
            continue
        lines.append(f"{a} -> {b}\n")
    return "".join(lines)


def rewrite_lockorder(model):
    edges, _ = compute_graph(model)
    _, pairs = load_lockorder(_read_lockorder(model))
    live = {}
    for pair, line in pairs.items():
        a, b = sorted(pair)
        if (a, b) in edges or (b, a) in edges:
            live[pair] = line
    path = _lockorder_path(model)
    if path is None:
        raise RuntimeError("lockorder path requires a full-tree scan")
    with open(path, "w", encoding="utf-8") as f:
        f.write(format_lockorder(edges, live))
    return path


# ── cycles ──
def _sccs(nodes, adj):
    """Tarjan, iterative; yields SCCs with >= 2 nodes."""
    index = {}
    low = {}
    on_stack = set()
    stack = []
    counter = [0]
    out = []
    for root in sorted(nodes):
        if root in index:
            continue
        work = [(root, iter(sorted(adj.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                u = work[-1][0]
                low[u] = min(low[u], low[v])
            if low[v] == index[v]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == v:
                        break
                if len(scc) > 1:
                    out.append(sorted(scc))
    return out


def _cycle_path(scc, adj):
    """A concrete cycle within the SCC, starting at its min node."""
    start = scc[0]
    members = set(scc)
    path = [start]
    seen = {start}
    node = start
    while True:
        nxt = None
        for w in sorted(adj.get(node, ())):
            if w == start and len(path) > 1:
                return path + [start]
            if w in members and w not in seen:
                nxt = w
                break
        if nxt is None:
            return path + [start]  # SCC guarantees an edge back
        path.append(nxt)
        seen.add(nxt)
        node = nxt


def find_cycles(edges, sanctioned_pairs):
    adj = {}
    for (a, b) in edges:
        if frozenset((a, b)) in sanctioned_pairs:
            continue
        adj.setdefault(a, set()).add(b)
    nodes = set(adj)
    for tos in adj.values():
        nodes |= tos
    return [( _cycle_path(scc, adj), scc) for scc in _sccs(nodes, adj)]


def check_model(model):
    edges, _ = compute_graph(model)
    declared, pairs = ({}, {})
    lockorder_text = _read_lockorder(model) if model.full_tree else ""
    if model.full_tree:
        declared, pairs = load_lockorder(lockorder_text)
    else:
        # fixture scans still honor sanctioned pairs when the source
        # set happens to include a lockorder file? No file: structural
        # cycle detection only.
        pass

    for cycle_path, scc in find_cycles(edges, pairs):
        arrows = " -> ".join(cycle_path)
        first = tuple(cycle_path[:2])
        site = edges.get(first)
        if site is None:
            site = edges[sorted(
                k for k in edges if k[0] in scc and k[1] in scc)[0]]
        yield Finding(
            RULE, site[0], site[1],
            f"potential lock-order cycle: {arrows} — break the "
            f"ordering, or sanction the reviewed pair with "
            f"'{scc[0]} <> {scc[1]}' in {LOCKORDER_RELPATH}")

    if not model.full_tree:
        return

    covered = {tuple(sorted(p)) for p in pairs}
    for (a, b), site in sorted(edges.items(), key=lambda kv: kv[1]):
        if (a, b) in declared or tuple(sorted((a, b))) in covered:
            continue
        yield Finding(
            RULE, site[0], site[1],
            f"undeclared lock-order edge: {a} -> {b} (acquires '{b}' "
            f"while holding '{a}') — review, then record it via "
            f"--fix-lockorder")
    for (a, b), line in sorted(declared.items()):
        if (a, b) not in edges:
            yield Finding(
                RULE, LOCKORDER_RELPATH, line,
                f"stale lockorder entry: {a} -> {b} no longer occurs "
                f"in the tree — remove it (or --fix-lockorder)")
    for pair, line in sorted(pairs.items(), key=lambda kv: kv[1]):
        a, b = sorted(pair)
        if (a, b) not in edges and (b, a) not in edges:
            yield Finding(
                RULE, LOCKORDER_RELPATH, line,
                f"stale lockorder sanction: {a} <> {b} matches no "
                f"remaining edge — remove it")


def check(tree, relpath):  # pragma: no cover - program rule
    return iter(())
