"""FL007: thread-escape — cross-thread attribute writes need a lock.

The "added a field to the batcher, forgot the mutex" class: an
instance attribute written from two or more THREAD ROOTS of the same
class must be written with a common lock held at every write site, or
carry an explicit ``# flowlint: shared(reason)`` annotation (on the
write line, the line above, or the attribute's ``__init__``
assignment). Single-thread-confined state — attributes only ever
written from one root — needs nothing.

Thread roots of a class are its ``threading.Thread(target=self.m)``
target methods (from the shared model's thread-target table) plus one
EXTERNAL root covering every public method — the caller's thread.
Reachability is the intra-class ``self.m()`` call graph, including
bare ``self.m`` references (handed-off callbacks run where they are
called, which may be another thread). A private helper reachable from
only one root stays single-thread-confined; ``__init__`` writes are
construction-time (happens-before the thread starts) and exempt.

The "common lock" requirement is the real invariant: holding *some*
lock at each site individually is not enough — two sites under two
different locks still race. The intersection of held-lock sets across
all write sites must be non-empty (lock identity comes from the model,
with Condition-wrapping-the-mutex aliasing, so ``with self._wake:``
counts as holding ``self._lock`` when the condition wraps it).
"""

import ast

from foundationdb_tpu.analysis.base import Finding

RULE = "FL007"
TITLE = "thread-escape"
PROGRAM = True

_EXTERNAL = "<external>"


def applies(relpath):
    return True


def _thread_target_refs(node):
    """id()s of ``self.m`` nodes passed as ``target=`` to a Thread
    construction — those do NOT run on the caller's thread (they define
    a thread root), so they must not count as caller reachability."""
    out = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and isinstance(
                sub.func, (ast.Name, ast.Attribute)):
            name = sub.func.id if isinstance(sub.func, ast.Name) \
                else sub.func.attr
            if name == "Thread":
                for kw in sub.keywords:
                    if kw.arg == "target":
                        out.add(id(kw.value))
    return out


def _method_refs(node):
    """Names of self.<m> references in a method body: calls AND bare
    references (callback handoff) — minus Thread targets, which run on
    the spawned thread, not the caller's."""
    skip = _thread_target_refs(node)
    out = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and \
                isinstance(sub.value, ast.Name) and \
                sub.value.id == "self" and id(sub) not in skip:
            out.add(sub.attr)
    return out


def _own_exprs(st):
    """Expression nodes belonging to statement ``st`` itself — nested
    statements (compound bodies) are visited separately at their own
    held level."""
    stack = [v for v in ast.iter_child_nodes(st)
             if not isinstance(v, (ast.stmt, ast.excepthandler))]
    while stack:
        n = stack.pop()
        yield n
        stack.extend(v for v in ast.iter_child_nodes(n)
                     if not isinstance(v, (ast.stmt, ast.excepthandler)))


def _method_sites(model, fm, cm, method):
    """Walk one method with the held-lock stack:

    * writes: ``(attr, line, held_lock_ids)`` for every ``self.X``
      assignment (nested defs excluded — they run elsewhere);
    * calls: ``(callee_name, held_lock_ids)`` for every intra-class
      ``self.m(...)`` call — plus bare ``self.m`` handoffs at held=∅
      (a stored callback may run anywhere), Thread targets excluded.
    """
    from foundationdb_tpu.analysis.rules.fl006_lockorder import \
        _Analyzer, _FuncInfo

    info = _FuncInfo(fm, cm, method)
    an = _Analyzer(model, info)
    writes = []
    calls = []
    thread_targets = _thread_target_refs(method)

    def targets_of(st):
        if isinstance(st, ast.Assign):
            return st.targets
        if isinstance(st, (ast.AugAssign, ast.AnnAssign)):
            return [st.target]
        return []

    def record_calls(st, held):
        nodes = list(_own_exprs(st))
        callfuncs = {id(n.func) for n in nodes
                     if isinstance(n, ast.Call)}
        for n in nodes:
            if isinstance(n, ast.Attribute) and \
                    isinstance(n.value, ast.Name) and \
                    n.value.id == "self" and \
                    id(n) not in thread_targets:
                if id(n) in callfuncs:
                    calls.append((n.attr, frozenset(held)))
                elif isinstance(n.ctx, ast.Load):
                    # bare handoff: assume it runs with nothing held
                    calls.append((n.attr, frozenset()))

    def visit(stmts, held):
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            if isinstance(st, (ast.With, ast.AsyncWith)):
                record_calls(st, held)  # context exprs, at outer held
                ids = frozenset()
                for item in st.items:
                    ids |= an.resolve(item.context_expr)
                visit(st.body, held | ids)
                continue
            record_calls(st, held)
            for tgt in targets_of(st):
                for t in ast.walk(tgt):
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        writes.append((t.attr, st.lineno,
                                       frozenset(held)))
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.stmt):
                    visit([child], held)
                elif isinstance(child, ast.excepthandler):
                    visit(child.body, held)

    visit(method.body, frozenset())
    return writes, calls


def _annotated_attrs(fm, cm):
    """Attributes blessed ``# flowlint: shared(reason)`` — the comment
    sits on (or right above) a line assigning self.X anywhere in the
    class, most naturally the __init__ declaration."""
    lines = set(fm.shared_annotations)
    if not lines:
        return set()
    out = set()
    for meth in cm.methods.values():
        for sub in ast.walk(meth):
            tgts = []
            if isinstance(sub, ast.Assign):
                tgts = sub.targets
            elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                tgts = [sub.target]
            for tgt in tgts:
                for t in ast.walk(tgt):
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self" and (
                                sub.lineno in lines or
                                sub.lineno - 1 in lines or
                                sub.lineno + 1 in lines):
                        out.add(t.attr)
    return out


def check_model(model):
    for fm in model.files.values():
        if fm.tree is None:
            continue
        for cm in fm.classes.values():
            if not cm.thread_targets:
                continue
            yield from _check_class(model, fm, cm)


def _check_class(model, fm, cm):
    methods = {}
    for c in model.class_and_bases(cm):
        for name, node in c.methods.items():
            methods.setdefault(name, node)
    refs = {name: _method_refs(node) & set(methods)
            for name, node in methods.items()}

    # roots: each thread target, plus EXTERNAL for public methods
    roots = {}  # method name -> root label
    for target, tname in sorted(cm.thread_targets.items()):
        if target in methods:
            roots[target] = f"thread:{tname or target}"
    reach = {}  # method name -> set of root labels

    def flood(start, label):
        frontier = [start]
        while frontier:
            m = frontier.pop()
            if label in reach.setdefault(m, set()):
                continue
            reach[m].add(label)
            for callee in refs.get(m, ()):
                frontier.append(callee)

    for target, label in roots.items():
        flood(target, label)
    for name in methods:
        if not name.startswith("_") and name not in roots:
            flood(name, _EXTERNAL)

    annotated = _annotated_attrs(fm, cm)

    sites_by_method = {name: _method_sites(model, fm, cm, node)
                       for name, node in methods.items()}

    # Must-hold entry sets: a private helper only ever called with a
    # lock held analyzes as holding it at entry (greatest fixpoint —
    # entry(m) = ⋂ over call sites of (site_held ∪ entry(caller));
    # roots and public methods enter with nothing held). None is TOP.
    call_sites = {}  # callee -> [(caller, held)]
    for caller, (_, calls) in sites_by_method.items():
        for callee, held in calls:
            if callee in methods:
                call_sites.setdefault(callee, []).append((caller, held))
    entry = {}
    entry_roots = set(roots) | {
        m for m in methods if not m.startswith("_")}
    for name in methods:
        entry[name] = frozenset() if name in entry_roots else None
    changed = True
    while changed:
        changed = False
        for name in methods:
            if name in entry_roots:
                continue
            new = None
            for caller, held in call_sites.get(name, ()):
                e = entry.get(caller)
                eff = None if e is None else (held | e)
                if eff is not None:
                    new = eff if new is None else (new & eff)
            if new != entry[name] and new is not None:
                entry[name] = new
                changed = True

    writes = {}  # attr -> [(root_labels, line, held, method)]
    for name, node in methods.items():
        if name == "__init__":
            continue
        labels = reach.get(name, set())
        if not labels:
            continue
        at_entry = entry.get(name) or frozenset()
        for attr, line, held in sites_by_method[name][0]:
            writes.setdefault(attr, []).append(
                (labels, line, held | at_entry, name))

    for attr in sorted(writes):
        if attr in annotated or attr in cm.lock_attrs:
            continue
        sites = writes[attr]
        all_roots = set()
        for labels, _, _, _ in sites:
            all_roots |= labels
        if len(all_roots) < 2:
            continue
        common = None
        for _, _, held, _ in sites:
            common = held if common is None else (common & held)
        if common:
            continue
        # anchor at the first unlocked site if any, else first site
        unlocked = [s for s in sites if not s[2]]
        anchor = min(unlocked or sites, key=lambda s: s[1])
        rootlist = ", ".join(sorted(all_roots))
        yield Finding(
            RULE, fm.relpath, anchor[1],
            f"attribute '{attr}' of {cm.name} is written from "
            f"{len(all_roots)} thread roots ({rootlist}) with no "
            f"common lock held at every write site — guard every "
            f"write with one lock, or annotate the write with "
            f"'# flowlint: shared(reason)'")


def check(tree, relpath):  # pragma: no cover - program rule
    return iter(())
