"""FL010: retry/backoff discipline — FDBError retry loops must decide,
back off through the seam, and never blind-resubmit 1021.

Ref rationale: the reference's retry protocol is ONE function —
``Transaction::onError`` — and everything about it is deliberate: it
consults the error predicate (retryable? maybe-committed?), it backs
off through the client's jittered schedule, and ``commit_unknown_
result`` (1021) is only safe to resubmit because idempotency ids let
the proxy dedupe the second apply. A hand-rolled Python retry loop can
silently drop all three properties; this rule checks them on the
shared ProgramModel.

A *retry loop* is a ``while`` loop (or ``for ... in range(...)``
attempt loop) containing an ``except FDBError`` handler — alone or in
a tuple — that can reach the next iteration (some path falls through
or ``continue``s). Loops over collections (``for fut in pending:``)
are per-item dispatch, not retries of one operation, and are exempt.
Three checks per retry handler:

* **Decide retryability.** The handler must consult
  ``.is_retryable``/``.is_maybe_committed``, compare ``.code``, or
  route through ``on_error`` (the sanctioned gate). A handler that
  instead PROPAGATES the exception object (``out[i] = e``,
  ``fut.set_exception(e)``) is exempt — the error isn't swallowed,
  it's delivered.
* **1021 is not a plain resubmit.** If the loop commits (a
  ``commit``/``commit_batch`` call) and the handler can loop again,
  the handler must treat ``commit_unknown_result`` explicitly (a 1021
  / ``is_maybe_committed`` branch), use ``on_error``, or have an
  idempotency id in scope in the enclosing function — otherwise a
  maybe-committed transaction is resubmitted blind: the silent
  double-apply the reference's IdempotencyId machinery exists to
  prevent.
* **Back off through the seam — inter-procedurally.** PR 15's FL001
  flags a loop that grows a delay multiplicatively and
  ``time.sleep``-s it in the SAME function. This rule promotes the
  heuristic across calls, rooted at the loop (thread entries and all
  other functions alike): a retry loop that grows a delay and passes
  it to a tree callee that sleeps it — or calls a helper that grows
  and sleeps its own delay parameter — is the same hand-rolled
  backoff, split across a call boundary. Route it through
  ``utils.backoff.Backoff`` (jittered off the seeded
  ``"backoff-jitter"`` stream; resets on success).

``analysis/`` is exempt (it reasons about errors, it never retries
them); ``utils/backoff.py`` is the seam itself.
"""

import ast

from foundationdb_tpu.analysis.base import Finding, dotted_name
from foundationdb_tpu.analysis.rules.fl001_determinism import (
    _dotted_refs,
    _grown_delay_names,
)

RULE = "FL010"
TITLE = "retry discipline: decide, back off through the seam, guard 1021"
PROGRAM = True

EXEMPT_DIRS = ("analysis/",)
EXEMPT_FILES = frozenset({"utils/backoff.py"})

COMMIT_CALLS = frozenset({"commit", "commit_batch"})


def applies(relpath):
    return True


def _exempt(relpath):
    return relpath.startswith(EXEMPT_DIRS) or relpath in EXEMPT_FILES


def _catches_fdberror(handler):
    if handler.type is None:
        return False  # FL005's territory
    types = handler.type.elts if isinstance(handler.type, ast.Tuple) \
        else [handler.type]
    for t in types:
        name = dotted_name(t)
        if name is not None and name.rsplit(".", 1)[-1] == "FDBError":
            return True
    return False


def _is_retry_loop(loop):
    if isinstance(loop, ast.While):
        return True
    if isinstance(loop, ast.For) and isinstance(loop.iter, ast.Call):
        fn = loop.iter.func
        return isinstance(fn, ast.Name) and fn.id == "range"
    return False


def _outcome(stmts):
    """(may_fall_through, may_continue) for a statement sequence —
    whether control can run off the end, and whether a ``continue``
    (to the enclosing loop) is reachable. Conservative: try/loop
    bodies are assumed able to fall through."""
    may_continue = False
    for st in stmts:
        if isinstance(st, ast.Continue):
            return False, True
        if isinstance(st, (ast.Break, ast.Return, ast.Raise)):
            return False, may_continue
        if isinstance(st, ast.If):
            f1, c1 = _outcome(st.body)
            f2, c2 = _outcome(st.orelse)
            may_continue = may_continue or c1 or c2
            if not (f1 or f2):
                return False, may_continue
    return True, may_continue


def _can_reach_next_iteration(handler):
    fall, cont = _outcome(handler.body)
    return fall or cont


def _walk_no_defs(node):
    """Walk a statement body, not descending into nested defs."""
    stack = list(node) if isinstance(node, list) else [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            stack.append(child)


def _handler_parents(handler):
    parents = {}
    for st in handler.body:
        for n in ast.walk(st):
            for child in ast.iter_child_nodes(n):
                parents[child] = n
    return parents


def _discriminates(handler):
    """The handler decides retryability: predicate properties, a .code
    comparison, a maybe-committed membership test, or on_error."""
    for n in _walk_no_defs(handler.body):
        if isinstance(n, ast.Attribute) and n.attr in (
                "is_retryable", "is_maybe_committed"):
            return True
        if isinstance(n, ast.Compare):
            for sub in ast.walk(n):
                if isinstance(sub, ast.Attribute) and sub.attr == "code":
                    return True
                if isinstance(sub, ast.Name) and sub.id in (
                        "RETRYABLE", "MAYBE_COMMITTED"):
                    return True
        if isinstance(n, ast.Call):
            fn = n.func
            t = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None)
            if t == "on_error":
                return True
    return False


def _propagates(handler):
    """The bound exception object escapes as a VALUE (stored, passed,
    returned) rather than being interrogated — delivery, not a
    swallow. Attribute reads (e.code) don't count."""
    if handler.name is None:
        return False
    parents = _handler_parents(handler)
    for n in _walk_no_defs(handler.body):
        if isinstance(n, ast.Name) and n.id == handler.name and \
                isinstance(n.ctx, ast.Load):
            p = parents.get(n)
            if not isinstance(p, ast.Attribute):
                return True
    return False


def _mentions_1021(handler):
    for n in _walk_no_defs(handler.body):
        if isinstance(n, ast.Constant) and n.value == 1021:
            return True
        if isinstance(n, ast.Constant) and \
                n.value == "commit_unknown_result":
            return True
        if isinstance(n, ast.Attribute) and \
                n.attr == "is_maybe_committed":
            return True
        if isinstance(n, ast.Name) and n.id == "MAYBE_COMMITTED":
            return True
        if isinstance(n, ast.Call):
            fn = n.func
            t = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None)
            if t == "on_error":
                return True
    return False


def _loop_commits(loop):
    for n in _walk_no_defs(loop.body):
        if isinstance(n, ast.Call):
            fn = n.func
            t = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None)
            if t in COMMIT_CALLS:
                return True
    return False


def _idempotency_in_scope(func):
    """Any idempotency token in the enclosing function: an attribute /
    name / option call / string mentioning it is the author recording
    that resubmits dedupe server-side."""
    for n in ast.walk(func):
        if isinstance(n, ast.Attribute) and "idempoten" in n.attr:
            return True
        if isinstance(n, ast.Name) and "idempoten" in n.id:
            return True
        if isinstance(n, ast.Constant) and isinstance(n.value, str) \
                and "idempoten" in n.value:
            return True
    return False


# ── inter-procedural backoff summaries ──
class _FnSummary:
    __slots__ = ("params", "sleep_params", "grown")

    def __init__(self, node):
        a = node.args
        self.params = [p.arg for p in
                       a.posonlyargs + a.args + a.kwonlyargs]
        self.grown = _grown_delay_names(node)
        self.sleep_params = set()
        pset = set(self.params)
        for n in _walk_no_defs(node.body):
            if isinstance(n, ast.Call) and n.args and \
                    dotted_name(n.func) == "time.sleep":
                self.sleep_params |= _dotted_refs(n.args[0]) & pset


def _iter_functions(model):
    for fm in model.files.values():
        if fm.tree is None or _exempt(fm.relpath):
            continue
        for cm in fm.classes.values():
            for node in cm.methods.values():
                yield fm, cm, node
        for node in fm.module_funcs.values():
            yield fm, None, node


def _resolve_call(model, fm, cm, call):
    """(label, funcnode) for a call resolvable to ONE tree function:
    bare same-file / globally-unique names, self.m through the class,
    mod.f through an import binding. Ambiguity resolves nowhere."""
    fn = call.func
    if isinstance(fn, ast.Name):
        if fn.id in fm.module_funcs:
            return fn.id, fm.module_funcs[fn.id]
        hits = model.func_index.get(fn.id, [])
        if len(hits) == 1:
            return fn.id, hits[0][1]
        return None
    if not isinstance(fn, ast.Attribute):
        return None
    base = fn.value
    if isinstance(base, ast.Name) and base.id == "self" and \
            cm is not None:
        hit = model.lookup_method(cm, fn.attr)
        if hit is not None:
            return f"self.{fn.attr}", hit[1]
        return None
    if isinstance(base, ast.Name) and base.id in fm.import_files:
        rp = fm.import_files[base.id]
        f2 = model.files.get(rp) if rp else None
        if f2 is not None and fn.attr in f2.module_funcs:
            return f"{base.id}.{fn.attr}", f2.module_funcs[fn.attr]
    return None


def _map_args(call, summary, is_method):
    """[(param_name, arg_expr)] pairing this call's arguments with the
    callee's parameters."""
    params = summary.params[1:] if is_method and summary.params else \
        summary.params
    out = []
    for i, arg in enumerate(call.args):
        if isinstance(arg, ast.Starred):
            break
        if i < len(params):
            out.append((params[i], arg))
    for kw in call.keywords:
        if kw.arg is not None and kw.arg in summary.params:
            out.append((kw.arg, kw.value))
    return out


def check_model(model):
    summaries = {}
    for fm, cm, node in _iter_functions(model):
        summaries[node] = _FnSummary(node)

    for fm, cm, func in _iter_functions(model):
        relpath = fm.relpath
        # handlers belong to their nearest enclosing loop, lexically,
        # within this function (nested defs iterate on their own)
        for loop in _walk_no_defs(func.body):
            if not isinstance(loop, (ast.While, ast.For)) or \
                    not _is_retry_loop(loop):
                continue
            handlers = [
                n for n in _walk_no_defs(loop.body)
                if isinstance(n, ast.ExceptHandler)
                and _catches_fdberror(n)
            ]
            retrying = [h for h in handlers
                        if _can_reach_next_iteration(h)]
            if not retrying:
                continue
            for h in retrying:
                if not _discriminates(h) and not _propagates(h):
                    yield Finding(
                        RULE, relpath, h.lineno,
                        "FDBError retry loop swallows the error "
                        "without deciding retryability — consult "
                        "e.is_retryable / compare e.code (or route "
                        "through Transaction.on_error); a "
                        "non-retryable code looping here spins "
                        "forever")
                if _loop_commits(loop) and not _mentions_1021(h) and \
                        not _propagates(h) and \
                        not _idempotency_in_scope(func):
                    yield Finding(
                        RULE, relpath, h.lineno,
                        "commit retry loop resubmits on "
                        "commit_unknown_result (1021) with no "
                        "idempotency id in scope — a maybe-committed "
                        "transaction applied twice is silent data "
                        "corruption; branch on e.code == 1021 / "
                        "e.is_maybe_committed, use on_error, or set "
                        "an idempotency id")
            # inter-procedural manual backoff: delay grown here, slept
            # in a callee (or grown AND slept by the callee)
            grown = _grown_delay_names(loop)
            for n in _walk_no_defs(loop.body):
                if not isinstance(n, ast.Call):
                    continue
                hit = _resolve_call(model, fm, cm, n)
                if hit is None:
                    continue
                label, callee = hit
                summary = summaries.get(callee)
                if summary is None or not summary.sleep_params:
                    continue
                is_method = label.startswith("self.")
                for param, argexpr in _map_args(n, summary, is_method):
                    if param not in summary.sleep_params:
                        continue
                    if _dotted_refs(argexpr) & grown:
                        yield Finding(
                            RULE, relpath, n.lineno,
                            f"manual backoff across a call: the retry "
                            f"delay grown in this loop is slept by "
                            f"'{label}' — route it through "
                            f"utils.backoff.Backoff (jittered off the "
                            f"seeded 'backoff-jitter' stream)")
                        break
                    if param in summary.grown:
                        yield Finding(
                            RULE, relpath, n.lineno,
                            f"manual backoff across a call: '{label}' "
                            f"grows and sleeps its delay parameter "
                            f"'{param}' for this retry loop — route "
                            f"it through utils.backoff.Backoff")
                        break


def check(tree, relpath):  # pragma: no cover - program rule
    return iter(())
