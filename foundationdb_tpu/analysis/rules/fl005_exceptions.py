"""FL005 — exception hygiene: drain/transport loops must not eat
errors silently.

Ref rationale: FoundationDB's long-lived server actors wrap their loops
in handlers that TraceEvent(SevError) and re-throw or degrade loudly
(see the ``loop choose`` + ``TraceEvent(SevError, ...)`` pattern across
fdbserver/*.actor.cpp); the trace files ARE the forensics when a role
misbehaves. A Python ``except Exception: pass`` inside a batcher drain
loop or an RPC serve loop converts a recurring failure into silence —
the process looks alive while every request quietly dies.

The rule (modules under ``server/`` and ``rpc/``): a blanket handler —
bare ``except:``, ``except Exception``, or ``except BaseException``
(alone or in a tuple) — that sits lexically inside a ``for``/``while``
loop must either re-raise or emit an error-severity ``TraceEvent``
(``severity=SEV_ERROR`` / ``severity>=40`` / the fluent ``.error(exc)``
form). Typed handlers (``except ConnectionLost:``) are exempt: naming
the exception is the author proving they expected it.
"""

import ast

from foundationdb_tpu.analysis.base import (
    Finding,
    ancestors,
    build_parents,
    constant_ge,
    terminal_name,
)

RULE = "FL005"
TITLE = "exception hygiene: loops must re-raise or SEV_ERROR-trace"

SCOPES = ("server/", "rpc/")
BLANKET = {"Exception", "BaseException"}
SEV_ERROR = 40


def applies(relpath):
    return relpath.startswith(SCOPES)


def _is_blanket(handler):
    if handler.type is None:
        return True
    types = handler.type.elts if isinstance(
        handler.type, ast.Tuple
    ) else [handler.type]
    return any(
        isinstance(t, ast.Name) and t.id in BLANKET for t in types
    )


def _sev_error_trace(body):
    """An error-severity TraceEvent (or fluent .error(...)) in body."""
    for node in (n for s in body for n in ast.walk(s)):
        if not isinstance(node, ast.Call):
            continue
        t = terminal_name(node.func)
        if t == "error" and isinstance(node.func, ast.Attribute):
            return True  # TraceEvent(...).error(exc) escalates to 40
        if t != "TraceEvent":
            continue
        for kw in node.keywords:
            if kw.arg != "severity":
                continue
            v = kw.value
            if constant_ge(v, SEV_ERROR):
                return True
            if isinstance(v, ast.Name) and v.id == "SEV_ERROR":
                return True
            if isinstance(v, ast.Attribute) and v.attr == "SEV_ERROR":
                return True
    return False


def _reraises(body):
    return any(
        isinstance(n, ast.Raise)
        for s in body for n in ast.walk(s)
    )


def check(tree, relpath):
    parents = build_parents(tree)
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler) or not _is_blanket(
            node
        ):
            continue
        in_loop = False
        for anc in ancestors(node, parents):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break  # lexical scope ends at the enclosing function
            if isinstance(anc, (ast.For, ast.While)):
                in_loop = True
                break
        if not in_loop:
            continue
        if _reraises(node.body) or _sev_error_trace(node.body):
            continue
        label = "bare except" if node.type is None else \
            f"except {ast.unparse(node.type)}"
        yield Finding(
            RULE, relpath, node.lineno,
            f"blanket `{label}` inside a loop swallows errors — "
            "re-raise or emit TraceEvent(severity=SEV_ERROR) with the "
            "exception type",
        )
