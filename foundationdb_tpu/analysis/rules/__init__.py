"""flowlint rule registry — one module per rule id.

FL001–FL005 are per-file rules (``check(tree, relpath)``);
FL006–FL011 are program-wide (``PROGRAM = True`` +
``check_model(model)``) and read the shared
:class:`~foundationdb_tpu.analysis.model.ProgramModel`.
"""

from foundationdb_tpu.analysis.rules import (
    fl001_determinism,
    fl002_settlement,
    fl003_locks,
    fl004_jit,
    fl005_exceptions,
    fl006_lockorder,
    fl007_threadescape,
    fl008_protocol,
    fl009_errortaxonomy,
    fl010_retrydiscipline,
    fl011_faultsites,
)

ALL_RULES = [
    fl001_determinism,
    fl002_settlement,
    fl003_locks,
    fl004_jit,
    fl005_exceptions,
    fl006_lockorder,
    fl007_threadescape,
    fl008_protocol,
    fl009_errortaxonomy,
    fl010_retrydiscipline,
    fl011_faultsites,
]

BY_ID = {rule.RULE: rule for rule in ALL_RULES}
