"""flowlint rule registry — one module per rule id."""

from foundationdb_tpu.analysis.rules import (
    fl001_determinism,
    fl002_settlement,
    fl003_locks,
    fl004_jit,
    fl005_exceptions,
)

ALL_RULES = [
    fl001_determinism,
    fl002_settlement,
    fl003_locks,
    fl004_jit,
    fl005_exceptions,
]

BY_ID = {rule.RULE: rule for rule in ALL_RULES}
