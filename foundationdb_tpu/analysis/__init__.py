"""flowlint — AST-based invariant checking for the package's own code.

FoundationDB's reliability rests on two static pillars this Python port
otherwise lacks: the actor compiler's compile-time enforcement of
concurrency discipline and the simulator's guarantee that a seed
replays byte-identically. ``flowlint`` recovers both as a lint pass
over the package's AST (stdlib ``ast``, no dependencies): determinism
seams (FL001), future settlement (FL002), lock discipline (FL003), jit
purity (FL004), and exception hygiene (FL005).

Run it: ``python -m foundationdb_tpu.analysis.flowlint`` (see
``analysis/README.md`` for the rule catalog and baseline workflow).
"""
