"""Shared whole-program model for flowlint v2.

flowlint's first five rules are per-function: each walks one file's
AST and never looks across a call. The tree has since grown eight
thread entry points and a v7 wire protocol, and the rules that police
them (FL006 lock-order, FL007 thread-escape, FL008 protocol/knob
drift) are inherently *cross-module*: a lock-order cycle is two
acquisition sites in two files, a thread-escape is a write site plus a
``threading.Thread(target=...)`` site somewhere else entirely.

This module parses the scanned tree ONCE into a :class:`ProgramModel`
— per-file ASTs, comment tables (via ``tokenize``, so a suppression
pattern quoted inside a docstring is not a suppression), class/method
indexes, lock-attribute declarations with Condition aliasing, and the
thread-root table — and every rule (old per-file and new program-wide)
reads from it. The engine builds one model per ``lint_paths`` run;
``lint_source`` builds a one-file model so fixtures keep working.

Lock identity is CLASS-based, like the kernel's lockdep: every
``self._lock = threading.Lock()`` declares the lock id
``"ClassName._lock"`` (or the string literal when constructed through
``utils.lockdep`` — ``lockdep.lock("ClassName._lock")`` — so the
static graph and the runtime witness agree on names by construction).
``threading.Condition(self._lock)`` ALIASES the wrapped lock: waiting
on a condition carved from the mutex is one lock, not two.
"""

import ast
import io
import re
import tokenize

from foundationdb_tpu.analysis.base import dotted_name

_SUPPRESS_RE = re.compile(r"#\s*flowlint:\s*disable=([A-Z0-9,\s]+)")
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*flowlint:\s*disable-file=([A-Z0-9,\s]+)"
)
_SHARED_RE = re.compile(r"#\s*flowlint:\s*shared\(([^)]*)\)")

# threading constructors (id derived from the attribute) and the
# lockdep factories (id taken from the name literal — static and
# runtime agree by construction)
_THREADING_CTORS = {"Lock": "lock", "RLock": "rlock",
                    "Condition": "condition"}
_LOCKDEP_CTORS = {"lock": "lock", "rlock": "rlock",
                  "condition": "condition"}


def parse_rule_list(text):
    return {r.strip() for r in text.replace(",", " ").split() if r.strip()}


def _comment_table(text):
    """[(lineno, comment_text)] for every REAL comment token — a
    ``# flowlint:`` pattern inside a docstring or string literal is
    documentation, not a directive."""
    out = []
    try:
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type == tokenize.COMMENT:
                out.append((tok.start[0], tok.string))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # tokenizer choked (the AST may still parse): degrade to the
        # historical line scan rather than dropping suppressions
        for i, line in enumerate(text.splitlines(), 1):
            if "#" in line:
                out.append((i, line[line.index("#"):]))
    return out


def _lock_ctor(node):
    """If ``node`` is a Call constructing a lock/condition, return
    ``(kind, name_literal_or_None, wrapped_expr_or_None)``; else None.

    Recognizes ``threading.Lock/RLock/Condition`` (bare imports too)
    and the ``lockdep.lock/rlock/condition`` factories.
    """
    if not isinstance(node, ast.Call):
        return None
    fn = dotted_name(node.func)
    if fn is None:
        return None
    terminal = fn.rsplit(".", 1)[-1]
    kind = None
    name = None
    wrapped = None
    if terminal in _THREADING_CTORS:
        kind = _THREADING_CTORS[terminal]
        if kind == "condition":
            if node.args:
                wrapped = node.args[0]
            for kw in node.keywords:
                if kw.arg == "lock":
                    wrapped = kw.value
    elif terminal in _LOCKDEP_CTORS and "lockdep" in fn.split("."):
        kind = _LOCKDEP_CTORS[terminal]
        args = list(node.args)
        if args and isinstance(args[0], ast.Constant) and \
                isinstance(args[0].value, str):
            name = args[0].value
        if kind == "condition" and len(args) > 1:
            wrapped = args[1]
        for kw in node.keywords:
            if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                name = kw.value.value
            elif kw.arg == "lock":
                wrapped = kw.value
    else:
        return None
    return kind, name, wrapped


class ClassModel:
    """One class: methods, declared lock attributes (with Condition
    aliasing), field types from ``self.f = KnownClass(...)``, and
    thread targets (``threading.Thread(target=self.m)`` sites)."""

    __slots__ = ("name", "relpath", "node", "base_names", "methods",
                 "lock_attrs", "lock_kinds", "field_types",
                 "thread_targets")

    def __init__(self, name, relpath, node):
        self.name = name
        self.relpath = relpath
        self.node = node
        self.base_names = [dotted_name(b) for b in node.bases]
        self.methods = {}
        self.lock_attrs = {}     # attr -> lock id
        self.lock_kinds = {}     # lock id -> "lock"|"rlock"|"condition"
        self.field_types = {}    # attr -> class name
        self.thread_targets = {}  # method name -> thread name literal

    def _scan(self, known_classes):
        for item in self.node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[item.name] = item
        for meth in self.methods.values():
            local_locks = {}
            for sub in ast.walk(meth):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    tgt = sub.targets[0]
                    ctor = _lock_ctor(sub.value)
                    if ctor is not None:
                        kind, literal, wrapped = ctor
                        lock_id = literal
                        if lock_id is None and wrapped is not None:
                            lock_id = self._resolve_wrapped(
                                wrapped, local_locks)
                        if isinstance(tgt, ast.Attribute) and \
                                isinstance(tgt.value, ast.Name) and \
                                tgt.value.id == "self":
                            if lock_id is None:
                                lock_id = f"{self.name}.{tgt.attr}"
                            self.lock_attrs[tgt.attr] = lock_id
                            self.lock_kinds.setdefault(lock_id, kind)
                        elif isinstance(tgt, ast.Name):
                            if lock_id is None:
                                lock_id = (f"{self.name}.{meth.name}"
                                           f".{tgt.id}")
                            local_locks[tgt.id] = lock_id
                        continue
                    # field types: self.f = KnownClass(...)
                    if isinstance(tgt, ast.Attribute) and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == "self" and \
                            isinstance(sub.value, ast.Call):
                        fn = dotted_name(sub.value.func)
                        if fn is not None:
                            term = fn.rsplit(".", 1)[-1]
                            if term in known_classes:
                                self.field_types[tgt.attr] = term
                            elif term[:1].isupper():
                                # constructed from a class OUTSIDE the
                                # tree (threading.Thread, Event, ...):
                                # mark external so name-based method
                                # lookup never guesses at its methods
                                self.field_types.setdefault(
                                    tgt.attr, None)
                elif isinstance(sub, ast.Call):
                    fn = dotted_name(sub.func)
                    if fn is not None and \
                            fn.rsplit(".", 1)[-1] == "Thread":
                        target = None
                        tname = None
                        for kw in sub.keywords:
                            if kw.arg == "target":
                                target = kw.value
                            elif kw.arg == "name" and \
                                    isinstance(kw.value, ast.Constant):
                                tname = kw.value.value
                        if isinstance(target, ast.Attribute) and \
                                isinstance(target.value, ast.Name) and \
                                target.value.id == "self":
                            self.thread_targets.setdefault(
                                target.attr, tname)

    def _resolve_wrapped(self, expr, local_locks):
        """Condition(<expr>) aliasing: the condition IS the wrapped
        lock for ordering purposes."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self":
            return self.lock_attrs.get(expr.attr)
        if isinstance(expr, ast.Name):
            return local_locks.get(expr.id)
        return None


class FileModel:
    """One parsed file: AST, comments, suppression tables, classes,
    module functions, module-level locks."""

    __slots__ = ("relpath", "text", "tree", "syntax_error", "comments",
                 "file_disabled", "line_disabled", "shared_annotations",
                 "classes", "module_funcs", "module_locks",
                 "imports", "import_files", "_fabrication_calls")

    def __init__(self, relpath, text):
        self.relpath = relpath
        self.text = text
        self.syntax_error = None
        self._fabrication_calls = None  # FL009/FL011 shared site cache
        try:
            self.tree = ast.parse(text)
        except SyntaxError as e:
            self.tree = None
            self.syntax_error = e
        self.comments = _comment_table(text) if self.tree is not None \
            else []
        self.file_disabled = set()
        self.line_disabled = {}
        self.shared_annotations = {}   # line -> reason
        for line, comment in self.comments:
            m = _SUPPRESS_FILE_RE.search(comment)
            if m:
                self.file_disabled |= parse_rule_list(m.group(1))
                continue
            m = _SUPPRESS_RE.search(comment)
            if m:
                self.line_disabled.setdefault(line, set()).update(
                    parse_rule_list(m.group(1)))
            m = _SHARED_RE.search(comment)
            if m:
                self.shared_annotations[line] = m.group(1).strip()
        self.classes = {}
        self.module_funcs = {}
        self.module_locks = {}
        self.imports = {}       # bound name -> dotted module path
        self.import_files = {}  # bound name -> relpath or None=external
        if self.tree is None:
            return
        for sub in ast.walk(self.tree):
            # lazy function-local imports included: a bound module name
            # is a module name wherever the binding happens
            if isinstance(sub, ast.Import):
                for alias in sub.names:
                    bound = alias.asname or alias.name.split(".")[0]
                    dotted = alias.name if alias.asname \
                        else alias.name.split(".")[0]
                    self.imports.setdefault(bound, dotted)
            elif isinstance(sub, ast.ImportFrom):
                for alias in sub.names:
                    if alias.name == "*":
                        continue
                    bound = alias.asname or alias.name
                    if sub.module:
                        dotted = ("." * sub.level + sub.module
                                  + "." + alias.name)
                    else:
                        dotted = "." * sub.level + alias.name
                    self.imports.setdefault(bound, dotted)
        for item in self.tree.body:
            if isinstance(item, ast.ClassDef):
                self.classes[item.name] = ClassModel(
                    item.name, relpath, item)
            elif isinstance(item, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                self.module_funcs[item.name] = item
            elif isinstance(item, ast.Assign) and \
                    len(item.targets) == 1 and \
                    isinstance(item.targets[0], ast.Name):
                ctor = _lock_ctor(item.value)
                if ctor is not None:
                    kind, literal, _ = ctor
                    var = item.targets[0].id
                    lock_id = literal or f"{self.module_stem()}.{var}"
                    self.module_locks[var] = lock_id

    def module_stem(self):
        parts = self.relpath.replace("\\", "/").split("/")
        stem = parts[-1]
        if stem == "__init__.py" and len(parts) > 1:
            return parts[-2]
        return stem[:-3] if stem.endswith(".py") else stem


class ProgramModel:
    """The whole scanned tree, parsed once and indexed for the
    program-wide rules.

    ``full_tree`` is True when the scan covers the real package (the
    anchor files ``rpc/wire.py`` and ``core/options.py`` are both
    present): only then do the tree-contract checks run (lockorder.txt
    comparison, dead-knob sweep, version-gate test references) —
    single-file fixture lints get pure structural checks (cycles,
    unlocked cross-thread writes, unpaired encode/decode arms).
    """

    def __init__(self, items, full_tree=False, package_root=None,
                 test_texts=None):
        self.files = {}
        for relpath, text in items:
            self.files[relpath] = FileModel(relpath, text)
        self.full_tree = full_tree
        self.package_root = package_root
        self.test_texts = test_texts  # {filename: text} or None
        # indexes
        self.classes = {}       # class name -> (FileModel, ClassModel)
        self.method_index = {}  # method name -> [(fm, cm, funcnode)]
        self.func_index = {}    # module fn name -> [(fm, funcnode)]
        self.lock_attr_index = {}  # attr -> sorted set of lock ids
        known = set()
        for fm in self.files.values():
            known |= set(fm.classes)
        for fm in self.files.values():
            for cm in fm.classes.values():
                cm._scan(known)
                self.classes.setdefault(cm.name, (fm, cm))
                for mname, mnode in cm.methods.items():
                    self.method_index.setdefault(mname, []).append(
                        (fm, cm, mnode))
                for attr, lock_id in cm.lock_attrs.items():
                    self.lock_attr_index.setdefault(attr, set()).add(
                        lock_id)
            for fname, fnode in fm.module_funcs.items():
                self.func_index.setdefault(fname, []).append(
                    (fm, fnode))
        # resolve import bindings to tree files: a bound name that maps
        # to a scanned module resolves precisely; one that maps nowhere
        # is EXTERNAL (os, threading, ...) and name-based method lookup
        # must never guess at its attributes
        dotted_map = {}
        for rp in self.files:
            base = rp.replace("\\", "/")
            if base.endswith(".py"):
                base = base[:-3]
            if base.endswith("/__init__"):
                base = base[: -len("/__init__")]
            dotted_map[base.replace("/", ".")] = rp
        for fm in self.files.values():
            for bound, dotted in fm.imports.items():
                fm.import_files[bound] = self._module_for(
                    dotted, fm.relpath, dotted_map)

    @staticmethod
    def _module_for(dotted, from_relpath, dotted_map):
        """Relpath of the tree module a dotted import names, or None
        for external modules. Absolute imports match on any dotted
        suffix (the scan roots at the package dir, so the package
        prefix is not part of relpath dotted forms); relative imports
        resolve against the importing file's directory."""
        if dotted.startswith("."):
            level = len(dotted) - len(dotted.lstrip("."))
            rest = [p for p in dotted.lstrip(".").split(".") if p]
            dirparts = from_relpath.replace("\\", "/").split("/")[:-1]
            if level > 1:
                dirparts = dirparts[: len(dirparts) - (level - 1)]
            parts = dirparts + rest
            key = ".".join(parts)
            return dotted_map.get(key)
        parts = dotted.split(".")
        for i in range(len(parts)):
            key = ".".join(parts[i:])
            if key in dotted_map:
                return dotted_map[key]
        return None

    def resolve_class(self, name):
        hit = self.classes.get(name)
        return hit[1] if hit else None

    def class_and_bases(self, cm):
        """cm plus every resolvable base class (single level of the
        tree's actual use; no MRO subtleties needed)."""
        out = [cm]
        seen = {cm.name}
        frontier = list(cm.base_names)
        while frontier:
            b = frontier.pop()
            if not b:
                continue
            b = b.rsplit(".", 1)[-1]
            if b in seen:
                continue
            seen.add(b)
            base = self.resolve_class(b)
            if base is not None:
                out.append(base)
                frontier.extend(base.base_names)
        return out

    def lookup_method(self, cm, name):
        """Resolve ``self.name()`` against cm and its bases."""
        for c in self.class_and_bases(cm):
            if name in c.methods:
                return c, c.methods[name]
        return None

    def lock_attr(self, cm, attr):
        """Resolve ``self.<attr>`` as a lock against cm and bases."""
        for c in self.class_and_bases(cm):
            if attr in c.lock_attrs:
                return c.lock_attrs[attr]
        return None


def build_model(items, full_tree=False, package_root=None,
                test_texts=None):
    return ProgramModel(items, full_tree=full_tree,
                        package_root=package_root,
                        test_texts=test_texts)
