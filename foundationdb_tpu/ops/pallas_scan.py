"""Fused Pallas TPU kernel for the resolver's whole per-batch accept step.

``ops/pallas_ring.py`` moved ONE lane — the query-vs-ring overlap check —
into VMEM; everything else (the four intra-batch segment-intersection
lanes, the Jacobi acceptance loop) still runs as jit'd jnp, streaming
``[T, S, T, S]`` broadcast intermediates through HBM. This kernel fuses
the complete accept decision into a single ``pallas_call``:

1. **Ring phase** — each txn tile's point reads / range reads checked
   against the committed range-write ring (the exact lane of
   ConflictSet::detectConflicts, fdbserver/SkipList.cpp), tiled TK
   entries at a time with only the per-txn kill bit kept.
2. **Intra-batch phase** — the strict-lower conflict relation O[w, r]
   ("an accepted earlier txn w's writes hit txn r's reads": point×point
   via the fnv hash lanes, point×range / range×range via the W-limb
   lexicographic compares shared with pallas_ring), computed per
   128×128 tile pair ON THE FLY — no [T, T] matrix ever materializes.
3. **Acceptance** — greedy sequential acceptance, computed directly:
   tiles resolve in txn order, earlier tiles' final verdict bits feed
   later tiles' kill masks. The jnp path's Jacobi iteration converges to
   the greedy assignment as its unique fixpoint (induction on txn
   index), so the two paths are bit-identical — which is what the
   interpreter-mode differential tests pin.

Layout: txns are padded to ``nt = ceil(T/128)`` tiles of 128 lanes; keys
arrive ``[S, nt, W, 128]`` (slot, tile, limb, lane) so every in-kernel
load is a static-index ``[W, 128]`` block with the lane axis minor, and
all compares run in the same sign-flipped int32 space as ``pallas_ring``
(the VPU is an int32 machine). Only the ``[nt, 128]`` verdict bits leave
the kernel; the history epilogue (hash-table scatter, ring append,
coarse summaries) stays in the shared jnp code of ``resolve_batch`` —
identical on both routes by construction.

On non-TPU backends the kernel runs in interpreter mode: bit-identical,
slow, exactly what the tier-1 differential fixtures want. Lowering
failures on real hardware fall back through the resolver's
``pallas_to_jit`` taxonomy like the ring kernel's do.
"""

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from foundationdb_tpu.ops.pallas_ring import (
    LANES,
    _pad_axis,
    _pairwise_lex,
    _signed,
)

# Static trace bound: the txn-tile loops unroll at trace time, so T is
# capped at MAX_TXNS (nt <= 8 tiles). validate_params rejects larger
# configs before a kernel is ever built.
MAX_TXNS = 1024

RING_TILE = 512  # TK: ring entries per VMEM block (matches pallas_ring)


class _ScanCfg(NamedTuple):
    """Static kernel config (closed over via functools.partial)."""

    key_width: int  # W limbs per key
    nt: int  # txn tiles of 128 lanes
    nk: int  # ring tiles
    ring_tile: int  # TK entries per ring tile
    pr: int  # point-read slots per txn (>=1; dummies masked)
    pw: int  # point-write slots per txn
    rr: int  # range-read slots per txn
    rw: int  # range-write slots per txn
    pp: bool  # point-write × point-read hash lane
    p_rr: bool  # point-write × range-read lane
    rw_p: bool  # range-write × point-read lane
    rw_rr: bool  # range-write × range-read lane
    pr_ring: bool  # point reads vs the committed ring
    rr_ring: bool  # range reads vs the committed ring


def _scan_kernel(cfg, a0_ref, rv_ref, pwh_ref, prh_ref, pwk_ref, pwm_ref,
                 prk_ref, prm_ref, rrb_ref, rre_ref, rrm_ref, rwb_ref,
                 rwe_ref, rwm_ref, ringb_ref, ringe_ref, ringv_ref,
                 ringm_ref, out_ref):
    TQ, TK, W = LANES, cfg.ring_tile, cfg.key_width

    lane = jax.lax.broadcasted_iota(jnp.int32, (1, TQ), 1).reshape(TQ)
    row_i = jax.lax.broadcasted_iota(jnp.int32, (TQ, TQ), 0)
    col_i = jax.lax.broadcasted_iota(jnp.int32, (TQ, TQ), 1)

    def block(wt, rt):
        """O tile [TQ, TQ]: does write-lane i (txn tile wt) conflict
        with read-lane j (txn tile rt). Mirrors the four O |= lanes of
        resolve_batch exactly; the strict-order and acceptance gating
        happen at the call sites."""
        blk = jnp.zeros((TQ, TQ), jnp.bool_)
        if cfg.pp:
            # masks ride the sentinel hashes (masked write → 0xFFFFFFFF,
            # masked read → 0xFFFFFFFE, never equal) — same encoding as
            # the jnp lane, so hash collisions resolve identically
            for s1 in range(cfg.pw):
                wh = pwh_ref[s1, wt].reshape(TQ, 1)
                for s2 in range(cfg.pr):
                    blk |= wh == prh_ref[s2, rt].reshape(1, TQ)
        if cfg.p_rr:
            for s1 in range(cfg.pw):
                k = pwk_ref[s1, wt]
                wm = pwm_ref[s1, wt].reshape(TQ, 1) != 0
                for s2 in range(cfg.rr):
                    inr = _pairwise_lex(
                        k, rre_ref[s2, rt], W, TQ, TQ, "lt"
                    ) & ~_pairwise_lex(k, rrb_ref[s2, rt], W, TQ, TQ, "lt")
                    blk |= inr & wm & (rrm_ref[s2, rt].reshape(1, TQ) != 0)
        if cfg.rw_p:
            for s1 in range(cfg.rw):
                b, e = rwb_ref[s1, wt], rwe_ref[s1, wt]
                wm = rwm_ref[s1, wt].reshape(TQ, 1) != 0
                for s2 in range(cfg.pr):
                    k = prk_ref[s2, rt]
                    # point k in [b, e): rows are the writer lanes, so
                    # "k >= b" reads as "NOT b > k" with b on the rows
                    inr = _pairwise_lex(
                        e, k, W, TQ, TQ, "gt"
                    ) & ~_pairwise_lex(b, k, W, TQ, TQ, "gt")
                    blk |= inr & wm & (prm_ref[s2, rt].reshape(1, TQ) != 0)
        if cfg.rw_rr:
            for s1 in range(cfg.rw):
                b, e = rwb_ref[s1, wt], rwe_ref[s1, wt]
                wm = rwm_ref[s1, wt].reshape(TQ, 1) != 0
                for s2 in range(cfg.rr):
                    ov = _pairwise_lex(
                        e, rrb_ref[s2, rt], W, TQ, TQ, "gt"
                    ) & _pairwise_lex(b, rre_ref[s2, rt], W, TQ, TQ, "lt")
                    blk |= ov & wm & (rrm_ref[s2, rt].reshape(1, TQ) != 0)
        return blk

    for rt in range(cfg.nt):
        a0_row = a0_ref[rt, :] != 0

        # ── ring phase: kill txns whose reads hit a newer live ring write
        if cfg.pr_ring or cfg.rr_ring:
            rv_col = rv_ref[rt, :].reshape(TQ, 1)

            def ring_body(kt, killed, rv_col=rv_col, rt=rt):
                rb, re = ringb_ref[kt], ringe_ref[kt]
                nl = (ringv_ref[kt].reshape(1, TK) > rv_col) & (
                    ringm_ref[kt].reshape(1, TK) != 0
                )
                acc = killed
                if cfg.pr_ring:
                    for s in range(cfg.pr):
                        q = prk_ref[s, rt]
                        inr = _pairwise_lex(
                            q, re, W, TQ, TK, "lt"
                        ) & ~_pairwise_lex(q, rb, W, TQ, TK, "lt")
                        acc = acc | (
                            jnp.any(inr & nl, axis=1)
                            & (prm_ref[s, rt] != 0)
                        )
                if cfg.rr_ring:
                    for s in range(cfg.rr):
                        ov = _pairwise_lex(
                            rrb_ref[s, rt], re, W, TQ, TK, "lt"
                        ) & _pairwise_lex(rre_ref[s, rt], rb, W, TQ, TK, "gt")
                        acc = acc | (
                            jnp.any(ov & nl, axis=1)
                            & (rrm_ref[s, rt] != 0)
                        )
                return acc

            a0_row = a0_row & ~jax.lax.fori_loop(
                0, cfg.nk, ring_body, jnp.zeros((TQ,), jnp.bool_)
            )

        # ── cross-tile kills: earlier tiles' verdicts are FINAL (greedy
        # order), so their accepted bits gate their conflict rows
        killed = jnp.zeros((TQ,), jnp.bool_)
        for wt in range(rt):
            acc_w = (out_ref[wt, :] != 0).reshape(TQ, 1)
            killed = killed | jnp.any(block(wt, rt) & acc_w, axis=0)

        # ── diagonal tile: greedy sequential acceptance within the tile.
        # O is strictly upper within a tile (earlier lane kills later),
        # so each step only ever kills lanes not yet decided.
        diag = block(rt, rt) & (row_i < col_i)
        base = a0_row & ~killed

        def greedy_body(t, kd, base=base, diag=diag):
            is_t = lane == t
            a_t = jnp.any(is_t & base & ~kd)
            victims = jnp.any(diag & is_t.reshape(TQ, 1), axis=0)
            return kd | (victims & a_t)

        kd = jax.lax.fori_loop(
            0, TQ, greedy_body, jnp.zeros((TQ,), jnp.bool_)
        )
        out_ref[rt, :] = (base & ~kd).astype(jnp.int32)


def fused_accept(state, batch, params, a0, interpret=False):
    """The fused accept decision: bool[T] accepted bits.

    ``a0`` is the per-txn admissibility AFTER the jnp history lanes that
    stay outside the kernel (hash table, coarse summaries, too_old,
    txn_mask); this function folds in the exact ring check and the
    intra-batch greedy acceptance — bit-identical to resolve_batch's
    jnp ring lanes + Jacobi fixpoint. Traced code (called from inside
    resolve_batch's jit region): no host calls.
    """
    T, W = params.txns, params.key_width
    u32 = jnp.uint32
    nt = -(-T // LANES)
    KR = state.ring_v.shape[0]
    PRn = batch.pr_hash.shape[1]
    PWn = batch.pw_hash.shape[1]
    RRn = batch.rr_b.shape[1]
    RWn = batch.rw_b.shape[1]

    # lane gating mirrors resolve_batch: a side is live iff its params
    # gate AND its array width are nonzero (packers may statically
    # zero-width lanes a workload never uses)
    pp = bool(params.point_writes and params.point_reads and PWn and PRn)
    p_rr = bool(params.point_writes and params.range_reads and PWn and RRn)
    rw_p = bool(params.range_writes and params.point_reads and RWn and PRn)
    rw_rr = bool(params.range_writes and params.range_reads and RWn and RRn)
    pr_ring = bool(params.range_writes and params.point_reads and PRn and KR)
    rr_ring = bool(params.range_writes and params.range_reads and RRn and KR)

    # absent sides get ONE all-masked dummy slot so the kernel signature
    # stays fixed; their lanes are statically off above, so the dummies
    # are never even read
    if PWn:
        wh = jnp.where(batch.pw_mask, batch.pw_hash, u32(0xFFFFFFFF))
        pwk, pwm = batch.pw_key, batch.pw_mask
    else:
        wh = jnp.full((T, 1), 0xFFFFFFFF, u32)
        pwk = jnp.zeros((T, 1, W), u32)
        pwm = jnp.zeros((T, 1), bool)
    if PRn:
        rh = jnp.where(batch.pr_mask, batch.pr_hash, u32(0xFFFFFFFE))
        prk, prm = batch.pr_key, batch.pr_mask
    else:
        rh = jnp.full((T, 1), 0xFFFFFFFE, u32)
        prk = jnp.zeros((T, 1, W), u32)
        prm = jnp.zeros((T, 1), bool)
    if RRn:
        rrb, rre, rrm = batch.rr_b, batch.rr_e, batch.rr_mask
    else:
        rrb = rre = jnp.zeros((T, 1, W), u32)
        rrm = jnp.zeros((T, 1), bool)
    if RWn:
        rwb, rwe, rwm = batch.rw_b, batch.rw_e, batch.rw_mask
    else:
        rwb = rwe = jnp.zeros((T, 1, W), u32)
        rwm = jnp.zeros((T, 1), bool)

    def tile_vec(x):  # int32-valued [T] → [nt, 128]
        return _pad_axis(x.reshape(1, T), LANES, 1).reshape(nt, LANES)

    def tile_slots(x):  # int32-valued [T, S] → [S, nt, 128]
        return _pad_axis(x, LANES, 0).T.reshape(x.shape[1], nt, LANES)

    def tile_keys(k):  # uint32 [T, S, W] → signed [S, nt, W, 128]
        S = k.shape[1]
        x = _pad_axis(_signed(k), LANES, 0)  # [Tp, S, W]
        return x.transpose(1, 0, 2).reshape(S, nt, LANES, W).transpose(
            0, 1, 3, 2
        )

    # ring layout: [nk, W, TK] / [nk, TK], lanes minor — same transform
    # pallas_ring applies, plus the tile fold on the leading axis
    if (pr_ring or rr_ring) and KR:
        tk = min(RING_TILE, -(-KR // LANES) * LANES)
        rgb = _pad_axis(_signed(state.ring_b), tk, 0)  # [KRp, W]
        nk = rgb.shape[0] // tk
        ringb = rgb.reshape(nk, tk, W).transpose(0, 2, 1)
        ringe = _pad_axis(_signed(state.ring_e), tk, 0).reshape(
            nk, tk, W
        ).transpose(0, 2, 1)
        ringv = _pad_axis(
            _signed(state.ring_v).reshape(1, KR), tk, 1
        ).reshape(nk, tk)
        ringm = _pad_axis(
            state.ring_mask.astype(jnp.int32).reshape(1, KR), tk, 1
        ).reshape(nk, tk)
    else:
        tk, nk = LANES, 1
        ringb = ringe = jnp.zeros((1, W, tk), jnp.int32)
        ringv = jnp.zeros((1, tk), jnp.int32)
        ringm = jnp.zeros((1, tk), jnp.int32)

    cfg = _ScanCfg(
        key_width=W, nt=nt, nk=nk, ring_tile=tk,
        pr=prk.shape[1], pw=pwk.shape[1], rr=rrb.shape[1],
        rw=rwb.shape[1], pp=pp, p_rr=p_rr, rw_p=rw_p, rw_rr=rw_rr,
        pr_ring=pr_ring, rr_ring=rr_ring,
    )
    out = pl.pallas_call(
        functools.partial(_scan_kernel, cfg),
        out_shape=jax.ShapeDtypeStruct((nt, LANES), jnp.int32),
        interpret=interpret,
    )(
        tile_vec(a0.astype(jnp.int32)),
        tile_vec(_signed(batch.rv)),
        tile_slots(_signed(wh)),
        tile_slots(_signed(rh)),
        tile_keys(pwk),
        tile_slots(pwm.astype(jnp.int32)),
        tile_keys(prk),
        tile_slots(prm.astype(jnp.int32)),
        tile_keys(rrb),
        tile_keys(rre),
        tile_slots(rrm.astype(jnp.int32)),
        tile_keys(rwb),
        tile_keys(rwe),
        tile_slots(rwm.astype(jnp.int32)),
        ringb, ringe, ringv, ringm,
    )
    return out.reshape(nt * LANES)[:T] != 0
