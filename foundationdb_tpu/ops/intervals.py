"""Vectorized lexicographic compare and interval overlap on limb-encoded keys.

This is the TPU replacement for SkipList::find's pointer-chasing key
comparisons (ref: fdbserver/SkipList.cpp). Keys arrive as uint32 limb
vectors (core/keys.py); comparisons are data-parallel over arbitrary
leading batch dimensions, so a whole batch of conflict ranges is compared
against a whole history of write ranges in one fused XLA computation.
"""

import jax
import jax.numpy as jnp


def lex_lt(a, b):
    """Elementwise lexicographic a < b over the trailing limb axis.

    a, b: uint32[..., W] (broadcastable). Returns bool[...].

    Folded limb-by-limb (most significant first) with result-shaped
    boolean carries, NOT by materializing the broadcast [..., W] tensors:
    when a and b broadcast against each other (a whole batch of keys vs a
    whole history ring, e.g. [T, PR, 1, W] vs [1, 1, KR, W]), the naive
    formulation streams W-times-wider uint32 intermediates through
    memory. Slicing each limb BEFORE the broadcast keeps every
    intermediate at the result shape — on TPU this is the difference
    between VPU-bound and HBM-bound for the ring lanes (and ~10x on the
    CPU twin). W is static, so the python loop unrolls into one fused
    XLA computation.
    """
    lt = None
    eq = None
    for i in range(a.shape[-1]):
        ai, bi = a[..., i], b[..., i]  # broadcast happens per-limb here
        if lt is None:
            lt = ai < bi
            eq = ai == bi
        else:
            lt = lt | (eq & (ai < bi))
            eq = eq & (ai == bi)
    return lt


def lex_le(a, b):
    return ~lex_lt(b, a)


def lex_eq(a, b):
    return jnp.all(a == b, axis=-1)


def ranges_overlap(rb, re, wb, we):
    """Half-open interval overlap: [rb, re) ∩ [wb, we) != ∅.

    All operands uint32[..., W], broadcastable. Empty ranges (rb >= re)
    never overlap anything by construction.
    """
    return lex_lt(rb, we) & lex_lt(wb, re)


def conflicts_brute(rb, re, rv, wb, we, wv, wmask):
    """Exact brute-force conflict check: each read range vs every write.

    The direct dense formulation of ConflictSet::detectConflicts
    (ref: fdbserver/SkipList.cpp): read range i conflicts iff some write
    range j with commit version wv[j] > read version rv[i] overlaps it.
    Used by the exact range lane and as the test oracle's device twin.

    rb, re: uint32[Q, W]   read conflict ranges
    rv:     uint32[Q]      read-version offsets
    wb, we: uint32[K, W]   write ranges (history)
    wv:     uint32[K]      commit-version offsets
    wmask:  bool[K]        valid entries
    Returns bool[Q].
    """
    ov = ranges_overlap(rb[:, None, :], re[:, None, :], wb[None, :, :], we[None, :, :])
    newer = wv[None, :] > rv[:, None]
    return jnp.any(ov & newer & wmask[None, :], axis=1)


def point_in_ranges(pk, wb, we):
    """bool[Q, K]: is point key pk[q] inside write range [wb[k], we[k))."""
    ge = ~lex_lt(pk[:, None, :], wb[None, :, :])
    lt = lex_lt(pk[:, None, :], we[None, :, :])
    return ge & lt


def fnv_hash(limbs):
    """FNV-1a-style 32-bit hash folded over the trailing limb axis.

    uint32[..., W] -> uint32[...]. Wraparound uint32 arithmetic maps
    directly onto TPU int lanes.
    """
    h = jnp.full(limbs.shape[:-1], 2166136261, dtype=jnp.uint32)
    for i in range(limbs.shape[-1]):
        h = (h ^ limbs[..., i]) * jnp.uint32(16777619)
    # final avalanche (xorshift-multiply)
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x7FEB352D)
    h = h ^ (h >> 15)
    return h


def searchsorted_limbs(sorted_keys, queries):
    """Vectorized lower-bound binary search over limb-encoded sorted keys.

    sorted_keys: uint32[M, W] ascending (lexicographic).
    queries:     uint32[Q, W].
    Returns int32[Q]: first index i with sorted_keys[i] >= query.
    """
    m = sorted_keys.shape[0]
    q = queries.shape[0]
    lo = jnp.zeros((q,), dtype=jnp.int32)
    hi = jnp.full((q,), m, dtype=jnp.int32)
    steps = max(1, m.bit_length())

    def body(_, carry):
        lo, hi = carry
        active = lo < hi
        mid = (lo + hi) // 2
        mid_keys = sorted_keys[jnp.clip(mid, 0, m - 1)]
        go_right = lex_lt(mid_keys, queries) & active  # sorted[mid] < query
        lo = jnp.where(go_right, mid + 1, lo)
        hi = jnp.where(go_right | ~active, hi, mid)
        return lo, hi

    lo, hi = jax.lax.fori_loop(0, steps, body, (lo, hi))
    return lo
