"""Pallas TPU kernel for the resolver's range-ring conflict lanes.

The jnp path in ops/conflict.py checks a batch's reads against the ring
of recent committed range-writes by broadcasting to ``[Q, KR]`` (with a
W-limb lexicographic compare inside), which XLA streams through HBM as
wide intermediates. This kernel tiles the same computation through VMEM:
queries in ``TQ=128`` lanes × ring entries in ``TK`` blocks, the limb
compare unrolled over W with the ``[TQ, TK]`` running prefix kept
on-chip, and only the per-query hit bit leaving the kernel. Ref
semantics: the ring walk of ConflictSet::detectConflicts
(fdbserver/SkipList.cpp) — "does any write newer than my read version
intersect my read range".

Keys are limb-encoded uint32 (core/keys.py); lanes compare in
order-preserving signed space (x ^ 0x8000_0000 bitcast to int32) because
the VPU is an int32 machine. Inputs arrive ``[Q, W]`` row-major and are
transposed once to ``[W, Q]`` so the minor axis is the 128-lane axis.

On non-TPU backends the kernel runs in interpreter mode — bit-identical,
slow, which is exactly what the differential tests want.

The fused whole-batch kernel (ops/pallas_scan.py, the ``pallas_scan``
knob) imports this module's shared compare helpers — ``LANES``,
``_signed``, ``_pairwise_lex``, ``_pad_axis`` — so the two kernels
agree limb-for-limb on key ordering; when ``pallas_scan`` engages it
subsumes these ring lanes and this kernel stands down for that
resolver.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128  # TQ: queries per block (the lane axis)


def _signed(x):
    """Order-preserving uint32 → int32 (flip the sign bit, bitcast)."""
    return jax.lax.bitcast_convert_type(
        x ^ jnp.uint32(0x80000000), jnp.int32
    )


def _pairwise_lex(a_ref, b_ref, W, TQ, TK, direction):
    """[TQ, TK] lexicographic compare between every a-column and every
    b-column: direction="lt" → a < b, "gt" → a > b. Unrolled over the W
    limbs; the eq-prefix and verdict stay in VMEM registers."""
    lt = jnp.zeros((TQ, TK), jnp.bool_)
    eq = jnp.ones((TQ, TK), jnp.bool_)
    for i in range(W):
        ai = a_ref[i, :].reshape(TQ, 1)
        bi = b_ref[i, :].reshape(1, TK)
        cmp = (ai < bi) if direction == "lt" else (ai > bi)
        lt = lt | (eq & cmp)
        eq = eq & (ai == bi)
    return lt


def _ring_kernel(point_mode, W, qlo_ref, qhi_ref, rv_ref, rb_ref, re_ref,
                 rver_ref, rmask_ref, out_ref):
    TQ = out_ref.shape[1]
    TK = rver_ref.shape[1]
    k = pl.program_id(1)

    # q starts before the write ends: q/qlo < ring_e
    before_end = _pairwise_lex(qlo_ref, re_ref, W, TQ, TK, "lt")
    if point_mode:
        # point k in [rb, re): also ¬(k < rb)
        ov = before_end & ~_pairwise_lex(qlo_ref, rb_ref, W, TQ, TK, "lt")
    else:
        # [qlo, qhi) ∩ [rb, re) ≠ ∅: also qhi > rb
        ov = before_end & _pairwise_lex(qhi_ref, rb_ref, W, TQ, TK, "gt")

    newer = rver_ref[0, :].reshape(1, TK) > rv_ref[0, :].reshape(TQ, 1)
    live = rmask_ref[0, :].reshape(1, TK) != 0
    hit = jnp.any(ov & newer & live, axis=1).astype(jnp.int32)

    @pl.when(k == 0)
    def _():
        out_ref[0, :] = jnp.zeros((TQ,), jnp.int32)

    out_ref[0, :] = jnp.maximum(out_ref[0, :], hit)


def _pad_axis(x, mult, axis):
    n = x.shape[axis]
    pad = (-n) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(
    jax.jit, static_argnames=("point_mode", "interpret", "ring_tile")
)
def ring_hits(qlo, qhi, rv, ring_b, ring_e, ring_v, ring_mask,
              point_mode=False, interpret=False, ring_tile=512):
    """Per-query ring-conflict bits.

    qlo/qhi: uint32[Q, W] query begins/ends (qhi ignored in point mode);
    rv: uint32[Q] read versions; ring_b/e: uint32[KR, W]; ring_v:
    uint32[KR]; ring_mask: bool[KR]. Returns bool[Q]: query q conflicts
    with some live ring write newer than rv[q].
    """
    Q, W = qlo.shape
    KR = ring_v.shape[0]

    qlo_t = _pad_axis(_signed(qlo).T, LANES, 1)  # [W, Qp]
    qhi_t = _pad_axis(_signed(qhi).T, LANES, 1)
    # versions get the same order-preserving sign-flip as the key limbs:
    # the jnp lanes compare uint32, and offsets may legally reach 2^31
    # before a rebase (the host threshold is policy, not a contract here)
    rv_p = _pad_axis(_signed(rv).reshape(1, Q), LANES, 1)
    tk = min(ring_tile, ((KR + LANES - 1) // LANES) * LANES)
    rb_t = _pad_axis(_signed(ring_b).T, tk, 1)  # [W, KRp]
    re_t = _pad_axis(_signed(ring_e).T, tk, 1)
    rver = _pad_axis(_signed(ring_v).reshape(1, KR), tk, 1)
    rmask = _pad_axis(ring_mask.astype(jnp.int32).reshape(1, KR), tk, 1)

    qp, krp = qlo_t.shape[1], rb_t.shape[1]
    grid = (qp // LANES, krp // tk)

    q_spec = pl.BlockSpec((W, LANES), lambda i, k: (0, i))
    r_spec = pl.BlockSpec((W, tk), lambda i, k: (0, k))
    qs_spec = pl.BlockSpec((1, LANES), lambda i, k: (0, i))
    rs_spec = pl.BlockSpec((1, tk), lambda i, k: (0, k))

    out = pl.pallas_call(
        functools.partial(_ring_kernel, point_mode, W),
        grid=grid,
        in_specs=[q_spec, q_spec, qs_spec, r_spec, r_spec, rs_spec, rs_spec],
        out_specs=pl.BlockSpec((1, LANES), lambda i, k: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, qp), jnp.int32),
        interpret=interpret,
    )(qlo_t, qhi_t, rv_p, rb_t, re_t, rver, rmask)
    return out[0, :Q] > 0
