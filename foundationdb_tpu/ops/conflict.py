"""The TPU conflict-detection kernel — FDB's Resolver hot path, redesigned.

Reference semantics (fdbserver/Resolver.actor.cpp + fdbserver/SkipList.cpp,
ConflictSet::detectConflicts): a resolver keeps the last ~5s of committed
write ranges; a transaction commits iff none of its read conflict ranges
intersects a write range committed after the transaction's read version —
including writes of earlier transactions *in the same batch* that were
themselves accepted.

The reference walks a lock-free skip list per conflict range. That design
is pointer-chasing and branchy — exactly what a TPU cannot do. This kernel
replaces it with four data-parallel structures, all fixed-shape device
arrays updated in one fused jit step:

1. **Point-version hash table** ``ht[2^HB]``: max commit-version offset per
   key-hash bucket. Point writes scatter-max into it; point reads gather
   and compare. Exact for point↔point conflicts up to hash collisions,
   which only ever *add* conflicts (a spurious retry — safe, same
   direction FDB's own conservative conflict ranges lean).

2. **Range ring** of the most recent ``KR`` committed range-writes, kept
   as limb-encoded intervals and checked exactly (vectorized interval
   overlap, ops/intervals.py).

3. **Coarse interval summary** ``(range_L, range_R)[C]`` over ``C``
   order-contiguous key buckets, absorbing range-writes *evicted* from
   the ring: scatter-max of the version at the interval's begin bucket
   into L and end bucket into R. A query range [qlo,qhi] can only overlap
   a stored interval if that interval starts at or before qhi (so its
   version is ≤ prefix-max of L at qhi) *and* ends at or after qlo (≤
   suffix-max of R at qlo); ``min(prefmax_L[qhi], sufmax_R[qlo])`` is
   therefore an upper bound on the newest possibly-overlapping write —
   conservative, never a miss.

4. **Coarse point summary** ``point[C]``: per-bucket max version of all
   point writes, with a per-batch sparse table for O(1) range-max — used
   only by range reads (point reads use the exact hash table).

Intra-batch ordering — the sequential part of the reference's resolver —
becomes a **Jacobi fixpoint on the MXU**: build the strict-lower-
triangular conflict matrix O[t',t] ("t' writes intersect t's reads"),
then iterate  a ← a0 ∧ ¬(a·O)  until unchanged. The greedy sequential
acceptance is the *unique* fixpoint of that map (induction on t: position
0 is exact immediately, position t is exact once 0..t-1 are), and each
iteration is one T×T matvec, so batches with conflict chains of depth d
cost d matmuls instead of T dependent skip-list walks.

Safety argument (why conservative lanes compose): every structure is used
both to *record* accepted writes and to *check* reads, and each lane's
check provably sees every write its record admitted (hash: same bucket;
ring: exact; coarse: bucket monotonicity). Hence the accepted set is
always mutually serializable — false positives only shrink it.

Versions are uint32 offsets from a host-held base (core/versions.py);
version 0 means "no write recorded".
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from foundationdb_tpu.ops.intervals import lex_lt, ranges_overlap


class ResolverParams(NamedTuple):
    """Static shape config (hashable; passed as a jit-static arg)."""

    txns: int = 1024  # T
    point_reads: int = 4  # PR per txn
    point_writes: int = 4  # PW per txn
    range_reads: int = 2  # RR per txn
    range_writes: int = 2  # RW per txn
    key_width: int = 9  # W = limbs + 1 (length limb)
    hash_bits: int = 22  # point table size 2^HB
    ring_capacity: int = 4096  # KR
    bucket_bits: int = 14  # C = 2^bucket_bits coarse buckets
    use_pallas: bool = False  # ring lanes via the Pallas VMEM kernel
    # record point writes into the coarse per-bucket summary even when
    # this variant has no range-read lanes to read it: set ONLY on the
    # point-specialized fast-path variant (Resolver), which shares
    # history with a full kernel whose future range reads must see these
    # writes. A config that is point-only by knobs (no full twin exists)
    # keeps the old gate and records nothing nothing can read.
    record_point_coarse: bool = False
    # Bucket-partitioned ring (single-device path): 2^bits sub-rings
    # keyed by the begin-key's top coarse-bucket bits. A range write
    # contained in ONE partition records exactly in its sub-ring;
    # spanning writes fold into the coarse interval summaries
    # (conservative). A query then checks only its two end partitions'
    # sub-rings exactly (plus a per-partition version max for any
    # middle partitions) — ~2/2^bits of the flat ring's pairwise work,
    # which is what bounds range-heavy throughput on-device. 0 = flat
    # ring (the mesh-sharded path always uses the flat ring).
    ring_partition_bits: int = 0
    # the FULL accept step as one fused Pallas kernel
    # (ops/pallas_scan.py): exact ring check + all four intra-batch
    # segment-intersection lanes + greedy acceptance in VMEM, with only
    # the verdict bits leaving the kernel. Subsumes use_pallas's ring
    # lane when set (the ring check moves inside the fused kernel); the
    # jnp history epilogue is shared, so both routes update state
    # identically. Single-device flat-ring layout only, T <= 1024
    # (validate_params enforces both).
    use_pallas_scan: bool = False


class ResolverState(NamedTuple):
    """Device-resident conflict history (the MVCC window)."""

    window_start: jnp.ndarray  # uint32[] — oldest admissible read version
    ht: jnp.ndarray  # uint32[2^HB] point-write version table
    ring_b: jnp.ndarray  # uint32[KR, W] range-write begins
    ring_e: jnp.ndarray  # uint32[KR, W] range-write ends
    ring_v: jnp.ndarray  # uint32[KR] commit versions
    ring_lo: jnp.ndarray  # int32[KR] begin bucket
    ring_hi: jnp.ndarray  # int32[KR] end bucket
    ring_mask: jnp.ndarray  # bool[KR]
    ring_head: jnp.ndarray  # int32[]
    range_L: jnp.ndarray  # uint32[C] evicted range-writes: v at begin bucket
    range_R: jnp.ndarray  # uint32[C] evicted range-writes: v at end bucket
    point_coarse: jnp.ndarray  # uint32[C] point writes per bucket


class ResolveBatch(NamedTuple):
    """One commit batch, packed to static shapes (invalid slots masked)."""

    rv: jnp.ndarray  # uint32[T] read-version offsets
    txn_mask: jnp.ndarray  # bool[T]
    pr_hash: jnp.ndarray  # uint32[T, PR]
    pr_key: jnp.ndarray  # uint32[T, PR, W] limb-encoded point-read keys
    pr_bucket: jnp.ndarray  # int32[T, PR]
    pr_mask: jnp.ndarray  # bool[T, PR]
    pw_hash: jnp.ndarray  # uint32[T, PW]
    pw_key: jnp.ndarray  # uint32[T, PW, W]
    pw_bucket: jnp.ndarray  # int32[T, PW]
    pw_mask: jnp.ndarray  # bool[T, PW]
    rr_b: jnp.ndarray  # uint32[T, RR, W]
    rr_e: jnp.ndarray  # uint32[T, RR, W]
    rr_lo: jnp.ndarray  # int32[T, RR]
    rr_hi: jnp.ndarray  # int32[T, RR]
    rr_mask: jnp.ndarray  # bool[T, RR]
    rw_b: jnp.ndarray  # uint32[T, RW, W]
    rw_e: jnp.ndarray  # uint32[T, RW, W]
    rw_lo: jnp.ndarray  # int32[T, RW]
    rw_hi: jnp.ndarray  # int32[T, RW]
    rw_mask: jnp.ndarray  # bool[T, RW]
    cv: jnp.ndarray  # uint32[] commit-version offset for this batch
    new_window_start: jnp.ndarray  # uint32[]


class ShardBatch(NamedTuple):
    """One commit batch COMPACTED per key-range lane — the presharded
    single-dispatch layout (resolver/packing.py ShardRouter builds it).

    Where ``ResolveBatch`` keeps a dense ``[T, K]`` slot grid per
    conflict side, this layout pools each side into a flat slot array of
    per-lane capacity Q with an explicit owning-txn index: the host
    router sends each entry ONLY to the lane(s) whose key range it
    touches, so per-lane work shrinks as the lane count grows (the dense
    layout replicates every entry to every lane and shrinks nothing).
    Point entries go to exactly ``lane(key)``; range entries get one
    slot in EVERY lane their span overlaps, carrying the FULL unclipped
    range (the overlap checks stay exact; duplicates only re-derive the
    same verdict). ``rv``/``txn_mask``/``cv``/``new_window_start`` stay
    replicated — the verdict fold needs them on every lane.
    """

    rv: jnp.ndarray  # uint32[T] read-version offsets (replicated)
    txn_mask: jnp.ndarray  # bool[T] (replicated)
    pr_hash: jnp.ndarray  # uint32[Qpr]
    pr_key: jnp.ndarray  # uint32[Qpr, W]
    pr_bucket: jnp.ndarray  # int32[Qpr]
    pr_txn: jnp.ndarray  # int32[Qpr] owning txn slot in [0, T)
    pr_mask: jnp.ndarray  # bool[Qpr]
    pw_hash: jnp.ndarray  # uint32[Qpw]
    pw_key: jnp.ndarray  # uint32[Qpw, W]
    pw_bucket: jnp.ndarray  # int32[Qpw]
    pw_txn: jnp.ndarray  # int32[Qpw]
    pw_mask: jnp.ndarray  # bool[Qpw]
    rr_b: jnp.ndarray  # uint32[Qrr, W]
    rr_e: jnp.ndarray  # uint32[Qrr, W]
    rr_lo: jnp.ndarray  # int32[Qrr]
    rr_hi: jnp.ndarray  # int32[Qrr]
    rr_txn: jnp.ndarray  # int32[Qrr]
    rr_mask: jnp.ndarray  # bool[Qrr]
    rw_b: jnp.ndarray  # uint32[Qrw, W]
    rw_e: jnp.ndarray  # uint32[Qrw, W]
    rw_lo: jnp.ndarray  # int32[Qrw]
    rw_hi: jnp.ndarray  # int32[Qrw]
    rw_txn: jnp.ndarray  # int32[Qrw]
    rw_mask: jnp.ndarray  # bool[Qrw]
    cv: jnp.ndarray  # uint32[] commit-version offset (replicated)
    new_window_start: jnp.ndarray  # uint32[] (replicated)


from foundationdb_tpu.core.status import COMMITTED, CONFLICT, TOO_OLD  # noqa: E402


def init_state(params: ResolverParams) -> ResolverState:
    kr, c, w = params.ring_capacity, 1 << params.bucket_bits, params.key_width
    u32 = jnp.uint32
    # partitioned ring: one append cursor per sub-ring
    head_shape = (
        (1 << params.ring_partition_bits,)
        if params.ring_partition_bits else ()
    )
    return ResolverState(
        window_start=jnp.zeros((), u32),
        ht=jnp.zeros((1 << params.hash_bits,), u32),
        ring_b=jnp.zeros((kr, w), u32),
        ring_e=jnp.zeros((kr, w), u32),
        ring_v=jnp.zeros((kr,), u32),
        ring_lo=jnp.zeros((kr,), jnp.int32),
        ring_hi=jnp.zeros((kr,), jnp.int32),
        ring_mask=jnp.zeros((kr,), bool),
        ring_head=jnp.zeros(head_shape, jnp.int32),
        range_L=jnp.zeros((c,), u32),
        range_R=jnp.zeros((c,), u32),
        point_coarse=jnp.zeros((c,), u32),
    )


def _sparse_table(vals):
    """Sparse-table (doubling) range-max preprocessing over a 1-D array.

    Returns list of arrays: level l gives max over [i, i + 2^l)."""
    levels = [vals]
    n = vals.shape[0]
    span = 1
    while span < n:
        prev = levels[-1]
        shifted = jnp.concatenate([prev[span:], jnp.zeros((span,), prev.dtype)])
        levels.append(jnp.maximum(prev, shifted))
        span *= 2
    return levels


def _range_max(levels, lo, hi):
    """Max over [lo, hi] inclusive (int32 indices, lo <= hi), O(1)/query."""
    length = (hi - lo + 1).astype(jnp.float32)
    j = jnp.floor(jnp.log2(jnp.maximum(length, 1.0))).astype(jnp.int32)
    j = jnp.clip(j, 0, len(levels) - 1)
    stacked = jnp.stack(levels)  # [L, C]
    n = levels[0].shape[0]
    a = stacked[j, jnp.clip(lo, 0, n - 1)]
    b = stacked[j, jnp.clip(hi - (1 << j) + 1, 0, n - 1)]
    return jnp.maximum(a, b)


def _point_in(k, b, e):
    """bool: limb key k in [b, e). Broadcasting over leading dims."""
    return (~lex_lt(k, b)) & lex_lt(k, e)


def resolve_batch(
    state: ResolverState,
    batch: ResolveBatch,
    params: ResolverParams,
    axis_name=None,
    n_shards=1,
):
    """One resolver step: statuses for a batch + updated history. Pure/jittable.

    Ref parity: Resolver::resolveBatch + ConflictSet::detectConflicts.

    With ``axis_name`` set (under shard_map over a mesh axis), each device
    is one resolver *shard* — the TPU analog of FDB's key-range-sharded
    resolvers, but finer: the point hash table is hash-sharded, the range
    ring is begin-bucket-sharded, the small coarse summaries are
    replicated (pmax-synced), and the batch is replicated. Per-lane
    invariant: whichever shard records a write is the shard whose check
    can see it, so OR-reducing per-shard verdicts (psum) loses nothing.
    Cross-device traffic per batch: a few [T]-bool reductions + two [C]
    pmax — all ICI-friendly.
    """
    T = params.txns
    u32 = jnp.uint32
    rv = batch.rv  # [T]

    if axis_name is None:
        n_shards, shard_idx = 1, 0

        def por(x):  # OR-reduce across shards
            return x

        def pmax_arr(x):
            return x

    else:
        # axis_name may be a tuple (hybrid host×chip mesh: state shards
        # over every axis; the flattened coordinate is the shard id and
        # collectives reduce over all of them — psum/pmax take tuples
        # natively, the index/size just need the row-major fold)
        names = axis_name if isinstance(axis_name, tuple) else (axis_name,)
        shard_idx = jnp.int32(0)
        mesh_n = 1
        for nm in names:
            # lax.axis_size is the modern API; older jax answers the
            # static size via the psum(1, axis) idiom
            sz = (jax.lax.axis_size(nm)
                  if hasattr(jax.lax, "axis_size")
                  else jax.lax.psum(1, nm))
            shard_idx = shard_idx * sz + jax.lax.axis_index(nm)
            mesh_n *= sz
        if n_shards != mesh_n:
            raise ValueError(
                f"n_shards={n_shards} does not match mesh axes "
                f"{names!r} total size {mesh_n}: ownership masks would "
                "silently un-own part of the key space"
            )

        def por(x):
            return jax.lax.psum(x.astype(jnp.int32), names) > 0

        def pmax_arr(x):
            return jax.lax.pmax(x, names)

    C = 1 << params.bucket_bits

    def hash_owned(h):  # point-lane ownership: hash mod n
        return (h % u32(n_shards)).astype(jnp.int32) == shard_idx

    def bucket_owned(bucket):  # range-lane ownership: contiguous buckets
        return (bucket * n_shards) // C == shard_idx

    # ───────────────────────── history conflicts ─────────────────────────
    too_old = rv < state.window_start

    hist = jnp.zeros((T,), bool)

    # The ring + coarse interval summaries are populated ONLY by range
    # writes: with params.range_writes == 0 they are statically all-zero,
    # and checking them would stream [T, *, KR, W] broadcast intermediates
    # through HBM for nothing (this alone is ~25x on the YCSB-A point
    # workload). Gate every dead lane on the static params.
    if params.range_writes:
        pref_L = jax.lax.associative_scan(jnp.maximum, state.range_L)
        suf_R = jax.lax.associative_scan(jnp.maximum, state.range_R, reverse=True)

    # bucket-partitioned ring (single-device path only — the mesh
    # bucket-shards the ring across devices instead): sub-ring views +
    # the partition shift, shared by the check and record lanes
    PB = params.ring_partition_bits if axis_name is None else 0
    if PB and params.range_writes:
        P = 1 << PB
        KRs = params.ring_capacity // P
        pshift = params.bucket_bits - PB
        rb_p = state.ring_b.reshape(P, KRs, params.key_width)
        re_p = state.ring_e.reshape(P, KRs, params.key_width)
        rv_p = state.ring_v.reshape(P, KRs)
        rm_p = state.ring_mask.reshape(P, KRs)
        # per-partition newest version: the conservative verdict for a
        # query's MIDDLE partitions (its end partitions get exact checks)
        part_max = jnp.max(jnp.where(rm_p, rv_p, u32(0)), axis=1)

    # the Pallas kernels run the single-shard flat-ring path only (each
    # shard_map lane is its own program; the jnp lanes stay canonical
    # there; the partitioned ring has its own gather-based layout)
    # — interpret mode keeps them runnable (and differential-testable)
    # on CPU. The fused scan kernel subsumes the ring kernel: when it is
    # on, the exact ring check happens INSIDE the fused accept step and
    # the standalone ring lanes here are skipped entirely.
    pallas_scan_on = (
        params.use_pallas_scan and axis_name is None and not PB
    )
    pallas_ring_on = (
        params.use_pallas and axis_name is None and not PB
        and not pallas_scan_on
    )
    if pallas_ring_on or pallas_scan_on:
        interp = jax.default_backend() != "tpu"
    if pallas_ring_on:
        from foundationdb_tpu.ops.pallas_ring import ring_hits

    # point reads vs point-write hash table (exact lane)
    if params.point_reads:
        own_pr = hash_owned(batch.pr_hash)
        ht_v = state.ht[batch.pr_hash & u32((1 << params.hash_bits) - 1)]  # [T, PR]
        hit = (ht_v > rv[:, None]) & batch.pr_mask & own_pr
        if params.range_writes:
            # point reads vs recent range-writes (exact ring)
            # lane counts come from the arrays: packers may statically
            # zero-width lanes a workload never uses
            PR = batch.pr_key.shape[1]
            if pallas_scan_on:
                # exact ring lane fused into the accept kernel below
                ring_hit = None
            elif pallas_ring_on and PR:
                flat_k = batch.pr_key.reshape(T * PR, params.key_width)
                rv_q = jnp.broadcast_to(rv[:, None], (T, PR)).reshape(-1)
                ring_hit = ring_hits(
                    flat_k, flat_k, rv_q, state.ring_b, state.ring_e,
                    state.ring_v, state.ring_mask,
                    point_mode=True, interpret=interp,
                ).reshape(T, PR)
            elif PB:
                # a point's partition is its bucket's partition; any
                # single-partition entry containing it lives exactly
                # there (spanning entries are in the coarse summaries)
                pq = jnp.clip(batch.pr_bucket >> pshift, 0, P - 1)
                in_rng = _point_in(
                    batch.pr_key[:, :, None, :], rb_p[pq], re_p[pq]
                )  # [T, PR, KRs]
                newer = (rv_p[pq] > rv[:, None, None]) & rm_p[pq]
                ring_hit = jnp.any(in_rng & newer, axis=2)
            else:
                in_rng = _point_in(
                    batch.pr_key[:, :, None, :], state.ring_b[None, None], state.ring_e[None, None]
                )  # [T, PR, KR]
                newer = (state.ring_v[None, None] > rv[:, None, None]) & state.ring_mask[None, None]
                ring_hit = jnp.any(in_rng & newer, axis=2)
            if ring_hit is not None:
                hit |= ring_hit & batch.pr_mask
            # point reads vs evicted range-writes (coarse interval summary)
            coarse = jnp.minimum(pref_L[batch.pr_bucket], suf_R[batch.pr_bucket])
            hit |= (coarse > rv[:, None]) & batch.pr_mask
        hist |= jnp.any(hit, axis=1)

    # range reads vs ring (exact), coarse ranges, and coarse points
    if params.range_reads:
        hit = jnp.zeros((T, params.range_reads), bool)
        if params.range_writes:
            RR = batch.rr_b.shape[1]
            if pallas_scan_on:
                # exact ring lane fused into the accept kernel below
                ring_hit = None
            elif pallas_ring_on and RR:
                rv_q = jnp.broadcast_to(rv[:, None], (T, RR)).reshape(-1)
                ring_hit = ring_hits(
                    batch.rr_b.reshape(T * RR, params.key_width),
                    batch.rr_e.reshape(T * RR, params.key_width),
                    rv_q, state.ring_b, state.ring_e,
                    state.ring_v, state.ring_mask,
                    point_mode=False, interpret=interp,
                ).reshape(T, RR)
            elif PB:
                # exact checks against the query's TWO end partitions'
                # sub-rings (equal for short scans — the common case),
                # conservative per-partition version max for middles
                pq_lo = jnp.clip(batch.rr_lo >> pshift, 0, P - 1)
                pq_hi = jnp.clip(batch.rr_hi >> pshift, 0, P - 1)

                def _sub_hit(pq):
                    ov = ranges_overlap(
                        batch.rr_b[:, :, None, :],
                        batch.rr_e[:, :, None, :],
                        rb_p[pq], re_p[pq],
                    )  # [T, RR, KRs]
                    newer = (rv_p[pq] > rv[:, None, None]) & rm_p[pq]
                    return jnp.any(ov & newer, axis=2)

                ring_hit = _sub_hit(pq_lo) | _sub_hit(pq_hi)
                pidx = jnp.arange(P)
                mid = (pidx[None, None, :] > pq_lo[:, :, None]) & (
                    pidx[None, None, :] < pq_hi[:, :, None]
                )
                mid_max = jnp.max(
                    jnp.where(mid, part_max[None, None, :], u32(0)), axis=2
                )
                ring_hit |= mid_max > rv[:, None]
            else:
                ov = ranges_overlap(
                    batch.rr_b[:, :, None, :],
                    batch.rr_e[:, :, None, :],
                    state.ring_b[None, None],
                    state.ring_e[None, None],
                )  # [T, RR, KR]
                newer = (state.ring_v[None, None] > rv[:, None, None]) & state.ring_mask[None, None]
                ring_hit = jnp.any(ov & newer, axis=2)
            if ring_hit is not None:
                hit |= ring_hit & batch.rr_mask
            coarse_rng = jnp.minimum(pref_L[batch.rr_hi], suf_R[batch.rr_lo])
            hit |= (coarse_rng > rv[:, None]) & batch.rr_mask
        if params.point_writes:
            levels = _sparse_table(state.point_coarse)
            pmax = _range_max(levels, batch.rr_lo, batch.rr_hi)
            hit |= (pmax > rv[:, None]) & batch.rr_mask
        hist |= jnp.any(hit, axis=1)

    hist = por(hist)

    # a0: admissible before intra-batch ordering (history + window + mask)
    a0 = (~too_old) & (~hist) & batch.txn_mask

    if pallas_scan_on:
        # ── fused accept kernel: exact ring check + intra-batch
        # segment intersection + greedy acceptance in one pallas_call.
        # Greedy sequential acceptance is the unique fixpoint of the
        # Jacobi map below (induction on txn index), so this route is
        # bit-identical to the jnp one.
        from foundationdb_tpu.ops.pallas_scan import fused_accept

        accepted = fused_accept(state, batch, params, a0, interpret=interp)
    else:
        # ───────────────── intra-batch conflict matrix ─────────────────
        # O[t1, t2]: an accepted t1 < t2 would abort t2 (t1's writes hit
        # t2's reads). Each shard builds rows only from writes it owns;
        # the Jacobi loop OR-reduces the kill vectors.
        O = jnp.zeros((T, T), bool)
        if params.point_writes and params.point_reads:
            w_ok = batch.pw_mask & hash_owned(batch.pw_hash)
            wh = jnp.where(w_ok, batch.pw_hash, u32(0xFFFFFFFF))  # [T, PW]
            rh = jnp.where(batch.pr_mask, batch.pr_hash, u32(0xFFFFFFFE))  # [T, PR]
            eq = wh[:, :, None, None] == rh[None, None, :, :]  # [T1, PW, T2, PR]
            O |= jnp.any(eq, axis=(1, 3))
        if params.point_writes and params.range_reads:
            inr = _point_in(
                batch.pw_key[:, :, None, None, :], batch.rr_b[None, None], batch.rr_e[None, None]
            )  # [T1, PW, T2, RR]
            w_ok = batch.pw_mask & hash_owned(batch.pw_hash)
            m = w_ok[:, :, None, None] & batch.rr_mask[None, None]
            O |= jnp.any(inr & m, axis=(1, 3))
        if params.range_writes and params.point_reads:
            inr = _point_in(
                batch.pr_key[None, None],  # [1, 1, T2, PR, W]
                batch.rw_b[:, :, None, None, :],  # [T1, RW, 1, 1, W]
                batch.rw_e[:, :, None, None, :],
            )  # [T1, RW, T2, PR]
            w_ok = batch.rw_mask & bucket_owned(batch.rw_lo)
            m = w_ok[:, :, None, None] & batch.pr_mask[None, None]
            O |= jnp.any(inr & m, axis=(1, 3))
        if params.range_writes and params.range_reads:
            ov = ranges_overlap(
                batch.rr_b[None, None],  # [1, 1, T2, RR, W]
                batch.rr_e[None, None],
                batch.rw_b[:, :, None, None, :],  # [T1, RW, 1, 1, W]
                batch.rw_e[:, :, None, None, :],
            )
            w_ok = batch.rw_mask & bucket_owned(batch.rw_lo)
            m = w_ok[:, :, None, None] & batch.rr_mask[None, None]
            O |= jnp.any(ov & m, axis=(1, 3))

        strict_lower = jnp.tril(jnp.ones((T, T), bool), k=-1).T  # [t1 < t2]
        O &= strict_lower & batch.txn_mask[:, None] & batch.txn_mask[None, :]

        # ───────── Jacobi fixpoint for sequential acceptance ─────────
        # The kill vector is psum-reduced per iteration rather than
        # OR-folding the whole [T,T] matrix up front: d small [T]
        # reductions measure cheaper than one [T,T] all-reduce for the
        # shallow conflict chains real batches carry (d is the chain
        # depth, typically 1-3).
        Of = O.astype(jnp.bfloat16)

        def cond(carry):
            _, changed = carry
            return changed

        def body(carry):
            a, _ = carry
            killed_local = jnp.dot(
                a.astype(jnp.bfloat16), Of, preferred_element_type=jnp.float32
            )
            if axis_name is not None:
                killed_local = jax.lax.psum(killed_local, axis_name)
            killed = killed_local > 0.5
            a_new = a0 & ~killed
            return a_new, jnp.any(a_new != a)

        accepted, _ = jax.lax.while_loop(cond, body, (a0, jnp.array(True)))

    status = jnp.where(too_old, TOO_OLD, jnp.where(accepted, COMMITTED, CONFLICT))
    status = jnp.where(batch.txn_mask, status, CONFLICT)

    # ───────────────────────── history update ─────────────────────────────
    cv = batch.cv
    hb_mask = u32((1 << params.hash_bits) - 1)

    ht = state.ht
    point_coarse = state.point_coarse
    if params.point_writes:
        ok = batch.pw_mask & accepted[:, None]  # [T, PW]
        flat_h = (batch.pw_hash & hb_mask).reshape(-1)
        flat_bk = batch.pw_bucket.reshape(-1)
        # hash table: only the owning shard records (its check lane reads it);
        # point_coarse: replicated — every shard applies the identical update.
        ht_ok = (ok & hash_owned(batch.pw_hash)).reshape(-1)
        ht = ht.at[flat_h].max(
            jnp.where(ht_ok, cv, u32(0)), mode="promise_in_bounds"
        )
        if params.range_reads or params.record_point_coarse:
            # read only by range reads, but a point-specialized variant
            # must still RECORD (the full kernel reads it later)
            val = jnp.where(ok.reshape(-1), cv, u32(0))
            point_coarse = point_coarse.at[
                jnp.clip(flat_bk, 0, point_coarse.shape[0] - 1)
            ].max(val)

    ring_b, ring_e, ring_v = state.ring_b, state.ring_e, state.ring_v
    ring_lo, ring_hi, ring_mask = state.ring_lo, state.ring_hi, state.ring_mask
    ring_head = state.ring_head
    range_L, range_R = state.range_L, state.range_R
    if params.range_writes:
        kr = params.ring_capacity
        own_rw = bucket_owned(batch.rw_lo)
        ok = (batch.rw_mask & own_rw & accepted[:, None]).reshape(-1)  # [T*RW]
        flat_lo = batch.rw_lo.reshape(-1)
        flat_hi = batch.rw_hi.reshape(-1)
        if PB:
            # single-partition entries go exactly to their sub-ring;
            # spanning (or a flood overflowing one sub-ring in a single
            # batch) entries fold conservatively into the coarse
            # summaries — the same direction as eviction
            part_lo = jnp.clip(flat_lo >> pshift, 0, P - 1)
            part_hi = jnp.clip(flat_hi >> pshift, 0, P - 1)
            single = part_lo == part_hi
            ok_ring = ok & single
            onehot = ok_ring[:, None] & (
                part_lo[:, None] == jnp.arange(P)[None, :]
            )
            ranks = jnp.cumsum(onehot.astype(jnp.int32), axis=0) - 1
            rank = jnp.sum(jnp.where(onehot, ranks, 0), axis=1)
            overflow = ok_ring & (rank >= KRs)
            ok_ring = ok_ring & (rank < KRs)
            ok_coarse = ok & (~single | overflow)
            counts = jnp.minimum(
                jnp.sum(onehot.astype(jnp.int32), axis=0), KRs
            )
            pos = jnp.where(
                ok_ring,
                part_lo * KRs + (ring_head[part_lo] + rank) % KRs,
                kr,
            )
            new_head = ((ring_head + counts) % KRs).astype(jnp.int32)
            c_val = jnp.where(ok_coarse, cv, u32(0))
            range_L = range_L.at[
                jnp.clip(flat_lo, 0, range_L.shape[0] - 1)
            ].max(c_val)
            range_R = range_R.at[
                jnp.clip(flat_hi, 0, range_R.shape[0] - 1)
            ].max(c_val)
        else:
            ok_ring = ok
            slot_order = jnp.cumsum(ok) - 1  # position among accepted
            pos = jnp.where(ok, (ring_head + slot_order) % kr, kr)
            new_head = ((ring_head + jnp.sum(ok)) % kr).astype(jnp.int32)
        # fold evicted entries into the coarse interval summary first
        will_evict = jnp.zeros((kr,), bool).at[pos].set(True, mode="drop")
        evict = will_evict & ring_mask
        ev_val = jnp.where(evict, ring_v, u32(0))
        range_L = range_L.at[jnp.clip(ring_lo, 0, range_L.shape[0] - 1)].max(ev_val)
        range_R = range_R.at[jnp.clip(ring_hi, 0, range_R.shape[0] - 1)].max(ev_val)
        # append
        flat_b = batch.rw_b.reshape(-1, params.key_width)
        flat_e = batch.rw_e.reshape(-1, params.key_width)
        ring_b = ring_b.at[pos].set(flat_b, mode="drop")
        ring_e = ring_e.at[pos].set(flat_e, mode="drop")
        ring_v = ring_v.at[pos].set(jnp.where(ok_ring, cv, u32(0)), mode="drop")
        ring_lo = ring_lo.at[pos].set(flat_lo, mode="drop")
        ring_hi = ring_hi.at[pos].set(flat_hi, mode="drop")
        ring_mask = ring_mask.at[pos].set(ok_ring, mode="drop")
        ring_head = new_head
        # folds target arbitrary buckets; sync the replicated summaries
        range_L = pmax_arr(range_L)
        range_R = pmax_arr(range_R)

    new_state = ResolverState(
        # monotone: never regress the window (a recovered resolver's fence
        # must survive proxies whose cv-derived window is still behind it)
        window_start=jnp.maximum(state.window_start, batch.new_window_start),
        ht=ht,
        ring_b=ring_b,
        ring_e=ring_e,
        ring_v=ring_v,
        ring_lo=ring_lo,
        ring_hi=ring_hi,
        ring_mask=ring_mask,
        ring_head=ring_head,
        range_L=range_L,
        range_R=range_R,
        point_coarse=point_coarse,
    )
    return status, accepted, new_state


def validate_params(params: ResolverParams):
    """Shape invariants the kernel's safety argument depends on."""
    if params.txns * params.range_writes > params.ring_capacity:
        raise ValueError(
            f"ring_capacity {params.ring_capacity} < txns*range_writes "
            f"{params.txns * params.range_writes}: one batch could wrap the "
            "ring and silently drop committed range-writes from history"
        )
    if params.bucket_bits > 30 or params.hash_bits > 28:
        raise ValueError("bucket_bits/hash_bits unreasonably large")
    if params.use_pallas_scan:
        from foundationdb_tpu.ops.pallas_scan import MAX_TXNS

        if params.txns > MAX_TXNS:
            raise ValueError(
                f"use_pallas_scan requires txns <= {MAX_TXNS}: the fused "
                "kernel's txn-tile loops unroll at trace time (got "
                f"{params.txns})"
            )
    pb = params.ring_partition_bits
    if pb:
        if pb > params.bucket_bits:
            raise ValueError(
                "ring_partition_bits exceeds bucket_bits: partitions are "
                "keyed by the top coarse-bucket bits"
            )
        if params.ring_capacity % (1 << pb):
            raise ValueError(
                "ring_capacity must divide evenly into 2^ring_partition_bits "
                "sub-rings"
            )
        if params.use_pallas or params.use_pallas_scan:
            raise ValueError(
                "ring_partition_bits and use_pallas/use_pallas_scan are "
                "mutually exclusive: the Pallas VMEM kernels implement "
                "the FLAT ring layout (silently ignoring the explicit "
                "pallas request would misattribute benchmarks)"
            )


def resolve_batch_presharded(
    state: ResolverState,
    sb: ShardBatch,
    params: ResolverParams,
    axis_name=None,
):
    """The compacted-lane resolver step (single-dispatch sharded path).

    Semantics match ``resolve_batch``'s sharded mode, but ownership is
    established HOST-side by the router instead of in-kernel masks: each
    lane sees only the entries whose keys it owns, so the dominant cost
    terms — the [Q, KR] ring scan and the [Qw, Qr] pairwise matrix —
    shrink with the lane count instead of being replicated n times.

    Correctness rests on the routing invariants (ShardBatch docstring):
    any read/write pair that overlaps shares a key point p, and both
    entries are routed to lane(p), so every conflict is checked on at
    least one lane; ``por``/psum folds the per-lane partials. Per-lane
    scalars (``rv``, ``txn_mask``, ``cv``, window) are replicated, so
    ``too_old``/``status``/``accepted`` come out replicated — the proxy
    reads ONE verdict vector.
    """
    T = params.txns
    u32 = jnp.uint32
    rv = sb.rv  # [T]
    Qpr = sb.pr_key.shape[0]
    Qpw = sb.pw_key.shape[0]
    Qrr = sb.rr_b.shape[0]
    Qrw = sb.rw_b.shape[0]

    if axis_name is None:

        def por(x):
            return x

        def pmax_arr(x):
            return x

    else:
        names = axis_name if isinstance(axis_name, tuple) else (axis_name,)

        def por(x):
            return jax.lax.psum(x.astype(jnp.int32), names) > 0

        def pmax_arr(x):
            return jax.lax.pmax(x, names)

    # ───────────────────────── history conflicts ─────────────────────────
    too_old = rv < state.window_start

    # per-txn hit counts accumulate by scatter-ADD (a bool scatter-max is
    # not portably lowered); padding slots point at txn 0 with mask False
    # so they add zero
    hist_i = jnp.zeros((T,), jnp.int32)

    if params.range_writes:
        pref_L = jax.lax.associative_scan(jnp.maximum, state.range_L)
        suf_R = jax.lax.associative_scan(jnp.maximum, state.range_R, reverse=True)

    if Qpr:
        rv_q = rv[sb.pr_txn]  # [Qpr]
        hit = (
            state.ht[sb.pr_hash & u32((1 << params.hash_bits) - 1)] > rv_q
        ) & sb.pr_mask
        if params.range_writes:
            in_rng = _point_in(
                sb.pr_key[:, None, :], state.ring_b[None], state.ring_e[None]
            )  # [Qpr, KR]
            newer = (state.ring_v[None] > rv_q[:, None]) & state.ring_mask[None]
            hit |= jnp.any(in_rng & newer, axis=1) & sb.pr_mask
            coarse = jnp.minimum(pref_L[sb.pr_bucket], suf_R[sb.pr_bucket])
            hit |= (coarse > rv_q) & sb.pr_mask
        hist_i = hist_i.at[sb.pr_txn].add(
            hit.astype(jnp.int32), mode="promise_in_bounds"
        )

    if Qrr:
        rv_q = rv[sb.rr_txn]  # [Qrr]
        hit = jnp.zeros((Qrr,), bool)
        if params.range_writes:
            ov = ranges_overlap(
                sb.rr_b[:, None, :], sb.rr_e[:, None, :],
                state.ring_b[None], state.ring_e[None],
            )  # [Qrr, KR]
            newer = (state.ring_v[None] > rv_q[:, None]) & state.ring_mask[None]
            hit |= jnp.any(ov & newer, axis=1) & sb.rr_mask
            coarse_rng = jnp.minimum(pref_L[sb.rr_hi], suf_R[sb.rr_lo])
            hit |= (coarse_rng > rv_q) & sb.rr_mask
        if params.point_writes:
            levels = _sparse_table(state.point_coarse)
            pmax = _range_max(levels, sb.rr_lo, sb.rr_hi)
            hit |= (pmax > rv_q) & sb.rr_mask
        hist_i = hist_i.at[sb.rr_txn].add(
            hit.astype(jnp.int32), mode="promise_in_bounds"
        )

    hist = por(hist_i > 0)

    # ─────────────────────── intra-batch conflict matrix ───────────────────
    # O[t1, t2] accumulates by 2-D scatter-add over (write_txn, read_txn)
    # pairs; cross-lane duplicates (a spanning write × spanning read seen
    # on two lanes) just add twice before the >0 threshold.
    O_i = jnp.zeros((T, T), jnp.int32)
    if Qpw and Qpr:
        wh = jnp.where(sb.pw_mask, sb.pw_hash, u32(0xFFFFFFFF))
        rh = jnp.where(sb.pr_mask, sb.pr_hash, u32(0xFFFFFFFE))
        eq = wh[:, None] == rh[None, :]  # [Qpw, Qpr]
        O_i = O_i.at[sb.pw_txn[:, None], sb.pr_txn[None, :]].add(
            eq.astype(jnp.int32), mode="promise_in_bounds"
        )
    if Qpw and Qrr:
        inr = _point_in(
            sb.pw_key[:, None, :], sb.rr_b[None], sb.rr_e[None]
        )  # [Qpw, Qrr]
        m = sb.pw_mask[:, None] & sb.rr_mask[None, :]
        O_i = O_i.at[sb.pw_txn[:, None], sb.rr_txn[None, :]].add(
            (inr & m).astype(jnp.int32), mode="promise_in_bounds"
        )
    if Qrw and Qpr:
        inr = _point_in(
            sb.pr_key[None], sb.rw_b[:, None, :], sb.rw_e[:, None, :]
        )  # [Qrw, Qpr]
        m = sb.rw_mask[:, None] & sb.pr_mask[None, :]
        O_i = O_i.at[sb.rw_txn[:, None], sb.pr_txn[None, :]].add(
            (inr & m).astype(jnp.int32), mode="promise_in_bounds"
        )
    if Qrw and Qrr:
        ov = ranges_overlap(
            sb.rr_b[None], sb.rr_e[None],
            sb.rw_b[:, None, :], sb.rw_e[:, None, :],
        )  # [Qrw, Qrr]
        m = sb.rw_mask[:, None] & sb.rr_mask[None, :]
        O_i = O_i.at[sb.rw_txn[:, None], sb.rr_txn[None, :]].add(
            (ov & m).astype(jnp.int32), mode="promise_in_bounds"
        )

    strict_lower = jnp.tril(jnp.ones((T, T), bool), k=-1).T  # [t1 < t2]
    O = (O_i > 0) & strict_lower & sb.txn_mask[:, None] & sb.txn_mask[None, :]

    # Jacobi fixpoint — identical to resolve_batch: the kill vector is
    # psum-reduced per iteration (d small [T] reductions beat one [T,T]
    # all-reduce for the shallow chains real batches carry)
    a0 = (~too_old) & (~hist) & sb.txn_mask
    Of = O.astype(jnp.bfloat16)

    def cond(carry):
        _, changed = carry
        return changed

    def body(carry):
        a, _ = carry
        killed_local = jnp.dot(
            a.astype(jnp.bfloat16), Of, preferred_element_type=jnp.float32
        )
        if axis_name is not None:
            killed_local = jax.lax.psum(killed_local, axis_name)
        killed = killed_local > 0.5
        a_new = a0 & ~killed
        return a_new, jnp.any(a_new != a)

    accepted, _ = jax.lax.while_loop(cond, body, (a0, jnp.array(True)))

    status = jnp.where(too_old, TOO_OLD, jnp.where(accepted, COMMITTED, CONFLICT))
    status = jnp.where(sb.txn_mask, status, CONFLICT)

    # ───────────────────────── history update ─────────────────────────────
    cv = sb.cv
    ht = state.ht
    point_coarse = state.point_coarse
    if Qpw:
        ok = sb.pw_mask & accepted[sb.pw_txn]  # [Qpw]
        ht = ht.at[sb.pw_hash & u32((1 << params.hash_bits) - 1)].max(
            jnp.where(ok, cv, u32(0)), mode="promise_in_bounds"
        )
        if params.range_reads or params.record_point_coarse:
            # unlike the dense sharded path (where every lane applies the
            # identical replicated update), lanes here record DIFFERENT
            # subsets — the replicated summary needs an explicit pmax
            point_coarse = point_coarse.at[
                jnp.clip(sb.pw_bucket, 0, point_coarse.shape[0] - 1)
            ].max(jnp.where(ok, cv, u32(0)))
            point_coarse = pmax_arr(point_coarse)

    ring_b, ring_e, ring_v = state.ring_b, state.ring_e, state.ring_v
    ring_lo, ring_hi, ring_mask = state.ring_lo, state.ring_hi, state.ring_mask
    ring_head = state.ring_head
    range_L, range_R = state.range_L, state.range_R
    if Qrw:
        kr = ring_v.shape[0]
        ok = sb.rw_mask & accepted[sb.rw_txn]  # [Qrw]
        slot_order = jnp.cumsum(ok) - 1
        # a skewed split can exceed the per-lane ring in one batch (the
        # dense path's T*RW <= KR invariant is per-lane Q-dependent
        # here): overflowing entries fold conservatively into the coarse
        # interval summaries — the same direction as eviction
        ok_ring = ok & (slot_order < kr)
        overflow = ok & (slot_order >= kr)
        pos = jnp.where(ok_ring, (ring_head + slot_order) % kr, kr)
        new_head = (
            (ring_head + jnp.minimum(jnp.sum(ok), kr)) % kr
        ).astype(jnp.int32)
        o_val = jnp.where(overflow, cv, u32(0))
        range_L = range_L.at[
            jnp.clip(sb.rw_lo, 0, range_L.shape[0] - 1)
        ].max(o_val)
        range_R = range_R.at[
            jnp.clip(sb.rw_hi, 0, range_R.shape[0] - 1)
        ].max(o_val)
        # fold evicted entries into the coarse interval summary first
        will_evict = jnp.zeros((kr,), bool).at[pos].set(True, mode="drop")
        evict = will_evict & ring_mask
        ev_val = jnp.where(evict, ring_v, u32(0))
        range_L = range_L.at[jnp.clip(ring_lo, 0, range_L.shape[0] - 1)].max(ev_val)
        range_R = range_R.at[jnp.clip(ring_hi, 0, range_R.shape[0] - 1)].max(ev_val)
        ring_b = ring_b.at[pos].set(sb.rw_b, mode="drop")
        ring_e = ring_e.at[pos].set(sb.rw_e, mode="drop")
        ring_v = ring_v.at[pos].set(jnp.where(ok_ring, cv, u32(0)), mode="drop")
        ring_lo = ring_lo.at[pos].set(sb.rw_lo, mode="drop")
        ring_hi = ring_hi.at[pos].set(sb.rw_hi, mode="drop")
        ring_mask = ring_mask.at[pos].set(ok_ring, mode="drop")
        ring_head = new_head
        # folds target arbitrary buckets; sync the replicated summaries
        range_L = pmax_arr(range_L)
        range_R = pmax_arr(range_R)

    new_state = ResolverState(
        window_start=jnp.maximum(state.window_start, sb.new_window_start),
        ht=ht,
        ring_b=ring_b,
        ring_e=ring_e,
        ring_v=ring_v,
        ring_lo=ring_lo,
        ring_hi=ring_hi,
        ring_mask=ring_mask,
        ring_head=ring_head,
        range_L=range_L,
        range_R=range_R,
        point_coarse=point_coarse,
    )
    return status, accepted, new_state


def validate_presharded_params(params: ResolverParams):
    """Invariants of the compacted-lane path. The dense path's
    T*RW <= KR wrap check does not apply: the kernel detects per-lane
    ring overflow at trace shapes and folds the excess into the coarse
    summaries instead of wrapping."""
    if params.use_pallas or params.use_pallas_scan:
        raise ValueError(
            "presharded resolve has no Pallas lanes: the VMEM kernels "
            "implement the dense [T, K] layout (silently ignoring the "
            "explicit pallas request would misattribute benchmarks)"
        )
    if params.ring_partition_bits:
        raise ValueError(
            "ring_partition_bits is a single-device layout; the presharded "
            "path shards the ring across lanes instead"
        )
    if params.bucket_bits > 30 or params.hash_bits > 28:
        raise ValueError("bucket_bits/hash_bits unreasonably large")


def make_resolve_fn(params: ResolverParams, donate=True):
    """jit-compiled resolver step with the history buffers donated."""
    validate_params(params)
    fn = lambda state, batch: resolve_batch(state, batch, params)
    return jax.jit(fn, donate_argnums=(0,) if donate else ())


def scan_of(step_fn):
    """Lift a single-batch resolver step into a multi-batch scan:
    (state, batches[B, ...]) → (state, statuses[B, T]), the history
    threaded sequentially exactly as B successive calls would. Shared by
    the single-device and shard_map paths so the scan semantics cannot
    diverge between them."""

    def scan_step(state, batches):
        def body(s, b):
            status, _accepted, s2 = step_fn(s, b)
            return s2, status

        return jax.lax.scan(body, state, batches)

    return scan_step


def make_resolve_scan_fn(params: ResolverParams, donate=True,
                         keep_pallas=False):
    """jit-compiled *multi-batch* resolver step: ``lax.scan`` threads the
    history through a stack of batches (leading axis B) in one dispatch.

    By default the scan path runs the jnp ring lanes: measured on v5e,
    the Pallas ring kernel wins the single-step latency path (~1.65x
    faster kernel step — it is what make_resolve_fn uses) but loses
    inside lax.scan on POINT workloads, where XLA overlaps the fused jnp
    lanes across scan iterations better than it schedules repeated
    pallas_call launches. ``keep_pallas=True`` keeps the Pallas ring
    inside the scan — the right call when the ring walk dominates the
    step (range-heavy workloads), where its VMEM tiling beats the
    overlap XLA loses.

    Semantics are identical to calling ``resolve_batch`` B times in order
    — the scan carry is the same sequential state dependency — but one
    dispatch covers the stack. ``use_pallas_scan`` is NOT stripped: the
    fused accept kernel replaces the whole step body (ring + intra-batch
    + acceptance), so there is no jnp/pallas split for XLA to schedule
    around — the scan path keeps it whenever the params carry it. One
    dispatch amortizes the host→device launch cost across B batches,
    which dominates when the host link is high-latency (remote TPU) and
    still saves ~dispatch-overhead×B on local chips. This is the proxy's
    throughput path; single-batch ``make_resolve_fn`` is the latency path.
    Returns (state, statuses[B, T]).
    """
    validate_params(params)
    if not keep_pallas:
        params = params._replace(use_pallas=False)
    scan_step = scan_of(lambda s, b: resolve_batch(s, b, params))
    return jax.jit(scan_step, donate_argnums=(0,) if donate else ())


def count_retraces(fn, on_retrace, gate=None):
    """HOST-side compile-cache observer: wrap a jitted dispatch callable
    so every NEW argument shape/dtype signature fires ``on_retrace(sig)``
    once — a new signature is exactly what forces XLA to retrace and
    recompile. The check runs around the jit call (never inside the
    traced region — FL004), costs one tree-leaves walk per dispatch, and
    is skipped entirely while ``gate()`` is falsy (the profiler kill
    switch), so the disabled arm of the overhead smoke pays nothing but
    the gate call."""
    seen = set()

    def wrapped(*args):
        if gate is None or gate():
            sig = tuple(
                (tuple(getattr(leaf, "shape", ())),
                 str(getattr(leaf, "dtype", type(leaf).__name__)))
                for leaf in jax.tree.leaves(args)
            )
            if sig not in seen:
                seen.add(sig)
                on_retrace(sig)
        return fn(*args)

    return wrapped


def rebase_state(state: ResolverState, delta):
    """Shift all version offsets down by ``delta`` (saturating at 0).

    Called by the host when offsets approach uint32 range
    (core/versions.py REBASE_THRESHOLD). Safe when delta <= the current
    window start: clamped-to-0 entries had versions no read inside the
    window can still see (such reads are rejected TOO_OLD), so clamping
    only forgets writes that can no longer conflict.
    """
    d = jnp.uint32(delta)

    def shift(v):
        return jnp.where(v > d, v - d, jnp.uint32(0))

    return state._replace(
        window_start=shift(state.window_start),
        ht=shift(state.ht),
        ring_v=shift(state.ring_v),
        range_L=shift(state.range_L),
        range_R=shift(state.range_R),
        point_coarse=shift(state.point_coarse),
    )
