"""Host ConflictSet — exact MVCC conflict detection on byte keys.

Semantics-parity twin of ConflictSet::detectConflicts +
Resolver::resolveBatch (ref: fdbserver/SkipList.cpp,
fdbserver/Resolver.actor.cpp): keeps committed write ranges of the MVCC
window; a txn commits iff its read ranges miss every write range newer
than its read version, where earlier *accepted* txns of the same batch
count as committed at the batch's commit version.

Used as (a) the differential-test oracle for the TPU kernel and (b) the
``resolver_backend=cpu`` implementation. The reference uses a lock-free
skip list; here an interval list with lazy window pruning is enough for
the CPU path (the TPU path is the performance story), and a C++ twin
(native/) can slot in behind the same interface.
"""

from dataclasses import dataclass, field

from foundationdb_tpu.core.status import COMMITTED, CONFLICT, TOO_OLD


@dataclass
class TxnRequest:
    """One transaction's resolve payload.

    Ref: CommitTransactionRef in fdbclient/CommitTransaction.h
    (read_conflict_ranges, write_conflict_ranges, read_snapshot version).
    """

    read_version: int
    point_reads: list = field(default_factory=list)  # [bytes]
    point_writes: list = field(default_factory=list)  # [bytes]
    range_reads: list = field(default_factory=list)  # [(begin, end)]
    range_writes: list = field(default_factory=list)  # [(begin, end)]

    def read_ranges(self):
        for k in self.point_reads:
            yield k, k + b"\x00"
        yield from self.range_reads

    def write_ranges(self):
        for k in self.point_writes:
            yield k, k + b"\x00"
        yield from self.range_writes


class CpuConflictSet:
    """Exact interval-list conflict set over byte keys."""

    def __init__(self):
        self.window_start = 0
        self._entries = []  # list of (begin, end, version), unsorted
        self._ops_since_prune = 0

    def _conflicts(self, ranges, read_version, extra):
        for rb, re_ in ranges:
            for wb, we, wv in self._entries:
                if wv > read_version and rb < we and wb < re_:
                    return True
            for wb, we, wv in extra:
                if wv > read_version and rb < we and wb < re_:
                    return True
        return False

    def resolve(self, txns, commit_version, new_window_start=None):
        """Resolve a batch in arrival order; returns list of statuses."""
        statuses = []
        batch_writes = []
        for txn in txns:
            if txn.read_version < self.window_start:
                statuses.append(TOO_OLD)
                continue
            if self._conflicts(txn.read_ranges(), txn.read_version, batch_writes):
                statuses.append(CONFLICT)
                continue
            statuses.append(COMMITTED)
            for wb, we in txn.write_ranges():
                batch_writes.append((wb, we, commit_version))
        self._entries.extend(batch_writes)
        if new_window_start is not None:
            self.set_oldest_version(new_window_start)
        return statuses

    def conflicting_ranges(self, txn):
        """The subset of ``txn``'s read ranges that currently overlap a
        write newer than its read version — the payload behind the
        \\xff\\xff/transaction/conflicting_keys/ special keys (ref:
        conflictingKeysRange population in SkipList.cpp when
        report_conflicting_keys is set). Called right after the resolve
        that rejected the txn, so the batch's accepted writes are already
        in the entry list and intra-batch conflicts report too."""
        out = []
        for rb, re_ in txn.read_ranges():
            for wb, we, wv in self._entries:
                if wv > txn.read_version and rb < we and wb < re_:
                    out.append((rb, re_))
                    break
        return out

    def set_oldest_version(self, version):
        """Advance the MVCC window; prune entries no read can see anymore.
        Monotone: a recovered resolver's fence (window at the recovery
        version) must not regress when the proxy's cv-derived window is
        still behind it."""
        self.window_start = max(self.window_start, version)
        self._ops_since_prune += 1
        if self._ops_since_prune >= 64:
            self._ops_since_prune = 0
            self._entries = [e for e in self._entries if e[2] > version]

    def __len__(self):
        return len(self._entries)
