"""MeshResolver — the resolver fleet as ONE mesh program, behind the
single-resolver API.

Ref parity: multi-resolver deployments in the reference key-range-shard
conflict detection across resolver processes, with the commit proxy
fanning out sub-batches and AND-ing verdicts over the network
(fdbserver/CommitProxyServer.actor.cpp resolution fan-out,
fdbserver/Resolver.actor.cpp). The TPU-native shape keeps the whole
fleet inside one SPMD program over a `jax.sharding.Mesh`
(parallel/mesh.py ShardedResolverKernel): every device owns a shard of
the conflict history (hash-sharded point table, bucket-sharded range
ring), the batch is replicated, and verdicts combine with psum over ICI
— no host fan-out, no clipped sub-batches, ONE dispatch per batch.

Because the sharding is hash/bucket based (not key-range), there are no
resolver boundaries to re-derive from the data distribution and no
fencing rebuilds when shards move — the coordination problem the
reference's keyResolvers map exists to solve disappears.

`Cluster(n_resolvers=k, resolver_backend="tpu")` constructs one
MeshResolver over a k-lane mesh (clamped to the devices present; a
single-chip deployment degenerates to one lane). The commit proxy sees
`len(resolvers) == 1` and drives the plain single-resolver path —
including `resolve_many`'s scanned backlog dispatch, which runs the
whole mesh under `lax.scan`.
"""

import jax

from foundationdb_tpu.core.options import DEFAULT_KNOBS
from foundationdb_tpu.resolver.packing import BatchPacker
from foundationdb_tpu.resolver.resolver import (
    BACKLOG_B,
    Resolver,
    fast_params_of,
    params_from_knobs,
)
from foundationdb_tpu.utils import deviceprofile


class MeshResolver(Resolver):
    """Resolver-interface facade over ShardedResolverKernel.

    Inherits every host-side behavior from Resolver — base-version
    fencing, chunking over-capacity batches, the point-specialized fast
    variant, backlog chunking in resolve_many, uint32 rebase — and swaps
    the compiled steps for their shard_map twins. The device state lives
    here (donated through each step), exactly like the single-device
    resolver.
    """

    def __init__(self, knobs=DEFAULT_KNOBS, base_version=0, n_lanes=None,
                 mesh=None):
        from foundationdb_tpu.parallel.mesh import (
            ShardedResolverKernel,
            default_mesh,
        )

        self.knobs = knobs
        self.backend = "tpu"
        self.base_version = base_version
        self.alive = True
        self._init_metrics()
        self.profile = deviceprofile.DeviceProfile("resolver")
        self.wants_point_split = True
        self.accepts_flat = True  # same packer machinery as Resolver
        self.dispatch_wall_s = 0.0
        if mesh is None:
            n = max(1, min(n_lanes or 1, len(jax.devices())))
            if n_lanes is not None and n < n_lanes:
                from foundationdb_tpu.utils.trace import TraceEvent

                # fewer lanes = proportionally less global conflict-
                # history capacity than the operator sized for (more
                # conservative 1020s under load) — say so loudly
                TraceEvent("ResolverLanesClamped", severity=30).detail(
                    requested=n_lanes, lanes=n,
                    devices=len(jax.devices())).log()
                # the structured taxonomy's sharded_to_local cause: the
                # operator asked for a fleet the hardware can't host
                self.profile.record_fallback("sharded_to_local",
                                             n_lanes - n)
            mesh = default_mesh(n)
        self.mesh = mesh
        self.n_lanes = int(mesh.devices.size)
        # use_pallas stays False: the Pallas ring kernel is single-shard
        # only (each shard_map lane is its own program); the mesh runs
        # the jnp lanes. ring_partition_bits too — the mesh already
        # bucket-shards the ring ACROSS devices; partitioning within a
        # shard would nest two ownership schemes.
        self.params = params_from_knobs(knobs, use_pallas=False)._replace(
            ring_partition_bits=0
        )
        self.packer = BatchPacker(self.params)
        self._kernel = ShardedResolverKernel(self.params, mesh=self.mesh)
        self.state = self._kernel.state
        self._kernel.state = None  # ownership moves here (donated per step)
        self._resolve = self._kernel._step
        # point-specialized fast variant (see Resolver.__init__): same
        # state, range lanes statically off. make_state=False — the twin
        # kernel shares THIS resolver's state arrays.
        self._fast = None
        self._fast_params = fast_params_of(self.params)
        self._fast_kernel = None
        self._range_history = False
        if self._fast_params is not None:
            self._fast_kernel = ShardedResolverKernel(
                self._fast_params, mesh=self.mesh, make_state=False
            )
            self._fast = (
                BatchPacker(self._fast_params), self._fast_kernel._step
            )
        self._scan_fns = {}
        self._scan_pad_buckets = (
            (2, 4, BACKLOG_B)
            if jax.default_backend() == "cpu" else (BACKLOG_B,)
        )
        self.adopt_profile(self.profile)  # attach the packer hooks

    def _make_scan_fn(self, use_fast):
        kernel = self._fast_kernel if use_fast else self._kernel
        return kernel._scan_step

    def _profile_lanes(self, statuses):
        """Per-lane dispatch wall for one mesh dispatch (ROADMAP item
        4's lane-utilization skew, measured). The verdicts are
        replicated (out_spec P()), so every lane holds its own finished
        copy: blocking each lane's shard in stable device order and
        timestamping its completion gives per-lane walls host-side —
        a straggler lane stretches its entry, balanced lanes land
        together. HOST-side only (materialize time, FL004-clean)."""
        if not deviceprofile.enabled():
            return
        from foundationdb_tpu.parallel.mesh import lane_shards

        shards = lane_shards(statuses)
        if len(shards) <= 1:
            return
        t0 = deviceprofile.now()
        walls = []
        for s in shards:
            s.data.block_until_ready()
            walls.append(deviceprofile.now() - t0)
        self.profile.record_lanes(walls)

    def respawn(self, base_version):
        """Recruitment: a fresh fleet on the same mesh, fenced (the
        sharded history died with this instance)."""
        new = MeshResolver(self.knobs, base_version=base_version,
                           mesh=self.mesh)
        new._init_metrics(self.metrics)
        new.adopt_profile(self.profile)
        new._m_respawns.inc()
        return new
