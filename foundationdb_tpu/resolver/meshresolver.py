"""MeshResolver — the resolver fleet as ONE mesh program, behind the
single-resolver API.

Ref parity: multi-resolver deployments in the reference key-range-shard
conflict detection across resolver processes, with the commit proxy
fanning out sub-batches and AND-ing verdicts over the network
(fdbserver/CommitProxyServer.actor.cpp resolution fan-out,
fdbserver/Resolver.actor.cpp). The TPU-native shape keeps the whole
fleet inside one SPMD program over a `jax.sharding.Mesh`
(parallel/mesh.py ShardedResolverKernel): every device owns a shard of
the conflict history (hash-sharded point table, bucket-sharded range
ring), the batch is replicated, and verdicts combine with psum over ICI
— no host fan-out, no clipped sub-batches, ONE dispatch per batch.

Two lane-ownership schemes, selected by ``knobs.resolver_sharding``:

- ``"range"`` (default): the host routes each already-encoded entry to
  the lane(s) owning its key range (resolver/packing.ShardRouter — a
  vectorized cumsum pass over the packed arrays, no TxnRequest decode)
  and the device runs the COMPACTED per-lane slots
  (ops/conflict.resolve_batch_presharded). Per-lane scan and pairwise
  work shrink ~1/n — the path that makes k lanes faster than one.
- ``"hash"``: the batch is replicated and each lane carves ownership
  in-kernel (hash-sharded point table, bucket-sharded ring). No host
  routing pass, but per-lane work never shrinks. No resolver
  boundaries to re-derive from the data distribution — the coordination
  problem the reference's keyResolvers map exists to solve disappears —
  at the price of k× replicated FLOPs.

`Cluster(n_resolvers=k, resolver_backend="tpu")` constructs one
MeshResolver over a k-lane mesh (clamped to the devices present; a
single-chip deployment degenerates to one lane). The commit proxy sees
`len(resolvers) == 1` and drives the plain single-resolver path —
including `resolve_many`'s scanned backlog dispatch, which runs the
whole mesh under `lax.scan`.
"""

import jax
import numpy as np

from foundationdb_tpu.core.options import DEFAULT_KNOBS
from foundationdb_tpu.resolver.packing import BatchPacker, ShardRouter
from foundationdb_tpu.resolver.resolver import (
    BACKLOG_B,
    Resolver,
    fast_params_of,
    params_from_knobs,
)
from foundationdb_tpu.utils import deviceprofile


class MeshResolver(Resolver):
    """Resolver-interface facade over ShardedResolverKernel.

    Inherits every host-side behavior from Resolver — base-version
    fencing, chunking over-capacity batches, the point-specialized fast
    variant, backlog chunking in resolve_many, uint32 rebase — and swaps
    the compiled steps for their shard_map twins. The device state lives
    here (donated through each step), exactly like the single-device
    resolver.
    """

    def __init__(self, knobs=DEFAULT_KNOBS, base_version=0, n_lanes=None,
                 mesh=None):
        from foundationdb_tpu.parallel.mesh import (
            PreshardedResolverKernel,
            ShardedResolverKernel,
            default_mesh,
        )

        self.knobs = knobs
        self.backend = "tpu"
        self.base_version = base_version
        self.alive = True
        self._init_metrics()
        self.profile = deviceprofile.DeviceProfile("resolver")
        self.wants_point_split = True
        self.accepts_flat = True  # same packer machinery as Resolver
        self.dispatch_wall_s = 0.0
        if mesh is None:
            n = max(1, min(n_lanes or 1, len(jax.devices())))
            if n_lanes is not None and n < n_lanes:
                from foundationdb_tpu.utils.trace import TraceEvent

                # fewer lanes = proportionally less global conflict-
                # history capacity than the operator sized for (more
                # conservative 1020s under load) — say so loudly
                TraceEvent("ResolverLanesClamped", severity=30).detail(
                    requested=n_lanes, lanes=n,
                    devices=len(jax.devices())).log()
                # the structured taxonomy's sharded_to_local cause: the
                # operator asked for a fleet the hardware can't host
                self.profile.record_fallback("sharded_to_local",
                                             n_lanes - n)
            mesh = default_mesh(n)
        self.mesh = mesh
        self.n_lanes = int(mesh.devices.size)
        # use_pallas stays False: the Pallas ring kernel is single-shard
        # only (each shard_map lane is its own program); the mesh runs
        # the jnp lanes. ring_partition_bits too — the mesh already
        # bucket-shards the ring ACROSS devices; partitioning within a
        # shard would nest two ownership schemes.
        self.params = params_from_knobs(knobs, use_pallas=False)._replace(
            ring_partition_bits=0
        )
        self.packer = BatchPacker(self.params)
        # "range" (the default) is the single-dispatch compacted path:
        # the host routes each entry to the lane(s) owning its keys
        # (ShardRouter), so per-lane scan/pairwise work shrinks ~1/n.
        # "hash" is the replicated-batch path (in-kernel hash/bucket
        # ownership): no per-lane work reduction, but no host routing
        # pass either — the latency-floor choice for tiny fleets.
        self.sharding = getattr(knobs, "resolver_sharding", "range")
        self._fast = None
        self._fast_params = None
        self._fast_kernel = None
        self._range_history = False
        if self.sharding == "range":
            self._kernel = PreshardedResolverKernel(self.params,
                                                    mesh=self.mesh)
            self._router = ShardRouter(self.params, self.n_lanes)
            self._resolve = self._route_step
            # no point-specialized twin: the compacted layout already
            # skips dead sides per-entry, and a second compiled variant
            # would double the routing/compile surface for little win
        else:
            self._kernel = ShardedResolverKernel(self.params,
                                                 mesh=self.mesh)
            self._router = None
            self._resolve = self._kernel._step
            # point-specialized fast variant (see Resolver.__init__):
            # same state, range lanes statically off. make_state=False —
            # the twin kernel shares THIS resolver's state arrays.
            self._fast_params = fast_params_of(self.params)
            if self._fast_params is not None:
                self._fast_kernel = ShardedResolverKernel(
                    self._fast_params, mesh=self.mesh, make_state=False
                )
                self._fast = (
                    BatchPacker(self._fast_params), self._fast_kernel._step
                )
        self.state = self._kernel.state
        self._kernel.state = None  # ownership moves here (donated per step)
        self._scan_fns = {}
        self._scan_pad_buckets = (
            (2, 4, BACKLOG_B)
            if jax.default_backend() == "cpu" else (BACKLOG_B,)
        )
        # the fused-scan ladder extension is single-device only (the
        # mesh never carries a Pallas route), so the chunk bound stays
        # at the classic BACKLOG_B
        self._scan_max_backlog = self._scan_pad_buckets[-1]
        self.adopt_profile(self.profile)  # attach the packer hooks

    def _split_counted(self, stacked):
        """Route a stacked numpy ResolveBatch through the ShardRouter,
        recording per-lane ENTRY COUNTS as the lane-balance instrument
        (host-side, FL004-clean). The counts feed the same lane_skew_pct
        rollup the hash path fills with per-lane walls — in range mode
        the split balance IS the utilization story, and it is known
        before the device ever runs."""
        sb, k, lane_counts = self._router.split(stacked)
        if deviceprofile.enabled():
            self.profile.record_lane_counts(lane_counts.tolist())
        return sb, k

    def _route_step(self, state, batch):
        """Single-batch presharded step behind the ``self._resolve``
        signature: (state, numpy ResolveBatch) → (status, accepted,
        state). Accepted is not materialized separately (the status
        vector already encodes it; _step_kernel only reads status)."""
        stacked = jax.tree.map(lambda a: np.asarray(a)[None], batch)
        sb, k = self._split_counted(stacked)
        if k == 1:
            single = jax.tree.map(lambda a: a[0], sb)
            status, accepted, state = self._kernel._step(state, single)
            return status, accepted, state
        # rare over-capacity skew: the batch rides the scan as k slices
        state, st = self._kernel._scan_step(state, sb)
        status = self._router.reassemble(st, k)[0]
        return status, None, state

    def _make_scan_fn(self, use_fast):
        if self.sharding == "range":
            kern = self._kernel
            router = self._router

            def routed_scan(state, stacked):
                sb, k = self._split_counted(stacked)
                state, st = kern._scan_step(state, sb)
                if k > 1:
                    st = router.reassemble(st, k)
                return state, st

            return routed_scan
        kernel = self._fast_kernel if use_fast else self._kernel
        return kernel._scan_step

    def _profile_lanes(self, statuses):
        """Per-lane dispatch wall for one mesh dispatch (ROADMAP item
        4's lane-utilization skew, measured). The verdicts are
        replicated (out_spec P()), so every lane holds its own finished
        copy: blocking each lane's shard in stable device order and
        timestamping its completion gives per-lane walls host-side —
        a straggler lane stretches its entry, balanced lanes land
        together. HOST-side only (materialize time, FL004-clean).

        Range mode records per-lane ENTRY COUNTS at split time instead
        (_split_counted) — one instrument per mode, never mixed units in
        the same rollup."""
        if self.sharding == "range" or not deviceprofile.enabled():
            return
        from foundationdb_tpu.parallel.mesh import lane_shards

        shards = lane_shards(statuses)
        if len(shards) <= 1:
            return
        t0 = deviceprofile.now()
        walls = []
        for s in shards:
            s.data.block_until_ready()
            walls.append(deviceprofile.now() - t0)
        self.profile.record_lanes(walls)

    def status(self):
        doc = super().status()
        doc["sharding"] = self.sharding
        return doc

    def respawn(self, base_version):
        """Recruitment: a fresh fleet on the same mesh, fenced (the
        sharded history died with this instance)."""
        new = MeshResolver(self.knobs, base_version=base_version,
                           mesh=self.mesh)
        new._init_metrics(self.metrics)
        new.adopt_profile(self.profile)
        new._m_respawns.inc()
        return new
