"""Host-side packing: TxnRequests → fixed-shape ResolveBatch arrays.

The analog of ResolveTransactionBatchRequest serialization (ref:
fdbserver/ResolverInterface.h): the commit proxy packs a batch of
transactions' conflict ranges into device arrays once per batch; all key
comparison work then happens on the TPU.

Host hashing/bucketing MUST match the device (ops/intervals.fnv_hash):
the hash table and coarse buckets are written by the kernel with values
the host computed — keep the two implementations in lockstep (test:
tests/test_resolver.py::test_host_device_hash_parity).
"""

import numpy as np

from foundationdb_tpu.core.keys import KeyCodec
from foundationdb_tpu.ops.conflict import ResolveBatch, ResolverParams


def fnv_hash_np(limbs):
    """numpy twin of ops.intervals.fnv_hash. limbs: uint32[..., W]."""
    with np.errstate(over="ignore"):
        h = np.full(limbs.shape[:-1], 2166136261, dtype=np.uint32)
        for i in range(limbs.shape[-1]):
            h = (h ^ limbs[..., i]) * np.uint32(16777619)
        h = h ^ (h >> 16)
        h = h * np.uint32(0x7FEB352D)
        h = h ^ (h >> 15)
    return h


def bucket_of(limbs, bucket_bits):
    """Coarse bucket = top bits of the first limb (monotone in the key)."""
    return (limbs[..., 0] >> np.uint32(32 - bucket_bits)).astype(np.int32)


class BatchPacker:
    """Packs transactions for one resolver (arrival order preserved)."""

    def __init__(self, params: ResolverParams):
        self.params = params
        self.codec = KeyCodec(num_limbs=params.key_width - 1)

    def pack(self, txns, base_version, commit_version, new_window_start):
        """txns: list[TxnRequest] (resolver/skiplist.py), len <= params.txns.

        Versions are absolute; stored as uint32 offsets from base_version.
        Oversize per-txn conflict-range lists spill into the range lanes
        (a point op is just a tiny range), mirroring how the reference
        treats all conflict ranges as ranges.
        """
        p = self.params
        if len(txns) > p.txns:
            raise ValueError(f"batch of {len(txns)} exceeds capacity {p.txns}")
        T, W = p.txns, p.key_width
        u32, i32 = np.uint32, np.int32

        def off(v):
            o = v - base_version
            if o < 0:
                o = 0
            return u32(min(o, 0xFFFFFFFF))

        rv = np.zeros(T, u32)
        txn_mask = np.zeros(T, bool)
        pr_key = np.zeros((T, p.point_reads, W), u32)
        pr_mask = np.zeros((T, p.point_reads), bool)
        pw_key = np.zeros((T, p.point_writes, W), u32)
        pw_mask = np.zeros((T, p.point_writes), bool)
        rr_b = np.zeros((T, p.range_reads, W), u32)
        rr_e = np.zeros((T, p.range_reads, W), u32)
        rr_mask = np.zeros((T, p.range_reads), bool)
        rw_b = np.zeros((T, p.range_writes, W), u32)
        rw_e = np.zeros((T, p.range_writes, W), u32)
        rw_mask = np.zeros((T, p.range_writes), bool)

        for t, txn in enumerate(txns):
            txn_mask[t] = True
            rv[t] = off(txn.read_version)
            preads = list(txn.point_reads)
            pwrites = list(txn.point_writes)
            rreads = list(txn.range_reads)
            rwrites = list(txn.range_writes)
            # spill overflow point ops into the range lanes
            if len(preads) > p.point_reads:
                rreads += [(k, k + b"\x00") for k in preads[p.point_reads :]]
                preads = preads[: p.point_reads]
            if len(pwrites) > p.point_writes:
                rwrites += [(k, k + b"\x00") for k in pwrites[p.point_writes :]]
                pwrites = pwrites[: p.point_writes]
            # coalesce range overflow into a single covering range (conservative)
            if len(rreads) > p.range_reads:
                if p.range_reads == 0:
                    raise ValueError(
                        "txn has range/overflow reads but params.range_reads=0"
                    )
                tail = rreads[p.range_reads - 1 :]
                rreads = rreads[: p.range_reads - 1] + [
                    (min(b for b, _ in tail), max(e for _, e in tail))
                ]
            if len(rwrites) > p.range_writes:
                if p.range_writes == 0:
                    raise ValueError(
                        "txn has range/overflow writes but params.range_writes=0"
                    )
                tail = rwrites[p.range_writes - 1 :]
                rwrites = rwrites[: p.range_writes - 1] + [
                    (min(b for b, _ in tail), max(e for _, e in tail))
                ]
            for i, k in enumerate(preads):
                pr_key[t, i] = self.codec.encode_lower(k)
                pr_mask[t, i] = True
            for i, k in enumerate(pwrites):
                pw_key[t, i] = self.codec.encode_lower(k)
                pw_mask[t, i] = True
            for i, (b, e) in enumerate(rreads):
                rr_b[t, i] = self.codec.encode_lower(b)
                rr_e[t, i] = self.codec.encode_upper(e)
                rr_mask[t, i] = True
            for i, (b, e) in enumerate(rwrites):
                rw_b[t, i] = self.codec.encode_lower(b)
                rw_e[t, i] = self.codec.encode_upper(e)
                rw_mask[t, i] = True

        return ResolveBatch(
            rv=rv,
            txn_mask=txn_mask,
            pr_hash=fnv_hash_np(pr_key),
            pr_key=pr_key,
            pr_bucket=bucket_of(pr_key, p.bucket_bits),
            pr_mask=pr_mask,
            pw_hash=fnv_hash_np(pw_key),
            pw_key=pw_key,
            pw_bucket=bucket_of(pw_key, p.bucket_bits),
            pw_mask=pw_mask,
            rr_b=rr_b,
            rr_e=rr_e,
            rr_lo=bucket_of(rr_b, p.bucket_bits),
            rr_hi=bucket_of(rr_e, p.bucket_bits),
            rr_mask=rr_mask,
            rw_b=rw_b,
            rw_e=rw_e,
            rw_lo=bucket_of(rw_b, p.bucket_bits),
            rw_hi=bucket_of(rw_e, p.bucket_bits),
            rw_mask=rw_mask,
            cv=np.uint32(commit_version - base_version),
            new_window_start=np.uint32(max(0, new_window_start - base_version)),
        )
