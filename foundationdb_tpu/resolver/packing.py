"""Host-side packing: TxnRequests → fixed-shape ResolveBatch arrays.

The analog of ResolveTransactionBatchRequest serialization (ref:
fdbserver/ResolverInterface.h): the commit proxy packs a batch of
transactions' conflict ranges into device arrays once per batch; all key
comparison work then happens on the TPU.

Host hashing/bucketing MUST match the device (ops/intervals.fnv_hash):
the hash table and coarse buckets are written by the kernel with values
the host computed — keep the two implementations in lockstep (test:
tests/test_resolver.py::test_host_device_hash_parity).
"""

import numpy as np

from foundationdb_tpu.core.keys import KeyCodec
from foundationdb_tpu.ops.conflict import (
    ResolveBatch,
    ResolverParams,
    ShardBatch,
)


def fnv_hash_np(limbs):
    """numpy twin of ops.intervals.fnv_hash. limbs: uint32[..., W]."""
    with np.errstate(over="ignore"):
        h = np.full(limbs.shape[:-1], 2166136261, dtype=np.uint32)
        for i in range(limbs.shape[-1]):
            h = (h ^ limbs[..., i]) * np.uint32(16777619)
        h = h ^ (h >> 16)
        h = h * np.uint32(0x7FEB352D)
        h = h ^ (h >> 15)
    return h


def bucket_of(limbs, bucket_bits):
    """Coarse bucket = top bits of the first limb (monotone in the key)."""
    return (limbs[..., 0] >> np.uint32(32 - bucket_bits)).astype(np.int32)


def _slots(c):
    """counts[n] → (txn index, lane index) per flattened op."""
    t_idx = np.repeat(np.arange(len(c)), c)
    starts = np.cumsum(c) - c
    i_idx = np.arange(len(t_idx)) - np.repeat(starts, c)
    return t_idx, i_idx


def _rows_struct(rows):
    """uint32[N, W] limb rows → structured[N] whose searchsorted order
    is exactly the limb-lexicographic key order (the host twin of
    ops/intervals.lex_lt): per-field big-endian u4 fields compare
    field-by-field numerically, i.e. limb-by-limb."""
    W = rows.shape[-1]
    dt = np.dtype([("l%d" % i, ">u4") for i in range(W)])
    be = np.ascontiguousarray(rows.astype(">u4"))
    return be.view(dt).reshape(rows.shape[:-1])


class ShardRouter:
    """Key-range router for the presharded single-dispatch resolve.

    Consumes the stacked numpy ResolveBatch ``pack_flat_group`` already
    built (no blob re-parse, no per-key Python) and re-scatters every
    live entry into per-lane COMPACTED slot arrays (ops/conflict
    ShardBatch): point entries go to exactly ``lane(key)``; range
    entries get one slot in every lane their span touches, carrying the
    full unclipped range. All routing is vectorized — nonzero gathers,
    one searchsorted per side against the lane bounds, and a stable
    argsort-rank (the cumsum trick) to assign slots within each
    (batch, lane) group.

    Per-lane capacity ``Q`` per conflict side is sized to
    ``headroom × T·K / n`` (the balanced-split expectation plus slack);
    a batch whose skew overflows a lane retries split into ``k``
    txn-slices (verdict-equivalent: intra-batch kills become
    history-version kills of the same direction, order preserved) —
    ``reassemble`` undoes the slicing on the status matrix. A
    single-txn slice always fits because Q ≥ K per side.

    ``bounds``: uint32[n-1, W] sorted limb-row split points; lane j owns
    [bounds[j-1], bounds[j]). Defaults to the uniform first-limb split —
    the same keyspace carve ``server/proxy._resolver_range`` uses before
    DD moves boundaries.
    """

    MAX_CHUNK_WARN = 16  # beyond this the host slicing dominates

    def __init__(self, params: ResolverParams, n, bounds=None,
                 headroom=1.75):
        self.params = params
        self.n = int(n)
        W = params.key_width
        if bounds is None:
            first = (
                (np.arange(1, self.n, dtype=np.uint64) << np.uint64(32))
                // np.uint64(self.n)
            ).astype(np.uint32)
            bounds = np.zeros((max(self.n - 1, 0), W), np.uint32)
            bounds[:, 0] = first
        self.bounds = np.ascontiguousarray(
            np.asarray(bounds, np.uint32).reshape(self.n - 1, W)
        )
        self._bounds_s = _rows_struct(self.bounds)
        T = params.txns
        self.caps = {
            "pr": self._cap(T, params.point_reads, headroom),
            "pw": self._cap(T, params.point_writes, headroom),
            "rr": self._cap(T, params.range_reads, headroom),
            "rw": self._cap(T, params.range_writes, headroom),
        }

    def _cap(self, T, K, headroom):
        """Per-lane slot capacity for a side with K entries/txn: the
        full dense width at n=1 (no routing win possible), otherwise
        the balanced-split share with headroom, floored at K (one txn's
        entries always fit → chunking terminates) and 8-rounded."""
        if not K:
            return 0
        full = T * K
        if self.n == 1:
            return full
        q = max(K, int(np.ceil(headroom * full / self.n)))
        q = -(-q // 8) * 8
        return min(q, full)

    def lane_of_points(self, rows):
        """lane index per limb row (uint32[N, W])."""
        return np.searchsorted(
            self._bounds_s, _rows_struct(rows), side="right"
        ).astype(np.int64)

    def lane_span(self, b_rows, e_rows):
        """(first, last) lane touched by each range [b, e): the last
        lane is the one containing the greatest key < e, i.e. the count
        of bounds strictly below e."""
        lo = np.searchsorted(
            self._bounds_s, _rows_struct(b_rows), side="right"
        ).astype(np.int64)
        hi = np.searchsorted(
            self._bounds_s, _rows_struct(e_rows), side="left"
        ).astype(np.int64)
        return lo, np.maximum(hi, lo)  # degenerate ranges stay 1-lane

    def split(self, stacked: ResolveBatch):
        """stacked numpy ResolveBatch [B, T, …] → (ShardBatch with
        leading dim B·k and lane axis n·Q, chunk factor k, per-lane
        entry counts[n] — the lane_skew_pct instrument)."""
        B, T = stacked.rv.shape
        k = 1
        while True:
            out = self._try_split(stacked, B, T, k)
            if out is not None:
                sb, lane_counts = out
                return sb, k, lane_counts
            k *= 2
            if k > T:
                raise ValueError(
                    "shard split cannot place a single-txn slice: "
                    f"caps {self.caps} mis-sized for T={T}"
                )

    def reassemble(self, st, k):
        """Undo txn-slice chunking on a status stack: [B·k, T] → [B, T]
        (sub-batch c carried txns [c·Ts, (c+1)·Ts) in slots [0, Ts))."""
        if k == 1:
            return st
        T = st.shape[-1]
        Ts = -(-T // k)
        B = st.shape[0] // k
        return st.reshape(B, k, T)[:, :, :Ts].reshape(B, k * Ts)[:, :T]

    def _try_split(self, stacked, B, T, k):
        n = self.n
        Ts = -(-T // k)
        rows = B * k
        i32, u32 = np.int32, np.uint32
        W = self.params.key_width
        lane_counts = np.zeros(n, np.int64)
        bufs = {}

        sides = (
            ("pr", False, (stacked.pr_hash, stacked.pr_key,
                           stacked.pr_bucket)),
            ("pw", False, (stacked.pw_hash, stacked.pw_key,
                           stacked.pw_bucket)),
            ("rr", True, (stacked.rr_b, stacked.rr_e,
                          stacked.rr_lo, stacked.rr_hi)),
            ("rw", True, (stacked.rw_b, stacked.rw_e,
                          stacked.rw_lo, stacked.rw_hi)),
        )
        for name, is_range, srcs in sides:
            Q = self.caps[name]
            nq = n * Q
            if is_range:
                bufs[name] = {
                    "b": np.zeros((rows, nq, W), u32),
                    "e": np.zeros((rows, nq, W), u32),
                    "lo": np.zeros((rows, nq), i32),
                    "hi": np.zeros((rows, nq), i32),
                    "txn": np.zeros((rows, nq), i32),
                    "mask": np.zeros((rows, nq), np.bool_),
                }
            else:
                zh = fnv_hash_np(np.zeros((1, W), u32))[0]
                bufs[name] = {
                    "hash": np.full((rows, nq), zh, u32),
                    "key": np.zeros((rows, nq, W), u32),
                    "bucket": np.zeros((rows, nq), i32),
                    "txn": np.zeros((rows, nq), i32),
                    "mask": np.zeros((rows, nq), np.bool_),
                }
            if not Q:
                continue
            mask = getattr(stacked, name + "_mask")
            b_idx, t_idx, l_idx = np.nonzero(mask)
            if not len(b_idx):
                continue
            if is_range:
                kb = srcs[0][b_idx, t_idx, l_idx]  # [N, W]
                ke = srcs[1][b_idx, t_idx, l_idx]
                lo, hi = self.lane_span(kb, ke)
                span = hi - lo + 1
                rep = np.repeat(np.arange(len(b_idx)), span)
                off = np.arange(span.sum()) - np.repeat(
                    np.cumsum(span) - span, span
                )
                lane = lo[rep] + off
            else:
                keys = srcs[1][b_idx, t_idx, l_idx]  # [N, W]
                lane = self.lane_of_points(keys)
                rep = np.arange(len(b_idx))
            sub = t_idx[rep] // Ts
            row = b_idx[rep] * k + sub
            g = row * n + lane
            counts = np.bincount(g, minlength=rows * n)
            if counts.max(initial=0) > Q:
                return None
            lane_counts += counts.reshape(rows, n).sum(axis=0)
            order = np.argsort(g, kind="stable")
            starts = np.cumsum(counts) - counts
            rank = np.empty(len(g), np.int64)
            rank[order] = np.arange(len(g)) - starts[g[order]]
            col = lane * Q + rank
            out = bufs[name]
            out["txn"][row, col] = (t_idx[rep] % Ts).astype(i32)
            out["mask"][row, col] = True
            if is_range:
                out["b"][row, col] = kb[rep]
                out["e"][row, col] = ke[rep]
                out["lo"][row, col] = srcs[2][b_idx, t_idx, l_idx][rep]
                out["hi"][row, col] = srcs[3][b_idx, t_idx, l_idx][rep]
            else:
                out["hash"][row, col] = srcs[0][b_idx, t_idx, l_idx][rep]
                out["key"][row, col] = keys[rep]
                out["bucket"][row, col] = srcs[2][b_idx, t_idx, l_idx][rep]

        if k == 1:
            rv_out = np.ascontiguousarray(stacked.rv, u32)
            mask_out = np.ascontiguousarray(stacked.txn_mask, np.bool_)
            cv_out = np.asarray(stacked.cv, u32).reshape(B)
            nws_out = np.asarray(
                stacked.new_window_start, u32
            ).reshape(B)
        else:
            pad = k * Ts - T
            rv_out = np.zeros((rows, T), u32)
            mask_out = np.zeros((rows, T), np.bool_)
            rv_out[:, :Ts] = np.pad(
                stacked.rv, ((0, 0), (0, pad))
            ).reshape(rows, Ts)
            mask_out[:, :Ts] = np.pad(
                stacked.txn_mask, ((0, 0), (0, pad))
            ).reshape(rows, Ts)
            cv_out = np.repeat(np.asarray(stacked.cv, u32).reshape(B), k)
            # the window advance rides ONLY the last slice of each
            # batch: earlier slices of the same batch must be judged
            # under the pre-batch window, exactly as the dense kernel
            # computes too_old before applying new_window_start
            nws_out = np.zeros(rows, u32)
            nws_out[k - 1 :: k] = np.asarray(
                stacked.new_window_start, u32
            ).reshape(B)

        sb = ShardBatch(
            rv=rv_out, txn_mask=mask_out,
            pr_hash=bufs["pr"]["hash"], pr_key=bufs["pr"]["key"],
            pr_bucket=bufs["pr"]["bucket"], pr_txn=bufs["pr"]["txn"],
            pr_mask=bufs["pr"]["mask"],
            pw_hash=bufs["pw"]["hash"], pw_key=bufs["pw"]["key"],
            pw_bucket=bufs["pw"]["bucket"], pw_txn=bufs["pw"]["txn"],
            pw_mask=bufs["pw"]["mask"],
            rr_b=bufs["rr"]["b"], rr_e=bufs["rr"]["e"],
            rr_lo=bufs["rr"]["lo"], rr_hi=bufs["rr"]["hi"],
            rr_txn=bufs["rr"]["txn"], rr_mask=bufs["rr"]["mask"],
            rw_b=bufs["rw"]["b"], rw_e=bufs["rw"]["e"],
            rw_lo=bufs["rw"]["lo"], rw_hi=bufs["rw"]["hi"],
            rw_txn=bufs["rw"]["txn"], rw_mask=bufs["rw"]["mask"],
            cv=cv_out, new_window_start=nws_out,
        )
        return sb, lane_counts


class BatchPacker:
    """Packs transactions for one resolver (arrival order preserved).

    Two paths, bit-identical outputs (tests/test_packing_native.py):
      - native: one C pass over the txn list (native/packer.cpp) — the
        default when the toolchain is available; >10x the numpy path.
      - numpy: whole-batch frombuffer encoding — the fallback, and the
        only path that handles lane overflow (spill/coalesce), so the
        native path defers to it on overflow (return code 1).
    """

    # staging sets kept alive per stacked shape before a slot is reused:
    # jax may alias (zero-copy) host numpy arrays into device buffers on
    # CPU backends, and the commit pipeline keeps up to
    # commit_pipeline_depth groups in flight — a slot must outlive every
    # dispatch that could still be reading it
    STAGING_RING = 4

    def __init__(self, params: ResolverParams, use_native=True):
        self.params = params
        self.codec = KeyCodec(num_limbs=params.key_width - 1)
        self._native = None
        self._empty = None  # cached zero-txn pad batch (pack_empty)
        self._flat_rings = {}  # B → list of reusable staging dicts
        self._flat_ring_next = {}  # B → next slot index
        self._zero_hash = None  # fnv of an all-zero key row (cached)
        self.flat_reuse_hits = 0
        self.flat_reuse_misses = 0
        # device-path profiler hook (utils/deviceprofile.py): the
        # owning resolver attaches its DeviceProfile so staging-ring
        # reuse-vs-realloc events land in the cluster.device doc
        self.profile = None
        if use_native and params.key_width - 1 <= 16:
            from foundationdb_tpu.native import load_packer

            self._native = load_packer()

    # ── flat columnar path (core/flatpack.py FlatTxnBatch) ───────────
    def flat_fits(self, flat):
        """Whether pack_flat_group can serve this batch: matching limb
        width and every txn's op counts inside the packed lanes (the
        legacy path's _normalize spill/coalesce has no flat twin — the
        rare overflowing batch decodes and rides legacy)."""
        p = self.params
        return (
            flat.num_limbs == p.key_width - 1
            and len(flat) <= p.txns
            and flat.prc.max(initial=0) <= p.point_reads
            and flat.pwc.max(initial=0) <= p.point_writes
            and flat.rrc.max(initial=0) <= p.range_reads
            and flat.rwc.max(initial=0) <= p.range_writes
        )

    def _flat_staging(self, B):
        """A zeroed staging set of stacked (B, T, …) arrays from the
        per-shape reuse ring. Reuse (a fill(0) instead of eleven fresh
        allocations per group) is the hit the pack-stage counters
        report."""
        p = self.params
        ring = self._flat_rings.get(B)
        if ring is None:
            ring = self._flat_rings[B] = []
            self._flat_ring_next[B] = 0
        zero_hash = self._zero_hash
        if zero_hash is None:
            zero_hash = self._zero_hash = fnv_hash_np(
                np.zeros((1, self.params.key_width), np.uint32)
            )[0]
        if len(ring) < self.STAGING_RING:
            self.flat_reuse_misses += 1
            if self.profile is not None:
                self.profile.record_staging(hit=False)
            T, W = p.txns, p.key_width
            bufs = {
                "rv": np.zeros((B, T), np.uint32),
                "txn_mask": np.zeros((B, T), np.bool_),
                "pr_key": np.zeros((B, T, p.point_reads, W), np.uint32),
                "pr_hash": np.full((B, T, p.point_reads), zero_hash,
                                   np.uint32),
                "pr_bucket": np.zeros((B, T, p.point_reads), np.int32),
                "pr_mask": np.zeros((B, T, p.point_reads), np.bool_),
                "pw_key": np.zeros((B, T, p.point_writes, W), np.uint32),
                "pw_hash": np.full((B, T, p.point_writes), zero_hash,
                                   np.uint32),
                "pw_bucket": np.zeros((B, T, p.point_writes), np.int32),
                "pw_mask": np.zeros((B, T, p.point_writes), np.bool_),
                "rr_b": np.zeros((B, T, p.range_reads, W), np.uint32),
                "rr_e": np.zeros((B, T, p.range_reads, W), np.uint32),
                "rr_lo": np.zeros((B, T, p.range_reads), np.int32),
                "rr_hi": np.zeros((B, T, p.range_reads), np.int32),
                "rr_mask": np.zeros((B, T, p.range_reads), np.bool_),
                "rw_b": np.zeros((B, T, p.range_writes, W), np.uint32),
                "rw_e": np.zeros((B, T, p.range_writes, W), np.uint32),
                "rw_lo": np.zeros((B, T, p.range_writes), np.int32),
                "rw_hi": np.zeros((B, T, p.range_writes), np.int32),
                "rw_mask": np.zeros((B, T, p.range_writes), np.bool_),
                "cv": np.zeros(B, np.uint32),
                "nws": np.zeros(B, np.uint32),
            }
            ring.append(bufs)
            return bufs
        i = self._flat_ring_next[B]
        self._flat_ring_next[B] = (i + 1) % len(ring)
        self.flat_reuse_hits += 1
        if self.profile is not None:
            self.profile.record_staging(hit=True)
        bufs = ring[i]
        for name, a in bufs.items():
            if name in ("pr_hash", "pw_hash"):
                a.fill(zero_hash)  # the hash of an all-zero key row
            elif name not in ("cv", "nws"):  # fully overwritten below
                a.fill(0)
        return bufs

    def pack_flat_group(self, flats, metas, base_version, B=None):
        """Pack a whole backlog group of FlatTxnBatches into ONE stacked
        ResolveBatch (leading dim ``B``, zero-padded past ``len(flats)``
        like resolve_many's pack_empty pads) — bit-identical to packing
        each batch with :meth:`pack` and ``np.stack``-ing, without a
        single per-transaction Python step: blob bytes become limb rows
        with one frombuffer per lane, slot indices come from cumsums,
        and hashing/bucketing run once over the stacked arrays.

        ``metas``: [(commit_version, new_window_start)] per flat batch;
        pads inherit the last entry (matching the legacy pad template).
        Callers must have checked :meth:`flat_fits` per batch.
        """
        from foundationdb_tpu.core import flatpack

        p = self.params
        nb = len(flats)
        if B is None:
            B = nb
        bufs = self._flat_staging(B)
        u32 = np.uint32
        # group-GLOBAL scatter: one index build + one fancy-index store
        # per lane for the whole backlog, however many batches it holds
        # (per-batch loops were the next-largest pack cost after the
        # dispatch itself). b_of/t_of map a global txn row to its
        # (batch, txn-lane) slot; entry rows index through them.
        if nb == 1:
            f = flats[0]
            n_txns = np.array([len(f)], dtype=np.int64)
            rv_all = f.rv
            cat = (
                (f.prc, f.pwc, f.rrc, f.rwc),
                (f.pr_blob, f.pw_blob, f.rr_blob, f.rw_blob),
            )
        else:
            n_txns = np.fromiter(
                (len(f) for f in flats), np.int64, count=nb
            )
            rv_all = np.concatenate([f.rv for f in flats])
            cat = (
                tuple(
                    np.concatenate([getattr(f, c) for f in flats])
                    for c in ("prc", "pwc", "rrc", "rwc")
                ),
                tuple(
                    b"".join([getattr(f, c) for f in flats])
                    for c in ("pr_blob", "pw_blob", "rr_blob", "rw_blob")
                ),
            )
        (prc, pwc, rrc, rwc), (pr_blob, pw_blob, rr_blob, rw_blob) = cat
        b_of = np.repeat(np.arange(nb), n_txns)
        _, t_of = _slots(n_txns)
        if len(rv_all):
            bufs["rv"][b_of, t_of] = np.clip(
                rv_all - base_version, 0, 0xFFFFFFFF
            ).astype(u32)
            bufs["txn_mask"][b_of, t_of] = True
        L = p.key_width - 1
        if len(pr_blob):
            t, i = _slots(prc)
            bufs["pr_key"][b_of[t], t_of[t], i] = flatpack.point_limbs(
                pr_blob, L)
            bufs["pr_mask"][b_of[t], t_of[t], i] = True
        if len(pw_blob):
            t, i = _slots(pwc)
            bufs["pw_key"][b_of[t], t_of[t], i] = flatpack.point_limbs(
                pw_blob, L)
            bufs["pw_mask"][b_of[t], t_of[t], i] = True
        if len(rr_blob):
            t, i = _slots(rrc)
            lo, hi = flatpack.range_limbs(rr_blob, L)
            bufs["rr_b"][b_of[t], t_of[t], i] = lo
            bufs["rr_e"][b_of[t], t_of[t], i] = hi
            bufs["rr_mask"][b_of[t], t_of[t], i] = True
        if len(rw_blob):
            t, i = _slots(rwc)
            lo, hi = flatpack.range_limbs(rw_blob, L)
            bufs["rw_b"][b_of[t], t_of[t], i] = lo
            bufs["rw_e"][b_of[t], t_of[t], i] = hi
            bufs["rw_mask"][b_of[t], t_of[t], i] = True
        for b, (cv, ws) in enumerate(metas):
            bufs["cv"][b] = u32(cv - base_version)
            bufs["nws"][b] = u32(max(0, ws - base_version))
        if nb < B:  # pads share the last batch's version scalars
            bufs["cv"][nb:] = bufs["cv"][nb - 1] if nb else 0
            bufs["nws"][nb:] = bufs["nws"][nb - 1] if nb else 0
        # hash/bucket only the LIVE batches: pad rows already hold the
        # all-zero-key constants (zero_hash / bucket 0) from staging
        bufs["pr_hash"][:nb] = fnv_hash_np(bufs["pr_key"][:nb])
        bufs["pr_bucket"][:nb] = bucket_of(bufs["pr_key"][:nb],
                                           p.bucket_bits)
        bufs["pw_hash"][:nb] = fnv_hash_np(bufs["pw_key"][:nb])
        bufs["pw_bucket"][:nb] = bucket_of(bufs["pw_key"][:nb],
                                           p.bucket_bits)
        bufs["rr_lo"][:nb] = bucket_of(bufs["rr_b"][:nb], p.bucket_bits)
        bufs["rr_hi"][:nb] = bucket_of(bufs["rr_e"][:nb], p.bucket_bits)
        bufs["rw_lo"][:nb] = bucket_of(bufs["rw_b"][:nb], p.bucket_bits)
        bufs["rw_hi"][:nb] = bucket_of(bufs["rw_e"][:nb], p.bucket_bits)
        return ResolveBatch(
            rv=bufs["rv"], txn_mask=bufs["txn_mask"],
            pr_hash=bufs["pr_hash"], pr_key=bufs["pr_key"],
            pr_bucket=bufs["pr_bucket"], pr_mask=bufs["pr_mask"],
            pw_hash=bufs["pw_hash"], pw_key=bufs["pw_key"],
            pw_bucket=bufs["pw_bucket"], pw_mask=bufs["pw_mask"],
            rr_b=bufs["rr_b"], rr_e=bufs["rr_e"],
            rr_lo=bufs["rr_lo"], rr_hi=bufs["rr_hi"],
            rr_mask=bufs["rr_mask"],
            rw_b=bufs["rw_b"], rw_e=bufs["rw_e"],
            rw_lo=bufs["rw_lo"], rw_hi=bufs["rw_hi"],
            rw_mask=bufs["rw_mask"],
            cv=bufs["cv"], new_window_start=bufs["nws"],
        )

    def pack_flat(self, flat, base_version, commit_version,
                  new_window_start):
        """Single-batch flat pack: one group slot, leading dim dropped —
        shape-compatible with :meth:`pack`'s output (the sync
        commit_batch path)."""
        stacked = self.pack_flat_group(
            [flat], [(commit_version, new_window_start)], base_version,
            B=1,
        )
        return ResolveBatch(*(a[0] for a in stacked))

    def pack_empty(self, base_version, commit_version, new_window_start):
        """A zero-txn pad batch (resolve_many's fixed-width padding).
        The zero arrays are immutable and version-independent, so ONE
        cached template serves every dispatch — only the cv/window
        scalars are swapped. Re-packing pads each backlog dispatch was
        measurable in the commit pipeline's pack stage."""
        if self._empty is None:
            self._empty = self.pack([], 0, 0, 0)
        return self._empty._replace(
            cv=np.uint32(commit_version - base_version),
            new_window_start=np.uint32(
                max(0, new_window_start - base_version)
            ),
        )

    def _normalize(self, txn):
        """Fold a txn whose op lists exceed the packed lanes: overflow
        point ops spill into the range lanes (a point op is a tiny
        range), and range overflow coalesces into a single covering
        range (conservative — can only add false conflicts)."""
        p = self.params
        preads = txn.point_reads
        pwrites = txn.point_writes
        rreads = txn.range_reads
        rwrites = txn.range_writes
        if len(preads) > p.point_reads:
            rreads = list(rreads) + [
                (k, k + b"\x00") for k in preads[p.point_reads :]
            ]
            preads = preads[: p.point_reads]
        if len(pwrites) > p.point_writes:
            rwrites = list(rwrites) + [
                (k, k + b"\x00") for k in pwrites[p.point_writes :]
            ]
            pwrites = pwrites[: p.point_writes]
        if len(rreads) > p.range_reads:
            if p.range_reads == 0:
                raise ValueError(
                    "txn has range/overflow reads but params.range_reads=0"
                )
            tail = rreads[p.range_reads - 1 :]
            rreads = list(rreads[: p.range_reads - 1]) + [
                (min(b for b, _ in tail), max(e for _, e in tail))
            ]
        if len(rwrites) > p.range_writes:
            if p.range_writes == 0:
                raise ValueError(
                    "txn has range/overflow writes but params.range_writes=0"
                )
            tail = rwrites[p.range_writes - 1 :]
            rwrites = list(rwrites[: p.range_writes - 1]) + [
                (min(b for b, _ in tail), max(e for _, e in tail))
            ]
        from foundationdb_tpu.resolver.skiplist import TxnRequest

        return TxnRequest(
            read_version=txn.read_version,
            point_reads=preads,
            point_writes=pwrites,
            range_reads=rreads,
            range_writes=rwrites,
        )

    def _pack_native(self, txns, base_version, commit_version,
                     new_window_start):
        """One C pass (native/packer.cpp pack_into) into freshly
        allocated arrays; None on lane overflow (numpy path normalizes).
        """
        p = self.params
        T, W = p.txns, p.key_width
        u32, i32 = np.uint32, np.int32
        zero_hash = u32(fnv_hash_np(np.zeros((1, W), u32))[0])
        rv = np.zeros(T, u32)
        txn_mask = np.zeros(T, bool)
        pr_key = np.zeros((T, p.point_reads, W), u32)
        pr_hash = np.full((T, p.point_reads), zero_hash, u32)
        pr_bucket = np.zeros((T, p.point_reads), i32)
        pr_mask = np.zeros((T, p.point_reads), bool)
        pw_key = np.zeros((T, p.point_writes, W), u32)
        pw_hash = np.full((T, p.point_writes), zero_hash, u32)
        pw_bucket = np.zeros((T, p.point_writes), i32)
        pw_mask = np.zeros((T, p.point_writes), bool)
        rr_b = np.zeros((T, p.range_reads, W), u32)
        rr_e = np.zeros((T, p.range_reads, W), u32)
        rr_lo = np.zeros((T, p.range_reads), i32)
        rr_hi = np.zeros((T, p.range_reads), i32)
        rr_mask = np.zeros((T, p.range_reads), bool)
        rw_b = np.zeros((T, p.range_writes, W), u32)
        rw_e = np.zeros((T, p.range_writes, W), u32)
        rw_lo = np.zeros((T, p.range_writes), i32)
        rw_hi = np.zeros((T, p.range_writes), i32)
        rw_mask = np.zeros((T, p.range_writes), bool)
        rc = self._native.pack_into(
            txns, base_version,
            (p.point_reads, p.point_writes, p.range_reads, p.range_writes),
            p.key_width - 1, p.bucket_bits,
            (rv, txn_mask,
             pr_key, pr_hash, pr_bucket, pr_mask,
             pw_key, pw_hash, pw_bucket, pw_mask,
             rr_b, rr_e, rr_lo, rr_hi, rr_mask,
             rw_b, rw_e, rw_lo, rw_hi, rw_mask),
        )
        if rc:
            return None
        return ResolveBatch(
            rv=rv, txn_mask=txn_mask,
            pr_hash=pr_hash, pr_key=pr_key, pr_bucket=pr_bucket,
            pr_mask=pr_mask,
            pw_hash=pw_hash, pw_key=pw_key, pw_bucket=pw_bucket,
            pw_mask=pw_mask,
            rr_b=rr_b, rr_e=rr_e, rr_lo=rr_lo, rr_hi=rr_hi, rr_mask=rr_mask,
            rw_b=rw_b, rw_e=rw_e, rw_lo=rw_lo, rw_hi=rw_hi, rw_mask=rw_mask,
            cv=np.uint32(commit_version - base_version),
            new_window_start=np.uint32(
                max(0, new_window_start - base_version)
            ),
        )

    def pack(self, txns, base_version, commit_version, new_window_start):
        """txns: list[TxnRequest] (resolver/skiplist.py), len <= params.txns.

        Versions are absolute; stored as uint32 offsets from base_version.
        Oversize per-txn conflict-range lists spill into the range lanes
        (a point op is just a tiny range), mirroring how the reference
        treats all conflict ranges as ranges.

        Vectorized: the per-txn walk only gathers (slot, key) pairs into
        flat lists; all limb encoding happens as four whole-batch
        frombuffer passes (KeyCodec.encode_*_batch) and one fancy-index
        scatter per lane. ~30x the per-key scalar-encode path — this is
        the proxy's host-side cost per batch, so it bounds sustainable
        e2e throughput.
        """
        p = self.params
        if len(txns) > p.txns:
            raise ValueError(f"batch of {len(txns)} exceeds capacity {p.txns}")
        if self._native is not None and isinstance(txns, list):
            try:
                batch = self._pack_native(txns, base_version, commit_version,
                                          new_window_start)
            except TypeError:
                batch = None  # e.g. bytearray keys; numpy path takes them
            if batch is not None:
                return batch
        T, W = p.txns, p.key_width
        u32 = np.uint32

        rv = np.zeros(T, u32)
        txn_mask = np.zeros(T, bool)
        pr_key = np.zeros((T, p.point_reads, W), u32)
        pr_mask = np.zeros((T, p.point_reads), bool)
        pw_key = np.zeros((T, p.point_writes, W), u32)
        pw_mask = np.zeros((T, p.point_writes), bool)
        rr_b = np.zeros((T, p.range_reads, W), u32)
        rr_e = np.zeros((T, p.range_reads, W), u32)
        rr_mask = np.zeros((T, p.range_reads), bool)
        rw_b = np.zeros((T, p.range_writes, W), u32)
        rw_e = np.zeros((T, p.range_writes, W), u32)
        rw_mask = np.zeros((T, p.range_writes), bool)

        n = len(txns)
        txn_mask[:n] = True
        if n:
            rv_abs = np.fromiter(
                (t.read_version for t in txns), dtype=np.int64, count=n
            )
            rv[:n] = np.clip(rv_abs - base_version, 0, 0xFFFFFFFF).astype(u32)

        # Per-txn op counts drive everything: overflow detection (rare —
        # only offending batches pay for normalization) and the flat
        # (txn, lane) slot indices, generated with repeat/cumsum instead
        # of Python loops.
        def counts():
            return (
                np.fromiter((len(x.point_reads) for x in txns), np.int64, count=n),
                np.fromiter((len(x.point_writes) for x in txns), np.int64, count=n),
                np.fromiter((len(x.range_reads) for x in txns), np.int64, count=n),
                np.fromiter((len(x.range_writes) for x in txns), np.int64, count=n),
            )

        prc, pwc, rrc, rwc = counts()
        if (
            prc.max(initial=0) > p.point_reads
            or pwc.max(initial=0) > p.point_writes
            or rrc.max(initial=0) > p.range_reads
            or rwc.max(initial=0) > p.range_writes
        ):
            txns = [self._normalize(t) for t in txns]
            prc, pwc, rrc, rwc = counts()

        pr_t, pr_i = _slots(prc)
        pw_t, pw_i = _slots(pwc)
        rr_t, rr_i = _slots(rrc)
        rw_t, rw_i = _slots(rwc)
        # single-pass key gathers; C-speed zip(*) unzips the range pairs
        pr_k = [k for x in txns for k in x.point_reads]
        pw_k = [k for x in txns for k in x.point_writes]
        rr_p = [r for x in txns for r in x.range_reads]
        rw_p = [r for x in txns for r in x.range_writes]
        rr_kb, rr_ke = (list(z) for z in zip(*rr_p)) if rr_p else ([], [])
        rw_kb, rw_ke = (list(z) for z in zip(*rw_p)) if rw_p else ([], [])

        # encode + scatter, one batched pass per lane
        if pr_k:
            pr_key[pr_t, pr_i] = self.codec.encode_lower_batch(pr_k)
            pr_mask[pr_t, pr_i] = True
        if pw_k:
            pw_key[pw_t, pw_i] = self.codec.encode_lower_batch(pw_k)
            pw_mask[pw_t, pw_i] = True
        if rr_kb:
            lo, hi = self.codec.encode_bounds_batch(rr_kb, rr_ke)
            rr_b[rr_t, rr_i] = lo
            rr_e[rr_t, rr_i] = hi
            rr_mask[rr_t, rr_i] = True
        if rw_kb:
            lo, hi = self.codec.encode_bounds_batch(rw_kb, rw_ke)
            rw_b[rw_t, rw_i] = lo
            rw_e[rw_t, rw_i] = hi
            rw_mask[rw_t, rw_i] = True

        return ResolveBatch(
            rv=rv,
            txn_mask=txn_mask,
            pr_hash=fnv_hash_np(pr_key),
            pr_key=pr_key,
            pr_bucket=bucket_of(pr_key, p.bucket_bits),
            pr_mask=pr_mask,
            pw_hash=fnv_hash_np(pw_key),
            pw_key=pw_key,
            pw_bucket=bucket_of(pw_key, p.bucket_bits),
            pw_mask=pw_mask,
            rr_b=rr_b,
            rr_e=rr_e,
            rr_lo=bucket_of(rr_b, p.bucket_bits),
            rr_hi=bucket_of(rr_e, p.bucket_bits),
            rr_mask=rr_mask,
            rw_b=rw_b,
            rw_e=rw_e,
            rw_lo=bucket_of(rw_b, p.bucket_bits),
            rw_hi=bucket_of(rw_e, p.bucket_bits),
            rw_mask=rw_mask,
            cv=np.uint32(commit_version - base_version),
            new_window_start=np.uint32(max(0, new_window_start - base_version)),
        )
