"""The Resolver role — batched MVCC conflict detection behind a backend knob.

Ref parity: fdbserver/Resolver.actor.cpp (resolveBatch). The commit proxy
hands a batch of transactions in arrival order; the resolver returns
per-txn statuses and remembers accepted writes for the MVCC window.

``resolver_backend="tpu"`` packs the batch to device arrays and runs
ops/conflict.py's jitted kernel (history buffers live on device and are
donated across steps — no host↔device copies of state, only the batch in
and T statuses out). ``"cpu"`` runs the exact host ConflictSet
(resolver/skiplist.py; later a C++ twin via native/).
"""

import jax
import numpy as np

from foundationdb_tpu.core.flatpack import FlatTxnBatch
from foundationdb_tpu.core.options import DEFAULT_KNOBS
from foundationdb_tpu.ops import conflict as ck
from foundationdb_tpu.resolver.packing import BatchPacker
from foundationdb_tpu.resolver.skiplist import CpuConflictSet
from foundationdb_tpu.utils import deviceprofile
from foundationdb_tpu.utils import metrics as metrics_mod
from foundationdb_tpu.utils import span as span_mod

COMMITTED, CONFLICT, TOO_OLD = ck.COMMITTED, ck.CONFLICT, ck.TOO_OLD

# resolve_many's fixed scan width: backlog dispatches pad to a multiple
# of this (server/batcher.py MAX_BACKLOG matches) so every backlog size
# shares one XLA compilation per variant; larger backlogs chunk into
# BACKLOG_B-sized scans rather than falling back to per-batch round
# trips (the overload case is exactly when batching matters most)
BACKLOG_B = 8

# Errors the Pallas-ring fallback handler is designed for: the kernel
# failed to build (Mosaic lowering) or to run (XLA runtime fault) on
# this backend. Anything else — packer bugs, shape errors from our own
# code — must propagate, NOT silently wipe the device conflict history.
# Mosaic's LoweringException is deliberately NOT imported here: an
# eager `from jax._src.pallas.mosaic.lowering import ...` at module
# import time partially initializes jax._src.pallas.pallas_call —
# registering its config flags, then dying on the circular init — after
# which ANY later `import jax.experimental.pallas` in the process fails
# with "Config option already defined". The module-origin check below
# classifies LoweringException (module starts with "jax") without ever
# naming the type.
_PALLAS_FALLBACK_ERRORS = (jax.errors.JaxRuntimeError, NotImplementedError)


def _is_pallas_fallback_error(e):
    """Module-origin check backs up the explicit type list: a private
    jax error class that moved between versions must still engage the
    fallback (a Mosaic failure that escapes here fails every commit
    forever), while errors raised by OUR code keep propagating."""
    if isinstance(e, _PALLAS_FALLBACK_ERRORS):
        return True
    mod = type(e).__module__ or ""
    return mod.startswith(("jax", "mosaic"))  # jaxlib too ("jax" prefix)


class ResolverDown(Exception):
    """This resolver process is dead; the proxy fails the batch
    not_committed and the cluster controller recruits a replacement."""


class ResolveHandle:
    """Deferred-sync result of a ``resolve_many`` dispatch.

    JAX dispatch is asynchronous: the scanned backlog kernel is enqueued
    on the device the moment ``resolve_many`` returns, but the statuses
    only need to exist on the host when the proxy's apply stage consumes
    them. Holding the un-materialized device arrays here lets the commit
    pipeline overlap device compute with the PREVIOUS group's tlog push
    and storage apply; ``wait()`` performs the one host sync
    (``np.asarray``) and unpacks per-batch status lists. Host backends
    (and fallback paths) resolve eagerly at dispatch — their handle just
    hands the finished result back."""

    __slots__ = ("_materialize", "_result")

    def __init__(self, materialize=None, result=None):
        self._materialize = materialize
        self._result = result

    def wait(self):
        if self._materialize is not None:
            self._result = self._materialize()
            self._materialize = None
        return self._result


def params_from_knobs(knobs, use_pallas=False, use_pallas_scan=False):
    """The one knobs→ResolverParams mapping (Resolver and MeshResolver
    must size their kernels identically or verdicts drift)."""
    return ck.ResolverParams(
        txns=knobs.batch_txn_capacity,
        point_reads=knobs.point_reads_per_txn,
        point_writes=knobs.point_writes_per_txn,
        range_reads=knobs.range_reads_per_txn,
        range_writes=knobs.range_writes_per_txn,
        key_width=knobs.key_limbs + 1,
        hash_bits=knobs.hash_table_bits,
        ring_capacity=knobs.range_ring_capacity,
        bucket_bits=knobs.coarse_buckets_bits,
        ring_partition_bits=knobs.ring_partition_bits,
        use_pallas=use_pallas,
        use_pallas_scan=use_pallas_scan,
    )


def fast_params_of(params):
    """The point-specialized variant's params: range lanes statically
    off, point writes still recorded into the coarse summary the full
    kernel's future range reads consult. None when the config has no
    range lanes to specialize away. Both Pallas routes are stripped:
    the point-only jnp step is a handful of gathers, and keeping the
    fallback machinery scoped to the FULL variant keeps its safety
    argument simple."""
    if not (params.range_reads or params.range_writes):
        return None
    return params._replace(
        range_reads=0, range_writes=0, use_pallas=False,
        use_pallas_scan=False, record_point_coarse=True,
    )


class Resolver:
    def __init__(self, knobs=DEFAULT_KNOBS, base_version=0):
        self.knobs = knobs
        self.backend = knobs.resolver_backend
        self.base_version = base_version
        self.alive = True
        self._init_metrics()
        # wall seconds spent inside resolve_many's device dispatch (the
        # scan call; for host backends, the eager resolve) — the batcher
        # subtracts this from its stage-A+B timer so stage_pack_ms
        # measures HOST PACKING and stage_dispatch_ms the dispatch
        self.dispatch_wall_s = 0.0
        # device-path profiler (utils/deviceprofile.py): per-dispatch
        # pad/bucket/fallback accounting. The cluster hands every
        # resolver its cluster-owned DeviceProfile via adopt_profile
        # (the PR-4 registry pattern) so history survives respawn.
        self.profile = deviceprofile.DeviceProfile("resolver")
        # The device kernel has dedicated point LANES, and the native
        # conflict set packs a split-out point key once with its end
        # span aliasing the same blob bytes — both want the proxy's
        # point/range split. The pure-python cpu backend treats a point
        # as the tiny range it is, so the proxy skips the split there
        # (it was the hottest line of the host commit pipeline).
        self.wants_point_split = self.backend in ("tpu", "native")
        # the flat columnar commit path (core/flatpack.py): the device
        # packer consumes limb blobs directly, and the native set reads
        # raw key bytes out of the same blobs; the pure-python cpu
        # backend sticks to byte-pair ranges
        self.accepts_flat = self.backend in ("tpu", "native")
        if self.backend == "tpu":
            pallas = getattr(knobs, "pallas_ring", "auto")
            use_pallas = pallas == "on" or (
                pallas == "auto" and jax.default_backend() == "tpu"
            )
            if getattr(knobs, "ring_partition_bits", 0) and pallas == "auto":
                # the Pallas kernel implements the FLAT ring; a
                # partitioned ring under "auto" downgrades to the jnp
                # lanes (an explicit "on" is rejected by validate_params)
                use_pallas = False
            # the fused accept kernel (ops/pallas_scan.py) subsumes the
            # ring kernel's lane when engaged; same tri-state, and
            # "auto" additionally gates off on ineligible static shapes
            # (partitioned ring, txn capacity beyond the kernel's tile
            # budget) — an explicit "on" leaves those to validate_params
            from foundationdb_tpu.ops.pallas_scan import MAX_TXNS
            scan_knob = getattr(knobs, "pallas_scan", "auto")
            use_pallas_scan = scan_knob == "on" or (
                scan_knob == "auto" and jax.default_backend() == "tpu"
            )
            if use_pallas_scan and scan_knob == "auto" and (
                    getattr(knobs, "ring_partition_bits", 0)
                    or knobs.batch_txn_capacity > MAX_TXNS):
                use_pallas_scan = False
            if use_pallas_scan:
                use_pallas = False  # mutually exclusive; scan wins
            self.params = params_from_knobs(
                knobs, use_pallas=use_pallas,
                use_pallas_scan=use_pallas_scan)
            self.packer = BatchPacker(self.params)
            self.state = ck.init_state(self.params)
            self._resolve = ck.make_resolve_fn(self.params)
            # Static specialization (the XLA idiom for workload shapes):
            # a second compiled variant with the range lanes statically
            # OFF serves batches that carry only point ops while no range
            # write has ever entered history — YCSB-shaped traffic never
            # pays the ring/coarse broadcast lanes. Both variants share
            # ResolverState (the fast one records the hash table AND the
            # coarse point summary, so a later range read through the
            # full kernel sees every point write it must conflict with).
            self._fast = None
            self._fast_params = fast_params_of(self.params)
            self._range_history = False
            if self._fast_params is not None:
                self._fast = (
                    BatchPacker(self._fast_params),
                    ck.make_resolve_fn(self._fast_params),
                )
            # scan fns for backlog dispatch (resolve_many), cached per
            # (variant, padded batch count) — each (fast, B) pair is one
            # XLA compilation
            self._scan_fns = {}
            # pad-width buckets: a backlog dispatch pads to the smallest
            # bucket that fits. Pad batches are pure wasted kernel
            # compute, so on an interpreter-hosted (cpu) device — where
            # a scan compile is cheap — small backlogs pay a fraction of
            # the fixed B=8 dispatch cost; on a real/tunneled TPU a scan
            # compile costs tens of seconds, so one bucket only. The
            # fused-kernel path extends the ladder to 16/32: the PR 8
            # bucket_histogram showed deep backlogs chunked into 8s pay
            # repeated dispatch overhead the single wider scan avoids,
            # and pad waste on the odd sizes stays bounded (gated by
            # BENCH_MODE=kernel_smoke's pad_waste_pct threshold).
            self._scan_pad_buckets = (
                ((2, 4, 8, 16, 32) if use_pallas_scan else (2, 4, BACKLOG_B))
                if jax.default_backend() == "cpu" else (BACKLOG_B,)
            )
            # deep-backlog chunk bound for resolve_many: the widest
            # bucket the ladder will pad to in one scan dispatch
            self._scan_max_backlog = self._scan_pad_buckets[-1]
        elif self.backend == "cpu":
            self.cset = CpuConflictSet()
            self.cset.window_start = base_version
        elif self.backend == "native":
            from foundationdb_tpu.native import NativeConflictSet

            self.cset = NativeConflictSet()
            if base_version:
                # windows only move forward; an empty resolve installs it
                self.cset.resolve([], 0, base_version)
        else:
            raise ValueError(f"unknown resolver_backend {self.backend!r}")
        self.adopt_profile(self.profile)  # attach the packer hooks

    def adopt_profile(self, profile):
        """Adopt a cluster-owned :class:`DeviceProfile` (the registry
        carryover pattern): fold whatever this instance already recorded
        into it, then point every capture site — including the packers'
        staging-ring hooks — at the shared object, so device-path
        history survives respawn / recovery / configure."""
        if profile is not getattr(self, "profile", None):
            mine = getattr(self, "profile", None)
            if mine is not None:
                profile.absorb(mine)
            self.profile = profile
        for p in (getattr(self, "packer", None),
                  self._fast[0] if getattr(self, "_fast", None) else None):
            if p is not None:
                p.profile = self.profile
        return self.profile

    def _init_metrics(self, registry=None):
        """Build (or adopt) the role registry + hot-path handles.
        Recruitment hands the replacement the dead instance's registry
        so resolver counters survive respawns without rewinding."""
        if registry is not None and registry is not getattr(
                self, "metrics", None):
            registry.absorb(self.metrics)
        self.metrics = registry if registry is not None \
            else metrics_mod.MetricsRegistry("resolver")
        self._m_batches = self.metrics.counter("resolve_batches")
        self._m_txns = self.metrics.counter("resolve_txns")
        self._m_backlogs = self.metrics.counter("backlog_dispatches")
        self._m_backlog_depth = self.metrics.gauge("backlog_depth")
        self._m_flat_fallbacks = self.metrics.counter("flat_fallbacks")
        self._m_pallas_fallbacks = self.metrics.counter("pallas_fallbacks")
        self._m_respawns = self.metrics.counter("respawns")

    def status(self):
        """This role's status RPC payload (leaf of the status doc)."""
        self.metrics.gauge("lanes").set(getattr(self, "n_lanes", 1))
        return {
            "alive": self.alive,
            "backend": self.backend,
            "lanes": getattr(self, "n_lanes", 1),
            "metrics": self.metrics.snapshot(),
        }

    def kill(self):
        """Process death: in-memory conflict history is gone; the
        replacement must fence pre-death read versions (ref: resolver
        failure forcing a recovery in the reference)."""
        self.alive = False

    def respawn(self, base_version):
        """A replacement of this resolver's own kind, fenced at
        ``base_version`` (the failure monitor's recruitment hook —
        subclasses recruit their own shape)."""
        new = type(self)(self.knobs, base_version=base_version)
        new._init_metrics(self.metrics)
        new.adopt_profile(self.profile)
        new._m_respawns.inc()
        return new

    def _make_scan_fn(self, use_fast):
        """Compile the multi-batch scan for resolve_many (subclasses
        swap in their mesh-sharded twin)."""
        params = self._fast_params if use_fast else self.params
        return ck.make_resolve_scan_fn(params)

    def _pad_bucket(self, nb):
        """Smallest scan pad width that fits ``nb`` batches."""
        for b in self._scan_pad_buckets:
            if nb <= b:
                return b
        return self._scan_pad_buckets[-1]

    def resolve(self, txns, commit_version, new_window_start):
        """txns: list[TxnRequest] (or a FlatTxnBatch — the columnar
        commit path) in arrival order → list of statuses."""
        if not self.alive:
            raise ResolverDown()
        self._m_batches.inc()
        self._m_txns.inc(len(txns))
        # HOST-side scan span (the proxy's ambient trace context): the
        # dispatch wall for this batch. Never inside a traced/jitted
        # region — FL004 keeps kernel code pure.
        ssp = span_mod.from_context("resolver.scan", span_mod.current(),
                                    txns=len(txns))
        try:
            return self._resolve_traced(txns, commit_version,
                                        new_window_start)
        finally:
            ssp.finish()

    def _resolve_traced(self, txns, commit_version, new_window_start):
        if isinstance(txns, FlatTxnBatch):
            return self._resolve_flat(txns, commit_version,
                                      new_window_start)
        if self.backend in ("cpu", "native"):
            prof = deviceprofile.enabled()
            pt0 = deviceprofile.now() if prof else 0.0
            out = self.cset.resolve(txns, commit_version, new_window_start)
            if prof:
                # host sets pack nothing: slots == live, zero pad waste
                self.profile.record_dispatch(
                    bucket=1, live_batches=1, live_txns=len(txns),
                    txn_slots=len(txns),
                    wall_s=deviceprofile.now() - pt0)
            return out
        self._maybe_rebase(commit_version)
        # base_version only ever advances to a past window start, so a read
        # version below it is too old by construction — reject on host
        # rather than letting the uint32 offset clamp to 0. Dropping these
        # txns from the batch is safe: they commit nothing.
        statuses = [None] * len(txns)
        live = []
        for i, t in enumerate(txns):
            if t.read_version < self.base_version:
                statuses[i] = TOO_OLD
            else:
                live.append((i, t))
        use_fast = self._pick_fast(t for _, t in live)
        packer, resolve_fn = self._fast if use_fast else (
            self.packer, self._resolve
        )
        for c in range(0, max(len(live), 1), self.params.txns):
            chunk = live[c : c + self.params.txns]
            batch = packer.pack(
                [t for _, t in chunk], self.base_version, commit_version, new_window_start
            )
            prof = deviceprofile.enabled()
            pt0 = deviceprofile.now() if prof else 0.0
            out = self._step_kernel(resolve_fn, batch, len(chunk),
                                    commit_version)
            if prof:
                # each chunk is one device step padded to a full
                # params.txns batch — the single-batch route's pad waste
                pp = self._fast_params if use_fast else self.params
                self.profile.record_dispatch(
                    bucket=1, live_batches=1, live_txns=len(chunk),
                    txn_slots=pp.txns,
                    transfer_bytes=sum(
                        int(x.nbytes) for x in jax.tree.leaves(batch)),
                    wall_s=deviceprofile.now() - pt0)
            if out is None:  # pallas fallback engaged: fenced restart
                for j in range(len(statuses)):
                    if statuses[j] is None:
                        statuses[j] = TOO_OLD
                return statuses
            self.profile.record_kernel_route(self._kernel_route(use_fast))
            for (i, _), s in zip(chunk, out):
                statuses[i] = s
        return statuses

    def _step_kernel(self, resolve_fn, batch, n, commit_version):
        """One threaded kernel step → statuses[:n], or None when the
        Pallas fallback engaged (the resolver restarted fenced and the
        caller must answer TOO_OLD)."""
        try:
            status, _accepted, self.state = resolve_fn(self.state, batch)
            # materialize INSIDE the try: dispatch is async, so a
            # kernel that compiles but faults at runtime only raises
            # here — outside, the fallback would never engage and
            # self.state would hold poisoned arrays
            return np.asarray(status)[:n].tolist()
        except Exception as e:
            if (not (self.params.use_pallas or self.params.use_pallas_scan)
                    or resolve_fn is not self._resolve
                    or not _is_pallas_fallback_error(e)):
                raise  # pallas only runs in the full variant; non-JAX
                # errors (packer bugs …) must not wipe device history
            self._engage_pallas_fallback(commit_version)
            return None

    def _engage_pallas_fallback(self, commit_version):
        """A Pallas kernel (ring lane or the fused scan) failed to
        build/run on this backend: fall back to the jnp path for the
        life of the resolver rather than failing every commit. The
        device history may be donated/poisoned by the failed dispatch,
        so restart fenced exactly like a recruited resolver — the
        in-flight batch (and any read version from before the fence)
        retries TOO_OLD with fresh reads."""
        from foundationdb_tpu.utils.trace import TraceEvent

        name = ("PallasScanFallback" if self.params.use_pallas_scan
                else "PallasRingFallback")
        TraceEvent(name, severity=30).detail(
            fenced_at=commit_version).log()
        self._m_pallas_fallbacks.inc()
        self.profile.record_fallback("pallas_to_jit")
        self.params = self.params._replace(use_pallas=False,
                                           use_pallas_scan=False)
        self._resolve = ck.make_resolve_fn(self.params)
        self._scan_fns = {}  # compiled scans baked the pallas step in
        self.state = ck.init_state(self.params)
        self.base_version = commit_version

    def _kernel_route(self, use_fast, scan=False):
        """Which per-batch step body actually serves this dispatch —
        the device profiler's kernel-route taxonomy. The fast variant
        strips both Pallas flags (fast_params_of); the multi-batch scan
        strips use_pallas (make_resolve_scan_fn) but keeps the fused
        scan kernel."""
        if not use_fast and self.params.use_pallas_scan:
            return "pallas_scan"
        if not use_fast and not scan and self.params.use_pallas:
            return "pallas_ring"
        return "jit"

    def _resolve_flat(self, flat, commit_version, new_window_start):
        """Resolve one columnar batch. The native set reads raw key
        bytes straight out of the blobs; the tpu path packs limb rows
        into the staging ring. Anything the flat lane can't serve —
        width mismatch, lane overflow, a too-old read version that the
        host must pre-filter — decodes to TxnRequests and rides the
        legacy path (rare by construction)."""
        if self.backend in ("native", "cpu"):
            prof = deviceprofile.enabled()
            pt0 = deviceprofile.now() if prof else 0.0
            if self.backend == "native":
                out = self.cset.resolve_flat(flat, commit_version,
                                             new_window_start)
            else:
                out = self.cset.resolve(flat.to_txn_requests(),
                                        commit_version, new_window_start)
            if prof:
                self.profile.record_dispatch(
                    bucket=1, live_batches=1, live_txns=len(flat),
                    txn_slots=len(flat),
                    wall_s=deviceprofile.now() - pt0)
            return out
        self._maybe_rebase(commit_version)
        cause = self._flat_fallback_cause(flat)
        if cause is not None:
            self._m_flat_fallbacks.inc()
            self.profile.record_fallback(cause)
            return self.resolve(flat.to_txn_requests(), commit_version,
                                new_window_start)
        use_fast = self._pick_fast_flat([flat])
        packer, resolve_fn = self._fast if use_fast else (
            self.packer, self._resolve
        )
        batch = packer.pack_flat(flat, self.base_version, commit_version,
                                 new_window_start)
        prof = deviceprofile.enabled()
        pt0 = deviceprofile.now() if prof else 0.0
        out = self._step_kernel(resolve_fn, batch, len(flat),
                                commit_version)
        if prof:
            pp = self._fast_params if use_fast else self.params
            self.profile.record_dispatch(
                bucket=1, live_batches=1, live_txns=len(flat),
                txn_slots=pp.txns,
                entries_live={"pr": int(flat.prc.sum()),
                              "pw": int(flat.pwc.sum()),
                              "rr": int(flat.rrc.sum()),
                              "rw": int(flat.rwc.sum())},
                entry_slots={"pr": pp.txns * pp.point_reads,
                             "pw": pp.txns * pp.point_writes,
                             "rr": pp.txns * pp.range_reads,
                             "rw": pp.txns * pp.range_writes},
                transfer_bytes=sum(
                    int(x.nbytes) for x in jax.tree.leaves(batch)),
                wall_s=deviceprofile.now() - pt0)
        if out is None:
            return [TOO_OLD] * len(flat)
        self.profile.record_kernel_route(self._kernel_route(use_fast))
        return out

    def _flat_fallback_cause(self, flat):
        """Why this flat batch cannot ride the columnar lane — the
        structured fallback_cause taxonomy behind the bare
        flat_fallbacks counter. None when it can: the predicate is
        exactly ``flat_fits and rv fresh`` (the legacy-route guard)."""
        if len(flat) and int(flat.rv.min()) < self.base_version:
            return "too_old_rv"
        if self.packer.flat_fits(flat):
            return None
        p = self.params
        if (len(flat) > p.txns
                or flat.prc.max(initial=0) > p.point_reads
                or flat.pwc.max(initial=0) > p.point_writes
                or flat.rrc.max(initial=0) > p.range_reads
                or flat.rwc.max(initial=0) > p.range_writes):
            return "over_capacity"
        return "flat_to_legacy"  # limb-width mismatch

    def _profile_lanes(self, statuses):
        """Per-lane dispatch-wall capture hook, called host-side at
        materialize time (never inside a traced fn — FL004). The
        single-device resolver is one implicit lane: nothing to record;
        MeshResolver overrides with the per-shard walls."""

    def _pick_fast(self, txns):
        """Whether the point-specialized variant may serve these txns
        (see __init__) — and the sticky _range_history update when a
        range write (or a point-write spill, which the packer records as
        ring history) appears."""
        if self._fast is None:
            return False
        point_only = True
        pr_cap = self.params.point_reads
        pw_cap = self.params.point_writes
        for t in txns:
            if t.range_writes or len(t.point_writes) > pw_cap:
                self._range_history = True
                point_only = False
                break
            if t.range_reads or len(t.point_reads) > pr_cap:
                point_only = False  # needs range lanes this batch
        return point_only and not self._range_history

    def _pick_fast_flat(self, flats):
        """_pick_fast's columnar twin — count maxima instead of per-txn
        walks. Callers route lane-overflowing batches to the legacy
        path first, so only range presence matters here."""
        if self._fast is None:
            return False
        point_only = True
        for f in flats:
            if f.rwc.max(initial=0) > 0:
                self._range_history = True
                point_only = False
                break
            if f.rrc.max(initial=0) > 0:
                point_only = False
        return point_only and not self._range_history

    def resolve_many(self, batches, lazy=False):
        """Resolve a BACKLOG of batches in one device dispatch.

        ``batches``: list of (txns, commit_version, new_window_start) in
        commit order. Semantically identical to calling :meth:`resolve`
        per batch (lax.scan threads the history with the same sequential
        dependency) but pays ONE host↔device round trip for the whole
        backlog — the difference between ~8 and ~60+ live batches/sec
        when the chip is behind a high-latency tunnel. The batch count
        is padded to a small power of two (empty batches commit nothing)
        so distinct backlog sizes share compilations.

        ``lazy=True`` returns a :class:`ResolveHandle` instead of the
        status lists: the device work is dispatched (history state is
        threaded at dispatch time, so a later dispatch still sees this
        one's writes) but the host sync is deferred to ``wait()`` — the
        commit pipeline's stage C. Dispatch-time failures (dead
        resolver, packer errors) still raise here; only the
        materialization moves.
        """
        if len(batches) > 1:
            self._m_backlogs.inc()
            self._m_backlog_depth.set(len(batches))
        ssp = span_mod.from_context("resolver.scan", span_mod.current())
        if ssp is not span_mod.NULL:
            # one scan span for the whole backlog dispatch (host-side
            # only — FL004 keeps kernel code pure). Ambient context is
            # cleared so the eager host route's per-batch resolve()
            # calls don't emit nested duplicates.
            ssp.attr(batches=len(batches),
                     txns=sum(len(t) for t, _, _ in batches))
            prior = span_mod.set_current(None)
            try:
                handle = self._dispatch_many(batches)
            finally:
                span_mod.set_current(prior)
                ssp.finish()
            return handle if lazy else handle.wait()
        handle = self._dispatch_many(batches)
        return handle if lazy else handle.wait()

    def _dispatch_many(self, batches):
        import time as _time

        if (self.backend != "tpu" or len(batches) <= 1
                or any(len(t) > self.params.txns for t, _, _ in batches)):
            # host backends / degenerate backlogs resolve eagerly — the
            # handle is already settled. The per-batch resolve() calls
            # own the dispatch accounting (one record per kernel step /
            # host scan), so nothing records here.
            t0 = _time.perf_counter()
            result = [self.resolve(t, cv, ws) for t, cv, ws in batches]
            self.dispatch_wall_s += _time.perf_counter() - t0
            return ResolveHandle(result=result)
        if len(batches) > self._scan_max_backlog:
            # Oversized backlog — the overload case this path exists for.
            # Chunk into max-bucket-wide scans (each one dispatch) instead
            # of collapsing to per-batch round trips: throughput stays
            # scan-bound, not RTT-bound, no matter how deep the queue.
            chunk_b = self._scan_max_backlog
            handles = [
                self._dispatch_many(batches[i:i + chunk_b])
                for i in range(0, len(batches), chunk_b)
            ]
            return ResolveHandle(materialize=lambda: [
                statuses for h in handles for statuses in h.wait()
            ])
        if not self.alive:
            raise ResolverDown()
        self._maybe_rebase(batches[-1][1])
        # the scanned paths below bypass resolve(): count their volume
        # here (the eager/host route above counts via resolve itself)
        self._m_batches.inc(len(batches))
        self._m_txns.inc(sum(len(t) for t, _, _ in batches))
        flats_present = any(
            isinstance(t, FlatTxnBatch) for t, _, _ in batches)
        if all(isinstance(t, FlatTxnBatch) for t, _, _ in batches):
            handle = self._dispatch_flat(batches)
            if handle is not None:
                return handle
            self._m_flat_fallbacks.inc()
            self.profile.record_fallback(next(
                (c for c in (self._flat_fallback_cause(t)
                             for t, _, _ in batches) if c),
                "flat_to_legacy"))
        elif flats_present:
            # flat batches interleaved with legacy requests: the whole
            # group must decode (one scan threads one history)
            self.profile.record_fallback("flat_to_legacy")
        # A mixed or flat-ineligible backlog decodes to the legacy path.
        # The decode is DISPATCH work: charge it to dispatch_wall_s so
        # the batcher's stage split doesn't land it in whichever stage
        # timer happens to be open (stage_pack_ms, before this fix).
        t_dec = _time.perf_counter()
        batches = [
            (t.to_txn_requests() if isinstance(t, FlatTxnBatch) else t,
             cv, ws)
            for t, cv, ws in batches
        ]
        if flats_present:
            self.dispatch_wall_s += _time.perf_counter() - t_dec
        per_batch = []
        all_live = []
        for txns, cv, ws in batches:
            statuses = [None] * len(txns)
            live = []
            for i, t in enumerate(txns):
                if t.read_version < self.base_version:
                    statuses[i] = TOO_OLD
                else:
                    live.append((i, t))
            per_batch.append((statuses, live, cv, ws))
            all_live.extend(t for _, t in live)
        use_fast = self._pick_fast(all_live)
        packer = self._fast[0] if use_fast else self.packer
        packed = [
            packer.pack([t for _, t in live], self.base_version, cv, ws)
            for statuses, live, cv, ws in per_batch
        ]
        # Pad to ONE fixed bucket: a scan compile costs tens of seconds
        # on a tunneled chip, so every backlog size must share the same
        # compilation (empty padding batches cost ~ms of device time —
        # noise against the round trip this dispatch saves; pads come
        # from the packer's cached template, not a fresh pack). The
        # flat path buckets instead (_dispatch_flat) — variable padded
        # shapes are part of its staging design. The fused-scan path
        # rides the full ladder both ways: deep backlogs pad up
        # (16/32) instead of chunking, shallow ones pad down (2/4) —
        # pad batches are whole wasted kernel launches there, and the
        # kernel ladder only widens on cpu where compiles are cheap.
        B = self._pad_bucket(len(packed))
        if not self.params.use_pallas_scan:
            B = max(BACKLOG_B, B)
        last_cv, last_ws = batches[-1][1], batches[-1][2]
        if len(packed) < B:
            pad = packer.pack_empty(self.base_version, last_cv, last_ws)
            packed.extend([pad] * (B - len(packed)))
        scan_fn = self._get_scan_fn(use_fast, B)
        stacked = jax.tree.map(lambda *xs: np.stack(xs), *packed)
        prof = deviceprofile.enabled()
        if prof:
            ent = {"pr": 0, "pw": 0, "rr": 0, "rw": 0}
            for t in all_live:
                ent["pr"] += len(t.point_reads)
                ent["pw"] += len(t.point_writes)
                ent["rr"] += len(t.range_reads)
                ent["rw"] += len(t.range_writes)
            pp = self._fast_params if use_fast else self.params
            xfer = sum(int(x.nbytes) for x in jax.tree.leaves(stacked))
            pt0 = deviceprofile.now()
        t0 = _time.perf_counter()
        try:
            self.state, st = scan_fn(self.state, stacked)
        except Exception as e:
            # the scan bakes the fused Pallas step into its body
            # (make_resolve_scan_fn strips only use_pallas): a lowering
            # error here engages the same fenced fallback as the
            # single-batch route, and the whole backlog answers TOO_OLD
            self.dispatch_wall_s += _time.perf_counter() - t0
            if (use_fast or not self.params.use_pallas_scan
                    or not _is_pallas_fallback_error(e)):
                raise
            self._engage_pallas_fallback(last_cv)
            return ResolveHandle(
                result=[[TOO_OLD] * len(s) for s, _, _, _ in per_batch])
        self.dispatch_wall_s += _time.perf_counter() - t0
        self.profile.record_kernel_route(
            self._kernel_route(use_fast, scan=True), n=len(per_batch))
        if prof:
            self.profile.record_dispatch(
                bucket=B, live_batches=len(per_batch),
                live_txns=len(all_live), txn_slots=B * pp.txns,
                entries_live=ent,
                entry_slots={"pr": B * pp.txns * pp.point_reads,
                             "pw": B * pp.txns * pp.point_writes,
                             "rr": B * pp.txns * pp.range_reads,
                             "rw": B * pp.txns * pp.range_writes},
                transfer_bytes=xfer,
                wall_s=deviceprofile.now() - pt0)

        def materialize():
            self._profile_lanes(st)
            rt0 = deviceprofile.now() if deviceprofile.enabled() else 0.0
            arr = np.asarray(st)  # the ONE host sync for the backlog
            if deviceprofile.enabled():
                self.profile.record_verdict_reduce(
                    deviceprofile.now() - rt0)
            out = []
            for b, (statuses, live, cv, ws) in enumerate(per_batch):
                row = arr[b][: len(live)].tolist()
                for (i, _), s in zip(live, row):
                    statuses[i] = s
                out.append(statuses)
            return out

        return ResolveHandle(materialize=materialize)

    def _get_scan_fn(self, use_fast, B):
        """The cached multi-batch scan for (variant, pad width) — a
        cache miss is an XLA compilation, recorded (with any later
        shape-driven retrace through ops/conflict.count_retraces) into
        the device profile's compile-cache accounting."""
        key = (use_fast, B)
        scan_fn = self._scan_fns.get(key)
        if scan_fn is None:
            scan_fn = ck.count_retraces(
                self._make_scan_fn(use_fast),
                lambda _sig, _k=key: self.profile.record_compile(_k),
                gate=deviceprofile.enabled,
            )
            self._scan_fns[key] = scan_fn
        return scan_fn

    def _dispatch_flat(self, batches):
        """The columnar backlog dispatch: the whole group packs into one
        stacked staging set (no per-batch ResolveBatch objects, no
        np.stack copy) and rides the same cached scan. None when any
        batch needs the legacy path (lane overflow, width mismatch, a
        too-old read version the host must pre-filter)."""
        flats = [t for t, _, _ in batches]
        for f in flats:
            if not self.packer.flat_fits(f) or (
                len(f) and int(f.rv.min()) < self.base_version
            ):
                return None
        use_fast = self._pick_fast_flat(flats)
        packer = self._fast[0] if use_fast else self.packer
        B = self._pad_bucket(len(flats))
        stacked = packer.pack_flat_group(
            flats, [(cv, ws) for _, cv, ws in batches],
            self.base_version, B=B,
        )
        scan_fn = self._get_scan_fn(use_fast, B)
        import time as _time

        prof = deviceprofile.enabled()
        if prof:
            pp = packer.params
            ent = {
                "pr": sum(int(f.prc.sum()) for f in flats),
                "pw": sum(int(f.pwc.sum()) for f in flats),
                "rr": sum(int(f.rrc.sum()) for f in flats),
                "rw": sum(int(f.rwc.sum()) for f in flats),
            }
            xfer = sum(int(x.nbytes) for x in jax.tree.leaves(stacked))
            pt0 = deviceprofile.now()
        t0 = _time.perf_counter()
        try:
            self.state, st = scan_fn(self.state, stacked)
        except Exception as e:
            # same fenced Pallas fallback as _dispatch_many's scan site
            self.dispatch_wall_s += _time.perf_counter() - t0
            if (use_fast or not self.params.use_pallas_scan
                    or not _is_pallas_fallback_error(e)):
                raise
            self._engage_pallas_fallback(batches[-1][1])
            return ResolveHandle(
                result=[[TOO_OLD] * len(f) for f in flats])
        self.dispatch_wall_s += _time.perf_counter() - t0
        self.profile.record_kernel_route(
            self._kernel_route(use_fast, scan=True), n=len(flats))
        if prof:
            self.profile.record_dispatch(
                bucket=B, live_batches=len(flats),
                live_txns=sum(len(f) for f in flats),
                txn_slots=B * pp.txns,
                entries_live=ent,
                entry_slots={"pr": B * pp.txns * pp.point_reads,
                             "pw": B * pp.txns * pp.point_writes,
                             "rr": B * pp.txns * pp.range_reads,
                             "rw": B * pp.txns * pp.range_writes},
                transfer_bytes=xfer,
                wall_s=deviceprofile.now() - pt0)

        def materialize():
            self._profile_lanes(st)
            rt0 = deviceprofile.now() if deviceprofile.enabled() else 0.0
            arr = np.asarray(st)  # the ONE host sync for the backlog
            if deviceprofile.enabled():
                self.profile.record_verdict_reduce(
                    deviceprofile.now() - rt0)
            return [
                arr[b][: len(f)].tolist() for b, f in enumerate(flats)
            ]

        return ResolveHandle(materialize=materialize)

    def _maybe_rebase(self, commit_version):
        """Keep uint32 version offsets in range (core/versions.py).

        Shifts the device state down by the current window start: entries
        clamped to 0 are exactly those no admissible read can conflict
        with anymore."""
        from foundationdb_tpu.core.versions import REBASE_THRESHOLD

        if commit_version - self.base_version < REBASE_THRESHOLD:
            return
        delta = int(jax.device_get(self.state.window_start))
        if delta == 0:
            raise RuntimeError(
                "version offsets exceed rebase threshold but the MVCC window "
                "never advanced; advance new_window_start to allow rebasing"
            )
        self.state = ck.rebase_state(self.state, delta)
        self.base_version += delta

    def window_start(self):
        if self.backend in ("cpu", "native"):
            return self.cset.window_start
        return self.base_version + int(jax.device_get(self.state.window_start))
