"""Subspace layer: a fixed key prefix + tuple-encoded suffixes.

Ref parity: bindings/python/fdb/subspace_impl.py behavior — a Subspace
scopes tuple keys under a raw prefix; sub[x] nests, range() spans the
contents, contains/unpack invert.
"""

from foundationdb_tpu.layers import tuple as fdbtuple


class Subspace:
    def __init__(self, prefix_tuple=(), raw_prefix=b""):
        self.raw_prefix = bytes(raw_prefix) + fdbtuple.pack(tuple(prefix_tuple))

    def key(self):
        return self.raw_prefix

    def pack(self, t=()):
        return fdbtuple.pack(tuple(t), prefix=self.raw_prefix)

    def pack_with_versionstamp(self, t):
        return fdbtuple.pack_with_versionstamp(tuple(t), prefix=self.raw_prefix)

    def unpack(self, key):
        key = bytes(key)
        if not self.contains(key):
            raise ValueError("key is not in subspace")
        return fdbtuple.unpack(key, prefix_len=len(self.raw_prefix))

    def range(self, t=()):
        return fdbtuple.range(tuple(t), prefix=self.raw_prefix)

    def contains(self, key):
        return bytes(key).startswith(self.raw_prefix)

    def as_foundationdb_key(self):
        return self.raw_prefix

    def subspace(self, t):
        return Subspace(tuple(t), self.raw_prefix)

    def __getitem__(self, item):
        return Subspace((item,), self.raw_prefix)

    def __eq__(self, other):
        return isinstance(other, Subspace) and self.raw_prefix == other.raw_prefix

    def __hash__(self):
        return hash(self.raw_prefix)

    def __repr__(self):
        return f"Subspace(raw_prefix={self.raw_prefix!r})"
