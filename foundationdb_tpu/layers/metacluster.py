"""Metacluster-lite: one MANAGEMENT cluster coordinating tenants across
several DATA clusters.

Ref parity: upstream ``metacluster/`` (MetaclusterManagement.actor.cpp
and the fdbcli metacluster commands) — a management cluster holds the
registry of data clusters and the tenant→cluster assignment; tenants are
created on the least-loaded data cluster with capacity, and a tenant can
be MOVED between data clusters. This lite keeps the same shapes with the
machinery this codebase already has: assignments live in the management
cluster's system keyspace, tenant CRUD delegates to ``layers/tenant.py``
on the owning data cluster, and a move fences in-flight transactions
through the tenant-map row every TenantTransaction reads conflictingly.

Move protocol (crash-resumable; each step is one transaction):
  1. management: assignment → ``moving`` (new ``open_tenant`` calls are
     refused with retryable 2144 tenant_locked);
  2. source: DELETE the tenant-map row — every in-flight tenant txn
     conflicts with (or re-resolves and misses) the row and fails, so
     the copy that follows reads a quiesced keyspace;
  3. copy the raw prefix rows to the destination under a freshly
     created tenant there (quota + group rows ride along);
  4. source: clear the raw data;
  5. management: assignment → ``ready`` on the destination.
``resume_move`` re-drives a move found mid-flight after a crash (the
management row says which step committed last).
"""

import json

from foundationdb_tpu.core.errors import FDBError, err
from foundationdb_tpu.core.keys import strinc
from foundationdb_tpu.layers.tenant import (
    TENANT_GROUP_PREFIX,
    TENANT_MAP_PREFIX,
    TENANT_QUOTA_PREFIX,
    Tenant,
    TenantManagement,
)

REGISTRATION_KEY = b"\xff/metacluster/registration"
DATA_CLUSTER_PREFIX = b"\xff/metacluster/dataCluster/"
TENANT_ASSIGN_PREFIX = b"\xff/metacluster/tenant/"


def _assign_key(name):
    return TENANT_ASSIGN_PREFIX + name


class Metacluster:
    """The management-cluster handle (ref: MetaclusterManagement).

    ``databases`` is the connection registry: cluster name → Database —
    the lite analog of the connection strings the reference stores in
    its data-cluster metadata."""

    def __init__(self, mgmt_db):
        reg = mgmt_db.run(lambda tr: tr.get(REGISTRATION_KEY))
        if reg is None or json.loads(reg)["role"] != "management":
            raise err("invalid_metacluster_operation")
        self.db = mgmt_db
        self.databases = {}

    # ── registration (ref: metacluster create_experimental / register) ──
    @classmethod
    def create(cls, mgmt_db, name=b"meta"):
        def txn(tr):
            if tr.get(REGISTRATION_KEY) is not None:
                raise err("cluster_already_registered")
            tr.set(REGISTRATION_KEY, json.dumps(
                {"role": "management", "name": name.decode("latin-1")}
            ).encode())

        mgmt_db.run(txn)
        return cls(mgmt_db)

    def register_data_cluster(self, name, db, capacity=100):
        """A data cluster must be tenant-free and not already part of a
        metacluster (ref: registerCluster's emptiness check).

        Two transactions on two clusters cannot be atomic, so the
        registry row commits FIRST in state "registering" (mirroring
        create_tenant's state machine), the data-side mark commits
        second, and only then does the row flip to "ready". A crash in
        the window leaves a resumable "registering" row — re-calling
        register_data_cluster picks up where the crash left off instead
        of failing cluster_already_registered until an operator runs
        remove_data_cluster — and create_tenant never assigns onto a
        cluster that hasn't reached "ready". A data cluster that
        REFUSES its mark (it belongs to another metacluster) still
        rolls the row back: nothing is half-joined."""
        name = bytes(name)
        if TenantManagement.list_tenants(db):
            raise err("cluster_not_empty")

        def txn(tr):
            key = DATA_CLUSTER_PREFIX + name
            row = tr.get(key)
            if row is not None:
                meta = json.loads(row)
                # rows from before the state field are fully registered
                if meta.get("state", "ready") != "registering":
                    raise err("cluster_already_registered")
                # crashed registration: resume it (refresh capacity to
                # this call's request; tenants is still 0 — the cluster
                # was never assignable)
                meta["capacity"] = capacity
                tr.set(key, json.dumps(meta).encode())
                return
            tr.set(key, json.dumps(
                {"capacity": capacity, "tenants": 0,
                 "state": "registering"}).encode())

        self.db.run(txn)

        def mark(tr):
            reg = tr.get(REGISTRATION_KEY)
            if reg is not None:
                meta = json.loads(reg)
                if (meta.get("role") == "data" and
                        meta.get("name", "").encode("latin-1") == name):
                    return  # our own mark from a crashed attempt
                raise err("cluster_already_registered")
            tr.set(REGISTRATION_KEY, json.dumps(
                {"role": "data", "name": name.decode("latin-1")}
            ).encode())

        try:
            db.run(mark)
        except FDBError:
            # the data cluster REFUSED its mark (already part of a
            # metacluster): undo the registry row — nothing half-joined.
            # Non-FDB failures (crash/outage shapes) deliberately leave
            # the "registering" row: a retry resumes it, exactly like a
            # process crash would have.
            self.db.run(
                lambda tr: tr.clear(DATA_CLUSTER_PREFIX + name))
            raise

        def ready(tr):
            key = DATA_CLUSTER_PREFIX + name
            meta = json.loads(tr.get(key))
            meta["state"] = "ready"
            tr.set(key, json.dumps(meta).encode())

        self.db.run(ready)
        self.databases[name] = db

    def attach_data_cluster(self, name, db):
        """Re-attach an ALREADY-registered data cluster's connection in
        a fresh process (the in-memory ``databases`` registry dies with
        the process; the registration marks don't) — what makes
        ``resume_move`` actually drivable after a crash."""
        name = bytes(name)
        if self.db.run(
            lambda tr: tr.get(DATA_CLUSTER_PREFIX + name)
        ) is None:
            raise err("invalid_metacluster_operation")
        reg = db.run(lambda tr: tr.get(REGISTRATION_KEY))
        if reg is None:
            raise err("invalid_metacluster_operation")
        meta = json.loads(reg)
        if meta["role"] != "data" or \
                meta["name"].encode("latin-1") != name:
            raise err("invalid_metacluster_operation")
        self.databases[name] = db

    def remove_data_cluster(self, name):
        name = bytes(name)

        def txn(tr):
            key = DATA_CLUSTER_PREFIX + name
            meta = tr.get(key)
            if meta is None:
                raise err("invalid_metacluster_operation")
            if json.loads(meta)["tenants"]:
                raise err("cluster_not_empty")
            tr.clear(key)

        self.db.run(txn)
        db = self.databases.pop(name, None)
        if db is not None:
            db.run(lambda tr: tr.clear(REGISTRATION_KEY))

    def list_data_clusters(self):
        rows = self.db.run(lambda tr: list(tr.get_range(
            DATA_CLUSTER_PREFIX, strinc(DATA_CLUSTER_PREFIX))))
        return {
            k[len(DATA_CLUSTER_PREFIX):]: json.loads(v) for k, v in rows
        }

    # ── tenants (ref: MetaclusterTenantManagement) ──
    def _data_db(self, name):
        db = self.databases.get(name)
        if db is None:
            raise err("invalid_metacluster_operation")
        return db

    def create_tenant(self, tenant_name, group=None):
        """Assign to the least-loaded data cluster with free capacity
        (ref: the assignment choosing a cluster with available tenant
        groups), record the assignment, create on the data cluster."""
        tenant_name = bytes(tenant_name)

        def assign(tr):
            existing = tr.get(_assign_key(tenant_name))
            if existing is not None:
                prior = json.loads(existing)
                if prior["state"] == "registering":
                    # a crashed create: resume onto the recorded
                    # cluster (capacity was already consumed)
                    return prior["cluster"].encode("latin-1")
                raise err("tenant_already_exists")
            rows = list(tr.get_range(
                DATA_CLUSTER_PREFIX, strinc(DATA_CLUSTER_PREFIX)))
            best, best_meta, best_load = None, None, None
            for k, v in rows:
                meta = json.loads(v)
                if meta.get("state", "ready") != "ready":
                    # mid-registration: its data-side mark may not
                    # exist yet — never assign tenants onto it
                    continue
                if meta["tenants"] >= meta["capacity"]:
                    continue
                load = meta["tenants"] / meta["capacity"]
                if best is None or load < best_load:
                    best = k[len(DATA_CLUSTER_PREFIX):]
                    best_meta, best_load = meta, load
            if best is None:
                raise err("metacluster_no_capacity")
            best_meta["tenants"] += 1
            tr.set(DATA_CLUSTER_PREFIX + best,
                   json.dumps(best_meta).encode())
            # "registering" until the data-side create lands (ref: the
            # reference's tenant-creation state machine): a crash
            # between the two transactions is resumable by re-calling
            # create_tenant, and open_tenant refuses the half-created
            # tenant retryably instead of handing out a 2108 handle
            tr.set(_assign_key(tenant_name), json.dumps(
                {"cluster": best.decode("latin-1"),
                 "state": "registering"}
            ).encode())
            return best

        cluster = self.db.run(assign)
        try:
            TenantManagement.create_tenant(
                self._data_db(cluster), tenant_name, group=group)
        except Exception as e:
            if getattr(e, "description", "") != "tenant_already_exists":
                raise  # assignment stays "registering": resumable
        self._set_assignment(tenant_name, cluster, "ready")
        return cluster

    def delete_tenant(self, tenant_name):
        tenant_name = bytes(tenant_name)
        assignment = self._assignment(tenant_name)
        if assignment["state"] in ("moving", "copied"):
            # a mid-move tenant has TWO partial copies: deleting the
            # registry row now would leak the source rows and leave the
            # destination copy to be silently resurrected by a later
            # same-name create (round-5 review). Finish the move first.
            raise err("tenant_locked")
        cluster = assignment["cluster"].encode("latin-1")
        try:
            TenantManagement.delete_tenant(
                self._data_db(cluster), tenant_name)
        except Exception as e:
            # a crashed earlier delete already removed the data-side
            # tenant: still clear the registry so the capacity slot and
            # assignment don't leak
            if getattr(e, "description", "") != "tenant_not_found":
                raise

        def txn(tr):
            tr.clear(_assign_key(tenant_name))
            key = DATA_CLUSTER_PREFIX + cluster
            meta = json.loads(tr.get(key))
            meta["tenants"] = max(0, meta["tenants"] - 1)
            tr.set(key, json.dumps(meta).encode())

        self.db.run(txn)

    def list_tenants(self):
        rows = self.db.run(lambda tr: list(tr.get_range(
            TENANT_ASSIGN_PREFIX, strinc(TENANT_ASSIGN_PREFIX))))
        return {
            k[len(TENANT_ASSIGN_PREFIX):]: json.loads(v) for k, v in rows
        }

    def _assignment(self, tenant_name):
        raw = self.db.run(lambda tr: tr.get(_assign_key(tenant_name)))
        if raw is None:
            raise err("tenant_not_found")
        return json.loads(raw)

    def open_tenant(self, tenant_name):
        """A Tenant handle on the owning data cluster. Mid-move the
        tenant is LOCKED: retryable 2144, retry after the move lands
        (ref: tenant_locked during metacluster moves)."""
        tenant_name = bytes(tenant_name)
        assignment = self._assignment(tenant_name)
        if assignment["state"] != "ready":
            raise err("tenant_locked")
        db = self._data_db(assignment["cluster"].encode("latin-1"))
        return Tenant(db, tenant_name)

    # ── tenant move (ref: metacluster/TenantMove shapes) ──
    # State machine, persisted in the management assignment row so a
    # crashed move is resumable without data loss:
    #   ready → moving (src_prefix recorded) → copied → ready@dst
    # The source's raw rows survive until AFTER the "copied" mark, so
    # re-driving the copy step always re-reads intact data.
    def move_tenant(self, tenant_name, dst_cluster):
        tenant_name = bytes(tenant_name)
        dst_cluster = bytes(dst_cluster)
        assignment = self._assignment(tenant_name)
        src_cluster = assignment["cluster"].encode("latin-1")
        if src_cluster == dst_cluster:
            return
        if assignment["state"] != "ready":
            raise err("invalid_metacluster_operation")
        dcs = self.list_data_clusters()
        if dst_cluster not in dcs:
            raise err("invalid_metacluster_operation")
        if dcs[dst_cluster]["tenants"] >= dcs[dst_cluster]["capacity"]:
            # same invariant create_tenant enforces (ref: the upstream
            # move refusing a destination without capacity)
            raise err("metacluster_no_capacity")
        src = self._data_db(src_cluster)
        src_prefix = src.run(
            lambda tr: tr.get(TENANT_MAP_PREFIX + tenant_name))
        if src_prefix is None:
            raise err("tenant_not_found")
        # the DESTINATION persists with the state mark: a resume must
        # finish THIS move, never re-target (a dst switch mid-flight
        # would strand a full copy on the original destination)
        self._set_assignment(tenant_name, src_cluster, "moving",
                             src_prefix=src_prefix, dst=dst_cluster)
        self._drive_move(tenant_name, src_cluster, dst_cluster)

    def resume_move(self, tenant_name, dst_cluster=None):
        """Re-drive a move found mid-flight after a crash: every step
        is idempotent, and the recorded src_prefix + destination +
        state mark say where to pick up. ``dst_cluster``, if given,
        must MATCH the recorded destination."""
        tenant_name = bytes(tenant_name)
        assignment = self._assignment(tenant_name)
        if assignment["state"] not in ("moving", "copied"):
            raise err("invalid_metacluster_operation")
        recorded = assignment["dst"].encode("latin-1")
        if dst_cluster is not None and bytes(dst_cluster) != recorded:
            raise err("invalid_metacluster_operation")
        self._drive_move(
            tenant_name, assignment["cluster"].encode("latin-1"),
            recorded,
        )

    def _set_assignment(self, tenant_name, cluster, state,
                        src_prefix=None, dst=None):
        payload = {"cluster": cluster.decode("latin-1"), "state": state}
        if src_prefix is not None:
            payload["src_prefix"] = src_prefix.decode("latin-1")
        if dst is not None:
            payload["dst"] = dst.decode("latin-1")

        self.db.run(lambda tr: tr.set(
            _assign_key(tenant_name), json.dumps(payload).encode()))

    def _drive_move(self, tenant_name, src_cluster, dst_cluster):
        src = self._data_db(src_cluster)
        dst = self._data_db(dst_cluster)
        assignment = self._assignment(tenant_name)
        src_prefix = assignment["src_prefix"].encode("latin-1")

        if assignment["state"] == "moving":
            # 2. fence the source: deleting the map row makes every
            # in-flight TenantTransaction's conflicting map-read fail,
            # so the rows copied below are the tenant's final state.
            # (Idempotent: the row may already be gone on a re-drive.)
            state = {}

            def fence(tr):
                state["quota"] = tr.get(TENANT_QUOTA_PREFIX + tenant_name)
                state["group"] = tr.get(TENANT_GROUP_PREFIX + tenant_name)
                if tr.get(TENANT_MAP_PREFIX + tenant_name) is not None:
                    tr.clear(TENANT_MAP_PREFIX + tenant_name)

            src.run(fence)

            # 3. create on the destination (idempotent) + install rows
            try:
                dst_prefix = TenantManagement.create_tenant(
                    dst, tenant_name, group=state["group"])
            except Exception as e:
                if getattr(e, "description", "") != \
                        "tenant_already_exists":
                    raise
                dst_prefix = dst.run(
                    lambda tr: tr.get(TENANT_MAP_PREFIX + tenant_name))
            rows = src.run(lambda tr: list(tr.get_range(
                src_prefix, strinc(src_prefix))))

            def install(tr):
                tr.clear_range(dst_prefix, strinc(dst_prefix))
                for k, v in rows:
                    tr.set(dst_prefix + k[len(src_prefix):], v)

            dst.run(install)
            if state["quota"] is not None:
                # through the management API so the destination's LIVE
                # ratekeeper limit engages, not just the persisted row
                TenantManagement.set_tenant_quota(
                    dst, tenant_name, float(state["quota"]))
            self._set_assignment(tenant_name, src_cluster, "copied",
                                 src_prefix=src_prefix, dst=dst_cluster)

        # 4. scrub the source's raw data (+ leftover tenant rows) —
        # only after "copied" is durable at the management cluster
        def scrub(tr):
            tr.clear_range(src_prefix, strinc(src_prefix))
            tr.clear(TENANT_QUOTA_PREFIX + tenant_name)
            tr.clear(TENANT_GROUP_PREFIX + tenant_name)

        src.run(scrub)
        from foundationdb_tpu.layers.tenant import tenant_tag

        if hasattr(src, "_cluster"):
            # release the source's live ratekeeper limit for the tenant
            src._cluster.set_tag_quota(tenant_tag(tenant_name), None)

        # 5. flip the assignment + per-cluster tenant counts
        def finish(tr):
            tr.set(_assign_key(tenant_name), json.dumps(
                {"cluster": dst_cluster.decode("latin-1"),
                 "state": "ready"}).encode())
            for cname, delta in ((src_cluster, -1), (dst_cluster, +1)):
                key = DATA_CLUSTER_PREFIX + cname
                meta = json.loads(tr.get(key))
                meta["tenants"] = max(0, meta["tenants"] + delta)
                tr.set(key, json.dumps(meta).encode())

        self.db.run(finish)
