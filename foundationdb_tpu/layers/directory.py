"""Directory layer: a filesystem-like hierarchy of short key prefixes.

Ref parity: bindings/python/fdb/directory_impl.py behavior, rebuilt from
the design doc (design/directory.md): a tree of named directories stored
in the node subspace (default ``\\xfe``), each assigned a short content
prefix by a windowed high-contention allocator (HCA); create/open/move/
remove/list with layer tags.

Metadata schema (all under node_subspace):
  node(prefix)[b'layer']        = layer tag bytes
  node(prefix)[SUBDIRS][name]   = child's content prefix
  root[b'version']              = struct <III (major, minor, micro)
  root[b'hca'][counters][w]     = allocation count in window starting w
  root[b'hca'][recent][c]       = candidate c claimed
"""

import struct

from foundationdb_tpu.core import deterministic
from foundationdb_tpu.core.keys import strinc
from foundationdb_tpu.layers import tuple as fdbtuple
from foundationdb_tpu.layers.subspace import Subspace

SUBDIRS = 0
VERSION = (1, 0, 0)


class HighContentionAllocator:
    """Windowed prefix allocator (ref: HCA in directory_impl.py).

    Counters track how many allocations each window start has seen; when a
    window is half-full the start advances. Candidates are drawn uniformly
    from the current window and claimed with a conflict-checked write, so
    concurrent allocators collide with probability ~count/window and
    retry cheaply — the OCC conflict detector is the mutex.
    """

    def __init__(self, subspace: Subspace):
        self.counters = subspace[0]
        self.recent = subspace[1]
        # candidate draws come from the injected stream: a seeded sim
        # allocates identical prefixes run after run (the HCA's window
        # draws are cluster-visible state), production stays OS-random
        self._rng = deterministic.rng("directory-hca")

    def allocate(self, tr):
        while True:
            start = 0
            kvs = tr.snapshot.get_range(*self.counters.range(), limit=1, reverse=True)
            if kvs:
                start = self.counters.unpack(kvs[0][0])[0]
            window_advanced = False
            while True:
                if window_advanced:
                    tr.clear_range(self.counters.key(), self.counters.pack((start,)))
                    tr.options.set_next_write_no_write_conflict_range()
                    tr.clear_range(self.recent.key(), self.recent.pack((start,)))
                tr.add(self.counters.pack((start,)), struct.pack("<q", 1))
                raw = tr.snapshot.get(self.counters.pack((start,)))
                count = struct.unpack("<q", raw)[0] if raw else 0
                window = self._window_size(start)
                if count * 2 < window:
                    break
                start += window
                window_advanced = True
            while True:
                candidate = start + self._rng.randrange(self._window_size(start))
                key = self.recent.pack((candidate,))
                # restart if another allocator advanced the window under us
                kvs = tr.snapshot.get_range(*self.counters.range(), limit=1, reverse=True)
                latest = self.counters.unpack(kvs[0][0])[0] if kvs else 0
                if latest > start:
                    break
                # conflicting read: two allocators claiming the same
                # candidate must OCC-conflict (one's write hits the
                # other's read) — a snapshot read here would let both
                # commit the same prefix
                if tr.get(key) is None:
                    tr.set(key, b"")
                    return fdbtuple.pack((candidate,))

    @staticmethod
    def _window_size(start):
        if start < 255:
            return 64
        if start < 65535:
            return 1024
        return 8192


class Directory:
    """A node in the directory hierarchy (shared impl of layer + subspace)."""

    def __init__(self, directory_layer, path=(), layer=b""):
        self._directory_layer = directory_layer
        self._path = tuple(path)
        self._layer = layer

    def get_path(self):
        return self._path

    def get_layer(self):
        return self._layer

    def _partition_and_rel(self, path):
        return self._directory_layer, self._path + _to_path(path)

    def create_or_open(self, tr, path, layer=None):
        dl, p = self._partition_and_rel(path)
        return dl.create_or_open(tr, p, layer)

    def open(self, tr, path, layer=None):
        dl, p = self._partition_and_rel(path)
        return dl.open(tr, p, layer)

    def create(self, tr, path, layer=None, prefix=None):
        dl, p = self._partition_and_rel(path)
        return dl.create(tr, p, layer, prefix)

    def list(self, tr, path=()):
        dl, p = self._partition_and_rel(path)
        return dl.list(tr, p)

    def move(self, tr, old_path, new_path):
        dl, _ = self._partition_and_rel(())
        return dl.move(tr, self._path + _to_path(old_path), self._path + _to_path(new_path))

    def move_to(self, tr, new_absolute_path):
        return self._directory_layer.move(tr, self._path, _to_path(new_absolute_path))

    def remove(self, tr, path=()):
        dl, p = self._partition_and_rel(path)
        return dl.remove(tr, p)

    def remove_if_exists(self, tr, path=()):
        dl, p = self._partition_and_rel(path)
        return dl.remove_if_exists(tr, p)

    def exists(self, tr, path=()):
        dl, p = self._partition_and_rel(path)
        return dl.exists(tr, p)


class DirectorySubspace(Directory, Subspace):
    """An opened directory: a Subspace over its content prefix plus the
    Directory navigation methods."""

    def __init__(self, path, prefix, directory_layer, layer=b""):
        Directory.__init__(self, directory_layer, path, layer)
        Subspace.__init__(self, (), prefix)

    def __repr__(self):
        return f"DirectorySubspace(path={self._path}, prefix={self.raw_prefix!r})"


PARTITION_LAYER = b"partition"


class DirectoryPartition(Directory):
    """A directory whose contents are an ISOLATED directory hierarchy.

    Ref parity: DirectoryPartition in bindings/python/fdb/directory_impl.py
    — created with ``layer=b"partition"``, it owns a child DirectoryLayer
    whose node subspace lives inside the partition's prefix
    (``prefix + \\xfe``), so the whole subtree (metadata AND contents) can
    be moved or removed as one unit from the parent hierarchy. Paths
    opened through the partition are RELATIVE to it and allocate from its
    own HCA; operations on the partition itself (exists/remove/move_to)
    route to the parent hierarchy. A partition is deliberately NOT a
    subspace — keys must live in directories created inside it.
    """

    def __init__(self, path, prefix, parent_layer):
        prefix = bytes(prefix)
        child = DirectoryLayer(
            node_subspace=Subspace(raw_prefix=prefix + b"\xfe"),
            content_subspace=Subspace(raw_prefix=prefix),
        )
        Directory.__init__(self, child, path, PARTITION_LAYER)
        self._parent_layer = parent_layer
        self.raw_prefix = prefix  # introspection only; packing is blocked

    def __repr__(self):
        return f"DirectoryPartition(path={self._path}, prefix={self.raw_prefix!r})"

    def _partition_and_rel(self, path):
        # contents operations (create/open/list) are relative to the
        # partition's own hierarchy — its root is the child layer's root
        return self._directory_layer, _to_path(path)

    def _self_or_rel(self, path):
        """exists/remove on an empty path target the partition ITSELF —
        a node of the PARENT hierarchy; deeper paths are child-relative."""
        p = _to_path(path)
        if not p:
            return self._parent_layer, self._path
        return self._directory_layer, p

    def exists(self, tr, path=()):
        dl, p = self._self_or_rel(path)
        return dl.exists(tr, p)

    def remove(self, tr, path=()):
        dl, p = self._self_or_rel(path)
        return dl.remove(tr, p)

    def remove_if_exists(self, tr, path=()):
        dl, p = self._self_or_rel(path)
        return dl.remove_if_exists(tr, p)

    def move(self, tr, old_path, new_path):
        # moves are within the partition's own hierarchy, relative paths
        return self._directory_layer.move(
            tr, _to_path(old_path), _to_path(new_path)
        )

    def move_to(self, tr, new_path_in_parent):
        """Relocate the partition itself within its PARENT hierarchy —
        the path is relative to the hierarchy the partition lives in
        (for a top-level partition that is the root layer; for a nested
        one, the enclosing partition). A partition can never move into a
        different hierarchy: its content prefix is a byte range of the
        parent's allocator."""
        return self._parent_layer.move(
            tr, self._path, _to_path(new_path_in_parent)
        )

    # ── a partition is not a content subspace (ref: the bindings raise) ──
    def _no_subspace(self, *_a, **_k):
        raise ValueError(
            "cannot open a key subspace in the root of a directory "
            "partition — create a directory inside it"
        )

    key = pack = unpack = range = contains = subspace = _no_subspace
    __getitem__ = _no_subspace


def _to_path(path):
    if isinstance(path, str):
        return (path,)
    return tuple(path)


class DirectoryLayer(Directory):
    def __init__(self, node_subspace=None, content_subspace=None, allow_manual_prefixes=False):
        Directory.__init__(self, self)
        self._node_subspace = node_subspace or Subspace(raw_prefix=b"\xfe")
        self._content_subspace = content_subspace or Subspace()
        self._allow_manual_prefixes = allow_manual_prefixes
        self._root_node = self._node_subspace[self._node_subspace.key()]
        self._allocator = HighContentionAllocator(self._root_node[b"hca"])

    # ────────────────────────── node helpers ───────────────────────────
    def _node_with_prefix(self, prefix):
        return self._node_subspace[bytes(prefix)]

    def _node_containing_key(self, tr, key):
        """Deepest existing directory whose content prefix contains key."""
        if key.startswith(self._node_subspace.key()):
            return self._root_node
        begin, _ = self._node_subspace.range(())
        kvs = tr.get_range(
            begin, self._node_subspace.pack((key,)) + b"\x00", limit=1, reverse=True
        )
        if kvs:
            prev_prefix = self._node_subspace.unpack(kvs[0][0])[0]
            if key.startswith(prev_prefix):
                return self._node_with_prefix(prev_prefix)
        return None

    def _find(self, tr, path):
        node = self._root_node
        for name in path:
            prefix = tr.get(node[SUBDIRS].pack((name,)))
            if prefix is None:
                return None
            node = self._node_with_prefix(prefix)
        return node

    def _route(self, tr, path):
        """Longest-prefix partition routing (ref: the bindings routing
        every operation through the deepest partition on its path): a
        path that TRAVERSES a partition delegates the remainder to the
        partition's own directory layer, whose metadata lives inside the
        partition prefix and is invisible to this layer's _find. Returns
        (directory_layer, relative_path); (self, path) when no partition
        is crossed. The final path element itself being a partition does
        NOT reroute — operations on the partition node (open/exists/
        remove/move of the partition) belong to THIS hierarchy."""
        node = self._root_node
        for i, name in enumerate(path[:-1]):
            prefix = tr.get(node[SUBDIRS].pack((name,)))
            if prefix is None:
                return self, path  # let the caller raise not-exists
            node = self._node_with_prefix(prefix)
            if (tr.get(node.pack((b"layer",))) or b"") == PARTITION_LAYER:
                part = self._contents_of_node(
                    node, path[: i + 1], PARTITION_LAYER
                )
                return part._directory_layer._route(tr, path[i + 1:])
        return self, path

    def _contents_of_node(self, node, path, layer=b""):
        prefix = self._node_subspace.unpack(node.key())[0]
        if layer == PARTITION_LAYER:
            return DirectoryPartition(path, prefix, self)
        return DirectorySubspace(path, prefix, self, layer)

    def _check_version(self, tr, write):
        raw = tr.get(self._root_node.pack((b"version",)))
        if raw is None:
            if write:
                tr.set(self._root_node.pack((b"version",)), struct.pack("<III", *VERSION))
            return
        major, _, _ = struct.unpack("<III", raw)
        if major > VERSION[0]:
            raise ValueError("directory layer written in a newer format version")

    # ─────────────────────────── operations ────────────────────────────
    def create_or_open(self, tr, path, layer=None):
        return self._create_or_open(tr, _to_path(path), layer, allow_open=True, allow_create=True)

    def open(self, tr, path, layer=None):
        return self._create_or_open(tr, _to_path(path), layer, allow_open=True, allow_create=False)

    def create(self, tr, path, layer=None, prefix=None):
        return self._create_or_open(
            tr, _to_path(path), layer, prefix=prefix, allow_open=False, allow_create=True
        )

    def _create_or_open(self, tr, path, layer, prefix=None, allow_open=True, allow_create=True):
        dl, rel = self._route(tr, path)
        if dl is not self:
            return dl._create_or_open(
                tr, rel, layer, prefix=prefix,
                allow_open=allow_open, allow_create=allow_create,
            )
        self._check_version(tr, write=False)
        if prefix is not None and not self._allow_manual_prefixes:
            raise ValueError("manual prefixes are not enabled on this DirectoryLayer")
        if not path:
            raise ValueError("the root directory cannot be opened")
        layer = layer or b""

        existing = self._find(tr, path)
        if existing is not None:
            if not allow_open:
                raise ValueError("the directory already exists")
            stored = tr.get(existing.pack((b"layer",))) or b""
            if layer and stored != layer:
                raise ValueError(
                    f"directory was created with incompatible layer {stored!r}"
                )
            return self._contents_of_node(existing, path, stored)

        if not allow_create:
            raise ValueError("the directory does not exist")
        self._check_version(tr, write=True)

        if prefix is None:
            prefix = self._content_subspace.key() + self._allocator.allocate(tr)
            if tr.get_range_startswith(prefix, limit=1):
                raise ValueError("the allocated prefix is not empty")
        if not self._is_prefix_free(tr, prefix):
            raise ValueError("the given prefix is already in use")

        if len(path) > 1:
            parent = self._create_or_open(tr, path[:-1], None)
            parent_node = self._node_with_prefix(parent.key())
        else:
            parent_node = self._root_node
        node = self._node_with_prefix(prefix)
        tr.set(parent_node[SUBDIRS].pack((path[-1],)), prefix)
        tr.set(node.pack((b"layer",)), layer)
        return self._contents_of_node(node, path, layer)

    def _is_prefix_free(self, tr, prefix):
        if not prefix:
            return False
        if self._node_containing_key(tr, prefix) is not None:
            return False
        begin = self._node_subspace.pack((prefix,))
        end = self._node_subspace.pack((strinc(prefix),))
        return not tr.get_range(begin, end, limit=1)

    def list(self, tr, path=()):
        self._check_version(tr, write=False)
        path = _to_path(path)
        dl, rel = self._route(tr, path)
        if dl is not self:
            return dl.list(tr, rel)
        node = self._find(tr, path)
        if node is None:
            raise ValueError("the directory does not exist")
        if path and (tr.get(node.pack((b"layer",))) or b"") == PARTITION_LAYER:
            # listing a partition's path lists its CONTENTS (child root)
            return self._contents_of_node(
                node, path, PARTITION_LAYER
            )._directory_layer.list(tr, ())
        sub = node[SUBDIRS]
        return [sub.unpack(k)[0] for k, _ in tr.get_range(*sub.range())]

    def exists(self, tr, path=()):
        self._check_version(tr, write=False)
        path = _to_path(path)
        dl, rel = self._route(tr, path)
        if dl is not self:
            return dl.exists(tr, rel)
        return self._find(tr, path) is not None

    def move(self, tr, old_path, new_path):
        self._check_version(tr, write=True)
        old_path, new_path = _to_path(old_path), _to_path(new_path)
        old_dl, old_rel = self._route(tr, old_path)
        new_dl, new_rel = self._route(tr, new_path)
        # routing builds fresh layer objects, so hierarchies compare by
        # their node-subspace prefix, not identity
        if old_dl._node_subspace.raw_prefix != new_dl._node_subspace.raw_prefix:
            # ref: the bindings refuse moves between partitions (the
            # content prefix cannot leave the partition's byte range)
            raise ValueError("cannot move between directory partitions")
        if old_dl is not self:
            return old_dl.move(tr, old_rel, new_rel)
        if new_path[: len(old_path)] == old_path:
            raise ValueError("cannot move a directory under itself")
        old_node = self._find(tr, old_path)
        if old_node is None:
            raise ValueError("the directory does not exist")
        if self._find(tr, new_path) is not None:
            raise ValueError("the directory already exists")
        parent_node = self._find(tr, new_path[:-1]) if len(new_path) > 1 else self._root_node
        if parent_node is None:
            raise ValueError("the directory does not exist")
        prefix = self._node_subspace.unpack(old_node.key())[0]
        tr.set(parent_node[SUBDIRS].pack((new_path[-1],)), prefix)
        self._remove_from_parent(tr, old_path)
        layer = tr.get(old_node.pack((b"layer",))) or b""
        return self._contents_of_node(old_node, new_path, layer)

    def remove(self, tr, path=()):
        if not self.remove_if_exists(tr, path):
            raise ValueError("the directory does not exist")
        return True

    def remove_if_exists(self, tr, path=()):
        self._check_version(tr, write=True)
        path = _to_path(path)
        if not path:
            raise ValueError("the root directory cannot be removed")
        dl, rel = self._route(tr, path)
        if dl is not self:
            return dl.remove_if_exists(tr, rel)
        node = self._find(tr, path)
        if node is None:
            return False
        self._remove_recursive(tr, node)
        self._remove_from_parent(tr, path)
        return True

    def _remove_recursive(self, tr, node):
        sub = node[SUBDIRS]
        for _, child_prefix in tr.get_range(*sub.range()):
            self._remove_recursive(tr, self._node_with_prefix(child_prefix))
        prefix = self._node_subspace.unpack(node.key())[0]
        tr.clear_range(prefix, strinc(prefix))  # contents
        b, e = self._node_subspace.range((prefix,))
        tr.clear_range(b, e)  # metadata
        tr.clear(self._node_subspace.pack((prefix,)))

    def _remove_from_parent(self, tr, path):
        parent = self._find(tr, path[:-1]) if len(path) > 1 else self._root_node
        tr.clear(parent[SUBDIRS].pack((path[-1],)))


directory = DirectoryLayer()
