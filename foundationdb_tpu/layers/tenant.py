"""Tenants: named, prefix-isolated keyspaces.

Ref parity: fdbclient/Tenant.h + TenantManagement.actor.h behavior — a
tenant is a name mapped to a short unique prefix; transactions opened on
a tenant see only their prefixed keyspace, with keys transparently
translated at the API boundary. Metadata lives in the system keyspace at
``\\xff/tenant/map/<name>`` (value = prefix, tuple-encoded id).

Round 3 adds the reference's management surface around the isolation:
- **tenant modes** (ref: TenantMode): ``optional`` (default), ``required``
  (non-tenant transactions may not touch user keys — 2130), ``disabled``
  (tenant-prefixed writes rejected — 2134); enforced structurally at the
  commit proxy by key prefix and persisted in ``\\xff/conf/tenant_mode``.
- **tenant quotas** (ref: the tenant quota system enforced through tag
  throttling): every tenant transaction auto-tags itself with a
  per-tenant transaction tag, so ``set_tenant_quota`` is exactly a
  ratekeeper tag quota — over-quota tenants see retryable 1213 while
  other tenants run at full rate. Quotas persist in
  ``\\xff/tenant/quota/<name>`` and are re-applied at recovery.
- **tenant groups** (ref: tenant groups in TenantMetadata): an optional
  label stored at ``\\xff/tenant/group/<name>`` for listing/placement.
"""

import hashlib

from foundationdb_tpu.core.errors import err
from foundationdb_tpu.core.keys import strinc
from foundationdb_tpu.layers import tuple as fdbtuple
from foundationdb_tpu.txn.database import retry_loop

TENANT_MAP_PREFIX = b"\xff/tenant/map/"
TENANT_ID_KEY = b"\xff/tenant/idcounter"
TENANT_DATA_PREFIX = b"\xfd"  # tenant content lives under \xfd<id>
TENANT_QUOTA_PREFIX = b"\xff/tenant/quota/"
TENANT_GROUP_PREFIX = b"\xff/tenant/group/"
TENANT_MODE_KEY = b"\xff/conf/tenant_mode"
TENANT_MODES = ("optional", "required", "disabled")


def tenant_tag(name):
    """The per-tenant transaction tag (stable, ≤16 bytes): quotas and
    busy-tenant throttling ride the ordinary tag throttler."""
    return "t/" + hashlib.sha256(bytes(name)).hexdigest()[:12]


class TenantManagement:
    """Static tenant CRUD (ref: TenantAPI in fdbclient)."""

    @staticmethod
    def create_tenant(db, name, group=None):
        name = bytes(name)
        if not name or name.startswith(b"\xff"):
            raise ValueError("tenant names must be non-empty and not start with \\xff")

        def txn(tr):
            # read the mode INSIDE the create txn: the conflicting read
            # serializes against a concurrent set_tenant_mode (no TOCTOU)
            if (tr.get(TENANT_MODE_KEY) or b"optional") == b"disabled":
                raise err("tenants_disabled")
            key = TENANT_MAP_PREFIX + name
            if tr.get(key) is not None:
                raise err("tenant_already_exists")
            raw = tr.get(TENANT_ID_KEY)
            tid = int.from_bytes(raw, "big") if raw else 0
            tr.set(TENANT_ID_KEY, (tid + 1).to_bytes(8, "big"))
            prefix = TENANT_DATA_PREFIX + fdbtuple.pack((tid,))
            tr.set(key, prefix)
            if group is not None:
                tr.set(TENANT_GROUP_PREFIX + name, bytes(group))
            return prefix

        return db.run(txn)

    @staticmethod
    def delete_tenant(db, name):
        name = bytes(name)

        def txn(tr):
            key = TENANT_MAP_PREFIX + name
            prefix = tr.get(key)
            if prefix is None:
                raise err("tenant_not_found")
            if tr.get_range(prefix, strinc(prefix), limit=1):
                raise err("tenant_not_empty")
            tr.clear(key)
            tr.clear(TENANT_GROUP_PREFIX + name)
            tr.clear(TENANT_QUOTA_PREFIX + name)

        db.run(txn)
        db._cluster.set_tag_quota(tenant_tag(name), None)

    @staticmethod
    def list_tenants(db, begin=b"", end=b"\xff", limit=0):
        def txn(tr):
            b = TENANT_MAP_PREFIX + bytes(begin)
            e = TENANT_MAP_PREFIX + bytes(end)
            return [
                (k[len(TENANT_MAP_PREFIX):], v)
                for k, v in tr.get_range(b, e, limit=limit)
            ]

        return db.run(txn)

    # ── modes (ref: TenantMode in DatabaseConfiguration) ──
    @staticmethod
    def set_tenant_mode(db, mode):
        if mode not in TENANT_MODES:
            raise err("invalid_option_value")

        def txn(tr):
            tr.set(TENANT_MODE_KEY, mode.encode())

        db.run(txn)
        db._cluster.set_tenant_mode(mode)  # live proxy enforcement

    @staticmethod
    def get_tenant_mode(db):
        raw = db.run(lambda tr: tr.get(TENANT_MODE_KEY))
        return raw.decode() if raw else "optional"

    # ── quotas (ref: the tenant quota keyspace + tag throttling) ──
    @staticmethod
    def set_tenant_quota(db, name, tps):
        """Per-tenant transaction rate limit; ``tps=None`` clears.
        Enforced by the ratekeeper's tag throttler against the tenant's
        auto-tag: over-quota tenant transactions see retryable 1213."""
        name = bytes(name)

        def txn(tr):
            if tr.get(TENANT_MAP_PREFIX + name) is None:
                raise err("tenant_not_found")
            if tps is None:
                tr.clear(TENANT_QUOTA_PREFIX + name)
            else:
                tr.set(TENANT_QUOTA_PREFIX + name, str(float(tps)).encode())

        db.run(txn)
        db._cluster.set_tag_quota(tenant_tag(name), tps)

    @staticmethod
    def get_tenant_quota(db, name):
        raw = db.run(lambda tr: tr.get(TENANT_QUOTA_PREFIX + bytes(name)))
        return float(raw) if raw else None

    # ── groups (ref: tenant groups in TenantMetadata) ──
    @staticmethod
    def get_tenant_group(db, name):
        return db.run(lambda tr: tr.get(TENANT_GROUP_PREFIX + bytes(name)))

    @staticmethod
    def list_tenant_groups(db):
        """{group: [tenant names]} for every grouped tenant."""
        rows = db.run(lambda tr: list(tr.get_range(
            TENANT_GROUP_PREFIX, strinc(TENANT_GROUP_PREFIX))))
        out = {}
        for k, g in rows:
            out.setdefault(g, []).append(k[len(TENANT_GROUP_PREFIX):])
        return out


class Tenant:
    """Handle to one tenant's keyspace (ref: Tenant in NativeAPI).

    The name→prefix mapping is resolved inside each transaction with a
    conflicting read of the tenant-map key, so a handle that outlives
    delete_tenant (or a delete+recreate) can never commit into a stale
    prefix — the map read either fails (tenant_not_found) or serializes
    against the management transaction."""

    def __init__(self, db, name):
        self._db = db
        self.name = bytes(name)

    def create_transaction(self):
        return TenantTransaction(self._db.create_transaction(), self.name)

    def run(self, fn):
        return retry_loop(self.create_transaction(), fn)

    transact = run

    def get(self, key):
        return self.run(lambda tr: tr.get(key))

    def set(self, key, value):
        self.run(lambda tr: tr.set(key, value))

    def clear(self, key):
        self.run(lambda tr: tr.clear(key))

    def get_range(self, begin, end, **kw):
        return self.run(lambda tr: tr.get_range(begin, end, **kw))

    def __getitem__(self, key):
        if isinstance(key, slice):
            return self.get_range(key.start, key.stop)
        return self.get(key)

    def __setitem__(self, key, value):
        self.set(key, value)


class TenantTransaction:
    """Key-translating view over a Transaction: user keys get the tenant
    prefix on the way in and lose it on the way out."""

    def __init__(self, tr, name):
        self._tr = tr
        self._name = name
        self._prefix = None  # resolved on first use, per txn attempt
        self.options = tr.options
        # auto-tag: quotas and busy-tenant throttling ride the ordinary
        # tag throttler (ref: tenant quotas enforced via tag throttling)
        self.options.set_tag(tenant_tag(name))

    @property
    def _p(self):
        if self._prefix is None:
            prefix = self._tr.get(TENANT_MAP_PREFIX + self._name)
            if prefix is None:
                raise err("tenant_not_found")
            self._prefix = prefix
        return self._prefix

    def _in(self, key):
        key = bytes(key)
        if key.startswith(b"\xff"):
            # system keys are not addressable through a tenant; allowing
            # them would also make the key invisible to full-range scans
            raise err("key_outside_legal_range")
        return self._p + key

    def _out(self, key):
        return bytes(key)[len(self._p):]

    def _in_end(self, key):
        """Exclusive end bound: clamp system-space ends to the tenant's
        upper edge instead of rejecting (an end bound is never accessed,
        and b'' .. b'\\xff' is the standard full-scan idiom)."""
        key = bytes(key)
        if key.startswith(b"\xff"):
            return strinc(self._p)
        return self._p + key

    def _range(self, begin, end):
        b = self._p if begin is None else self._in(begin)
        e = strinc(self._p) if end is None else self._in_end(end)
        return b, e

    # reads
    def get(self, key, snapshot=False):
        return self._tr.get(self._in(key), snapshot=snapshot)

    def get_range(self, begin, end, **kw):
        b, e = self._range(begin, end)
        return [(self._out(k), v) for k, v in self._tr.get_range(b, e, **kw)]

    def get_range_startswith(self, prefix, **kw):
        prefix = bytes(prefix)
        return self.get_range(prefix or None, strinc(prefix) if prefix else None, **kw)

    def get_read_version(self):
        return self._tr.get_read_version()

    def get_committed_version(self):
        return self._tr.get_committed_version()

    @property
    def snapshot(self):
        return _TenantSnapshot(self)

    # writes
    def set(self, key, value):
        self._tr.set(self._in(key), value)

    def clear(self, key):
        self._tr.clear(self._in(key))

    def clear_range(self, begin, end):
        b, e = self._range(begin, end)
        self._tr.clear_range(b, e)

    def add(self, key, param):
        self._tr.add(self._in(key), param)

    def min(self, key, param):
        self._tr.min(self._in(key), param)

    def max(self, key, param):
        self._tr.max(self._in(key), param)

    def byte_min(self, key, param):
        self._tr.byte_min(self._in(key), param)

    def byte_max(self, key, param):
        self._tr.byte_max(self._in(key), param)

    def bit_and(self, key, param):
        self._tr.bit_and(self._in(key), param)

    def bit_or(self, key, param):
        self._tr.bit_or(self._in(key), param)

    def bit_xor(self, key, param):
        self._tr.bit_xor(self._in(key), param)

    def compare_and_clear(self, key, param):
        self._tr.compare_and_clear(self._in(key), param)

    def append_if_fits(self, key, param):
        self._tr.append_if_fits(self._in(key), param)

    def add_read_conflict_key(self, key):
        self._tr.add_read_conflict_key(self._in(key))

    def add_write_conflict_key(self, key):
        self._tr.add_write_conflict_key(self._in(key))

    def add_read_conflict_range(self, begin, end):
        self._tr.add_read_conflict_range(self._in(begin), self._in_end(end))

    def add_write_conflict_range(self, begin, end):
        self._tr.add_write_conflict_range(self._in(begin), self._in_end(end))

    def watch(self, key):
        return self._tr.watch(self._in(key))

    # lifecycle
    def commit(self):
        self._tr.commit()

    def on_error(self, e):
        self._tr.on_error(e)
        self._prefix = None  # re-resolve after reset (mapping may change)
        self.options.set_tag(tenant_tag(self._name))  # reset drops tags

    def reset(self):
        self._tr.reset()
        self._prefix = None
        self.options.set_tag(tenant_tag(self._name))

    def cancel(self):
        self._tr.cancel()

    def __getitem__(self, key):
        if isinstance(key, slice):
            return self.get_range(key.start, key.stop)
        return self.get(key)

    def __setitem__(self, key, value):
        self.set(key, value)

    def __delitem__(self, key):
        if isinstance(key, slice):
            self.clear_range(key.start, key.stop)
        else:
            self.clear(key)


class _TenantSnapshot:
    def __init__(self, ttr):
        self._ttr = ttr

    def get(self, key):
        return self._ttr.get(key, snapshot=True)

    def get_range(self, begin, end, **kw):
        kw["snapshot"] = True
        return self._ttr.get_range(begin, end, **kw)
