"""Tuple layer: order-preserving encoding of typed tuples to keys.

Ref parity: the FDB tuple-encoding spec implemented by every binding
(design/tuple.md in the reference tree; bindings/python/fdb/tuple.py is
the behavioral model, re-implemented here from the wire spec). Encoded
bytes compare (as unsigned byte strings) exactly like the tuples compare
element-wise, which is what makes tuples usable as range-queryable keys.

Wire format (type code byte, then payload):
  0x00        null       (escaped as 00 FF inside nested tuples)
  0x01        bytes      payload with 00 -> 00 FF escaping, 00 terminator
  0x02        str        utf-8, same escaping/terminator
  0x05        nested     elements encoded recursively, 00 terminator
  0x0b        -bigint    length-complement byte, then complemented bytes
  0x0c..0x13  int < 0    8..1 payload bytes, value + 2^(8n) - 1 big-endian
  0x14        int == 0
  0x15..0x1c  int > 0    1..8 payload bytes, big-endian
  0x1d        +bigint    length byte, then bytes
  0x20        float32    big-endian IEEE with order-transform
  0x21        float64    big-endian IEEE with order-transform
  0x26/0x27   False/True
  0x30        UUID       16 raw bytes
  0x33        Versionstamp  12 bytes (10 txn + 2 user)
"""

import struct
import uuid as _uuid

from foundationdb_tpu.core.keys import strinc
from foundationdb_tpu.core.versions import Versionstamp

NULL_CODE = 0x00
BYTES_CODE = 0x01
STRING_CODE = 0x02
NESTED_CODE = 0x05
NEG_INT_START = 0x0B
INT_ZERO_CODE = 0x14
POS_INT_END = 0x1D
FLOAT_CODE = 0x20
DOUBLE_CODE = 0x21
FALSE_CODE = 0x26
TRUE_CODE = 0x27
UUID_CODE = 0x30
VERSIONSTAMP_CODE = 0x33

_size_limits = tuple((1 << (i * 8)) - 1 for i in range(9))


class SingleFloat:
    """Wrapper marking a value as 32-bit float (Python floats are doubles)."""

    __slots__ = ("value",)

    def __init__(self, value):
        self.value = struct.unpack(">f", struct.pack(">f", value))[0]

    def __eq__(self, other):
        return isinstance(other, SingleFloat) and self.value == other.value

    def __lt__(self, other):
        return self.value < other.value

    def __hash__(self):
        return hash(("SingleFloat", self.value))

    def __repr__(self):
        return f"SingleFloat({self.value})"


def _float_transform(raw, decode=False):
    """IEEE bits -> order-preserving bytes: negative numbers get all bits
    flipped, non-negative get the sign bit flipped (spec: total order incl.
    -0 < +0, and NaNs sort to the edges deterministically)."""
    if decode:
        if raw[0] & 0x80:
            return bytes(b ^ 0x80 if i == 0 else b for i, b in enumerate(raw))
        return bytes(b ^ 0xFF for b in raw)
    if raw[0] & 0x80:
        return bytes(b ^ 0xFF for b in raw)
    return bytes((raw[0] ^ 0x80,)) + raw[1:]


def _encode(value, nested=False):
    if value is None:
        return b"\x00\xff" if nested else b"\x00"
    if value is True:
        return bytes((TRUE_CODE,))
    if value is False:
        return bytes((FALSE_CODE,))
    if isinstance(value, (bytes, bytearray)):
        return bytes((BYTES_CODE,)) + bytes(value).replace(b"\x00", b"\x00\xff") + b"\x00"
    if isinstance(value, str):
        return bytes((STRING_CODE,)) + value.encode("utf-8").replace(b"\x00", b"\x00\xff") + b"\x00"
    if isinstance(value, int):
        return _encode_int(value)
    if isinstance(value, SingleFloat):
        return bytes((FLOAT_CODE,)) + _float_transform(struct.pack(">f", value.value))
    if isinstance(value, float):
        return bytes((DOUBLE_CODE,)) + _float_transform(struct.pack(">d", value))
    if isinstance(value, _uuid.UUID):
        return bytes((UUID_CODE,)) + value.bytes
    if isinstance(value, Versionstamp):
        return bytes((VERSIONSTAMP_CODE,)) + value.to_bytes()
    if isinstance(value, (tuple, list)):
        return (
            bytes((NESTED_CODE,))
            + b"".join(_encode(v, nested=True) for v in value)
            + b"\x00"
        )
    raise ValueError(f"unencodable tuple element of type {type(value).__name__}")


def _encode_int(v):
    if v == 0:
        return bytes((INT_ZERO_CODE,))
    if v > 0:
        if v > _size_limits[8]:  # bigint
            payload = v.to_bytes((v.bit_length() + 7) // 8, "big")
            if len(payload) > 255:
                raise ValueError("integer magnitude too large for tuple encoding")
            return bytes((POS_INT_END, len(payload))) + payload
        n = (v.bit_length() + 7) // 8
        return bytes((INT_ZERO_CODE + n,)) + v.to_bytes(n, "big")
    mag = -v
    if mag > _size_limits[8]:
        payload = mag.to_bytes((mag.bit_length() + 7) // 8, "big")
        if len(payload) > 255:
            raise ValueError("integer magnitude too large for tuple encoding")
        complemented = bytes(b ^ 0xFF for b in payload)
        return bytes((NEG_INT_START, len(payload) ^ 0xFF)) + complemented
    n = (mag.bit_length() + 7) // 8
    return bytes((INT_ZERO_CODE - n,)) + (v + _size_limits[n]).to_bytes(n, "big")


def _find_terminator(data, pos):
    """Index of the unescaped 0x00 terminator from ``pos``."""
    while True:
        idx = data.index(b"\x00", pos)
        if idx + 1 < len(data) and data[idx + 1] == 0xFF:
            pos = idx + 2
            continue
        return idx


def _decode(data, pos, nested=False):
    code = data[pos]
    if code == NULL_CODE:
        if nested:  # inside a nested tuple, null is 00 FF
            return None, pos + 2
        return None, pos + 1
    if code == BYTES_CODE or code == STRING_CODE:
        end = _find_terminator(data, pos + 1)
        raw = data[pos + 1 : end].replace(b"\x00\xff", b"\x00")
        return (raw if code == BYTES_CODE else raw.decode("utf-8")), end + 1
    if code == NESTED_CODE:
        out = []
        p = pos + 1
        while True:
            if data[p] == 0x00:
                if p + 1 < len(data) and data[p + 1] == 0xFF:
                    out.append(None)
                    p += 2
                    continue
                return tuple(out), p + 1
            v, p = _decode(data, p, nested=True)
            out.append(v)
    if code == NEG_INT_START:  # negative bigint
        n = data[pos + 1] ^ 0xFF
        payload = bytes(b ^ 0xFF for b in data[pos + 2 : pos + 2 + n])
        return -int.from_bytes(payload, "big"), pos + 2 + n
    if code == POS_INT_END:  # positive bigint
        n = data[pos + 1]
        return int.from_bytes(data[pos + 2 : pos + 2 + n], "big"), pos + 2 + n
    if NEG_INT_START < code < POS_INT_END:
        n = code - INT_ZERO_CODE
        if n == 0:
            return 0, pos + 1
        if n > 0:
            return int.from_bytes(data[pos + 1 : pos + 1 + n], "big"), pos + 1 + n
        n = -n
        raw = int.from_bytes(data[pos + 1 : pos + 1 + n], "big")
        return raw - _size_limits[n], pos + 1 + n
    if code == FLOAT_CODE:
        raw = _float_transform(data[pos + 1 : pos + 5], decode=True)
        return SingleFloat(struct.unpack(">f", raw)[0]), pos + 5
    if code == DOUBLE_CODE:
        raw = _float_transform(data[pos + 1 : pos + 9], decode=True)
        return struct.unpack(">d", raw)[0], pos + 9
    if code == FALSE_CODE:
        return False, pos + 1
    if code == TRUE_CODE:
        return True, pos + 1
    if code == UUID_CODE:
        return _uuid.UUID(bytes=bytes(data[pos + 1 : pos + 17])), pos + 17
    if code == VERSIONSTAMP_CODE:
        return Versionstamp.from_bytes(bytes(data[pos + 1 : pos + 13])), pos + 13
    raise ValueError(f"unknown tuple type code 0x{code:02x} at offset {pos}")


def pack(t, prefix=b""):
    """Encode tuple ``t`` to an order-preserving byte string."""
    return bytes(prefix) + b"".join(_encode(v) for v in t)


def unpack(key, prefix_len=0):
    """Decode a packed tuple (inverse of :func:`pack`)."""
    data = bytes(key)
    out = []
    pos = prefix_len
    while pos < len(data):
        v, pos = _decode(data, pos)
        out.append(v)
    return tuple(out)


def pack_with_versionstamp(t, prefix=b""):
    """Pack a tuple containing exactly one incomplete Versionstamp, with a
    4-byte little-endian offset trailer for SET_VERSIONSTAMPED_KEY.

    Ref: bindings' pack_with_versionstamp + MutationRef::SetVersionstampedKey
    (the last 4 bytes locate where the commit version is spliced in)."""
    packed = bytes(prefix)
    offset = None
    for v in t:
        if isinstance(v, Versionstamp) and not v.complete:
            if offset is not None:
                raise ValueError("tuple has multiple incomplete versionstamps")
            offset = len(packed) + 1  # skip the type code byte
        elif _contains_incomplete(v):
            raise ValueError("incomplete versionstamp in nested tuple unsupported")
        packed += _encode(v)
    if offset is None:
        raise ValueError("tuple has no incomplete versionstamp")
    return packed + struct.pack("<I", offset)


def _contains_incomplete(v):
    if isinstance(v, Versionstamp) and not v.complete:
        return True
    if isinstance(v, (tuple, list)):
        return any(_contains_incomplete(x) for x in v)
    return False


def has_incomplete_versionstamp(t):
    return _contains_incomplete(tuple(t))


def range(t, prefix=b""):  # noqa: A001 — binding-parity name
    """(begin, end) spanning all keys that are extensions of tuple ``t``."""
    p = pack(t, prefix)
    return p + b"\x00", p + b"\xff"


def range_startswith(prefix):
    prefix = bytes(prefix)
    return prefix, strinc(prefix)


def compare(a, b):
    """Tuple comparison via the encoding (total order incl. mixed types)."""
    ka, kb = pack(a), pack(b)
    return (ka > kb) - (ka < kb)
