"""Framed TCP transport with multiplexed request/reply endpoints.

Ref parity: fdbrpc/FlowTransport.actor.cpp — connections carry
length-prefixed packets addressed to endpoint tokens; replies are matched
to requests by id; a connection failure fails every outstanding request
on it. The reference multiplexes actor futures over one socket per peer;
here a reader thread per connection completes `concurrent.futures`
futures, and server handlers run on a shared pool so a blocking endpoint
(a watch wait, a batched GRV) never stalls the socket.

Frame: 4-byte big-endian length + wire payload.
Request: ("q", seq, method, args-tuple)  Reply: ("r", seq, ok, payload).

Authentication: with a shared ``secret`` configured, every connection
starts with a challenge/response — the server sends a random nonce, the
client must answer HMAC-SHA256(secret, nonce) before any request is
read (ref: FlowTransport's TLS handshake gating endpoint access; ours
is a shared-secret MAC rather than certificates). Without a secret the
transport is open: listening on a non-loopback interface without one
exposes full read/write/management access and is unsafe.
"""

import hashlib
import hmac
import os
import socket
import struct
import threading
import time

from concurrent.futures import Future, ThreadPoolExecutor

from foundationdb_tpu.core.errors import FDBError
from foundationdb_tpu.rpc import wire
from foundationdb_tpu.utils import lockdep
from foundationdb_tpu.utils import span as span_mod
from foundationdb_tpu.utils.trace import SEV_ERROR, TraceEvent

MAX_FRAME = 64 * 1024 * 1024
_AUTH_CONTEXT = b"fdbtpu-rpc-auth-v1:"
_AUTH_HANDSHAKE_TIMEOUT_S = 5.0
# deadline-sweep cadence: the client reader blocks in recv at most this
# long before checking outstanding requests against their deadlines, so
# a wedged peer costs one deadline + one tick, never a hung thread
_DEADLINE_TICK_S = 0.05
# consecutive deadline sweeps (with zero frames received in between)
# after which a connection is presumed black-holed rather than slow:
# callers close it and reconnect on a fresh socket instead of paying
# the full deadline again on a link that will never answer
WEDGED_STRIKE_LIMIT = 3

# Chaos transport hook (rpc/chaos.py): when armed, every NEW client
# socket is wrapped in the seeded fault injector. None on the default
# path — chaos code is never even imported unless a seed arms it via
# chaos.arm()/the rpc_chaos_seed knob/FDB_TPU_CHAOS_SEED.
SOCKET_WRAP = None


def _socket_wrap():
    global SOCKET_WRAP
    if SOCKET_WRAP is None:
        seed = os.environ.get("FDB_TPU_CHAOS_SEED")
        if seed:
            from foundationdb_tpu.rpc import chaos

            chaos.arm(seed)  # sets SOCKET_WRAP
    return SOCKET_WRAP


class DeadlineExceeded(TimeoutError):
    """A request outlived its deadline; the connection itself is fine.

    The service layer maps this by RPC class: commit-class calls become
    ``commit_unknown_result`` (1021 — the txn MAY have committed),
    read/GRV/admin calls become plainly retryable errors.
    """

    def __init__(self, method, deadline_s, address=""):
        super().__init__(
            f"rpc {method!r} to {address or '?'} exceeded its "
            f"{deadline_s:.3f}s deadline"
        )
        self.method = method
        self.deadline_s = deadline_s
        self.address = address


def _auth_proof(secret, nonce):
    if isinstance(secret, str):
        secret = secret.encode()
    return hmac.new(secret, _AUTH_CONTEXT + nonce, hashlib.sha256).digest()


class ConnectionLost(ConnectionError):
    """The peer vanished with requests outstanding."""


def _send_frame(sock, lock, payload: bytes):
    if len(payload) > MAX_FRAME:
        raise ValueError(f"frame too large: {len(payload)}")
    msg = struct.pack(">I", len(payload)) + payload
    with lock:
        # this per-socket lock EXISTS to serialize whole-frame sends —
        # interleaved partial frames would corrupt the stream; nothing
        # else is ever guarded by it, so no convoy can form
        sock.sendall(msg)  # flowlint: disable=FL003


def _recv_exact(sock, n):
    parts = []
    while n:
        chunk = sock.recv(n)
        if not chunk:
            raise ConnectionLost("peer closed")
        parts.append(chunk)
        n -= len(chunk)
    return b"".join(parts)


def _recv_frame(sock):
    (n,) = struct.unpack(">I", _recv_exact(sock, 4))
    if n > MAX_FRAME:
        raise ConnectionLost(f"oversized frame: {n}")
    return _recv_exact(sock, n)


class _FrameReader:
    """Buffered frame reader that survives ``socket.timeout`` mid-frame.

    The client reader runs its socket with a short timeout so it can
    sweep request deadlines between frames. ``_recv_exact`` would LOSE
    partially-received bytes on a timeout and desync the stream; this
    reader keeps partial state across ticks, so a timeout is always a
    clean "nothing complete yet — go sweep" signal.
    """

    def __init__(self, sock):
        self._sock = sock
        self._buf = bytearray()
        self._need = None  # payload length once the header is parsed

    def recv_frame(self):
        while True:
            if self._need is None and len(self._buf) >= 4:
                (n,) = struct.unpack(">I", bytes(self._buf[:4]))
                if n > MAX_FRAME:
                    raise ConnectionLost(f"oversized frame: {n}")
                del self._buf[:4]
                self._need = n
            if self._need is not None and len(self._buf) >= self._need:
                payload = bytes(self._buf[: self._need])
                del self._buf[: self._need]
                self._need = None
                return payload
            chunk = self._sock.recv(65536)  # may raise socket.timeout
            if not chunk:
                raise ConnectionLost("peer closed")
            self._buf += chunk


class RpcServer:
    """Listens for connections; dispatches requests to named handlers.

    ``handlers`` is the endpoint table: method name → callable(*args).
    A handler raising FDBError sends the error to the client intact
    (the client re-raises it); any other exception becomes a generic
    remote failure string.
    """

    def __init__(self, host, port, handlers, max_workers=16,
                 long_methods=(), secret=None):
        self.secret = secret
        self.handlers = dict(handlers)
        # endpoints that legitimately block (watch waits) run on their
        # own pool so parked waiters cannot starve short RPCs
        self.long_methods = set(long_methods)
        self._listener = socket.create_server(
            (host, port), reuse_port=False, backlog=64
        )
        self._listener.settimeout(0.2)
        self.host, self.port = self._listener.getsockname()[:2]
        self._pool = ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="rpc-handler"
        )
        self._long_pool = (
            ThreadPoolExecutor(
                max_workers=256, thread_name_prefix="rpc-blocking"
            )
            if self.long_methods
            else None
        )
        self._conns = set()
        self._lock = lockdep.lock("RpcServer._lock")
        self._closed = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="rpc-accept", daemon=True
        )
        self._accept_thread.start()

    @property
    def address(self):
        return f"{self.host}:{self.port}"

    def add_handlers(self, handlers, long_methods=()):
        """Register more endpoints on a live server (an fdbserver process
        brings its coordinator endpoints up first so peers can reach the
        quorum, then attaches the cluster service after recovery).

        Long-method routing is installed BEFORE the handlers become
        callable: a blocking endpoint must never be reachable while it
        would still dispatch onto the short-RPC pool."""
        new_long = set(long_methods) - self.long_methods
        if new_long:
            if self._long_pool is None:
                self._long_pool = ThreadPoolExecutor(
                    max_workers=256, thread_name_prefix="rpc-blocking"
                )
            self.long_methods |= new_long
        self.handlers.update(handlers)

    def _accept_loop(self):
        while not self._closed.is_set():
            try:
                sock, peer = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._conns.add(sock)
            threading.Thread(
                target=self._serve_conn, args=(sock, peer),
                name=f"rpc-conn-{peer}", daemon=True,
            ).start()

    def _authenticate(self, sock, send_lock, peer):
        """Challenge/response before the first request frame. The
        handshake runs under a timeout so an idle port-scanner cannot
        park a connection thread forever."""
        # crypto material must NOT come from the seeded determinism
        # registry: a replayable nonce is a replayable handshake
        nonce = os.urandom(16)  # flowlint: disable=FL001
        _send_frame(sock, send_lock, nonce)
        sock.settimeout(_AUTH_HANDSHAKE_TIMEOUT_S)
        try:
            # pre-auth frames are capped at the proof size (32 bytes):
            # an unauthenticated peer must not be able to make us buffer
            # a MAX_FRAME allocation before the HMAC check rejects it
            (n,) = struct.unpack(">I", _recv_exact(sock, 4))
            if n > 64:
                raise ConnectionLost(f"oversized auth proof: {n}")
            proof = _recv_exact(sock, n)
        finally:
            sock.settimeout(None)
        if not hmac.compare_digest(proof, _auth_proof(self.secret, nonce)):
            TraceEvent("RpcAuthFailed", severity=30).detail(
                peer=str(peer)).log()
            raise ConnectionLost("authentication failed")
        # confirmation frame: the client learns its proof was accepted
        # before sending requests, so a secret mismatch surfaces as a
        # deterministic handshake failure, not a later dead socket
        _send_frame(sock, send_lock, b"\x00ok")

    def _serve_conn(self, sock, peer):
        send_lock = lockdep.lock("RpcServer._serve_conn.send_lock")
        try:
            if self.secret is not None:
                self._authenticate(sock, send_lock, peer)
            while not self._closed.is_set():
                frame = _recv_frame(sock)
                msg = wire.loads(frame)
                # protocol v5: an optional TRACING frame rides as a 5th
                # element (the caller's SpanContext); shorter tuples are
                # the untraced form — peers ignore what isn't there
                kind, seq, method, args = msg[0], msg[1], msg[2], msg[3]
                trace_ctx = msg[4] if len(msg) > 4 else None
                if kind != "q":
                    raise ConnectionLost(f"unexpected message kind {kind!r}")
                pool = (
                    self._long_pool
                    if self._long_pool is not None
                    and method in self.long_methods
                    else self._pool
                )
                pool.submit(
                    self._dispatch, sock, send_lock, seq, method, args,
                    trace_ctx,
                )
        except (ConnectionLost, ConnectionError, OSError, ValueError):
            pass
        finally:
            with self._lock:
                self._conns.discard(sock)
            try:
                sock.close()
            except OSError:
                pass

    def _dispatch(self, sock, send_lock, seq, method, args,
                  trace_ctx=None):
        prior_ctx = None
        if trace_ctx is not None:
            # install the caller's SpanContext as this handler thread's
            # ambient context: role code (grv grant, storage reads)
            # opens child spans off span.current() without every
            # handler signature growing a tracing parameter
            prior_ctx = span_mod.set_current(tuple(trace_ctx))
        try:
            fn = self.handlers.get(method)
            if fn is None:
                raise KeyError(f"no such endpoint: {method}")
            result = fn(*args)
            reply = wire.dumps(("r", seq, True, result))
        except FDBError as e:
            reply = wire.dumps(("r", seq, False, e))
        except Exception as e:  # generic remote failure
            # the client only receives a flattened string — the server
            # trace is the record with the real type/context (FL005)
            TraceEvent("RpcHandlerError", severity=SEV_ERROR).detail(
                method=method, etype=type(e).__name__,
                error=str(e)[:200]).log()
            reply = wire.dumps(("r", seq, False, f"{type(e).__name__}: {e}"))
        finally:
            if trace_ctx is not None:
                span_mod.set_current(prior_ctx)
        try:
            _send_frame(sock, send_lock, reply)
        except (ConnectionError, OSError):
            pass  # client vanished; nothing to tell it
        except ValueError:
            # reply exceeds MAX_FRAME: the client must still get an answer
            # or its future hangs forever — send the error instead
            try:
                _send_frame(sock, send_lock, wire.dumps((
                    "r", seq, False,
                    f"ValueError: reply to {method} exceeds frame limit",
                )))
            except (ConnectionError, OSError, ValueError):
                pass

    def close(self):
        self._closed.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        self._pool.shutdown(wait=False)
        if self._long_pool is not None:
            self._long_pool.shutdown(wait=False)
        self._accept_thread.join(timeout=2)


class RemoteError(RuntimeError):
    """A non-FDBError exception raised inside a remote handler."""


class RpcClient:
    """One connection to an RpcServer; thread-safe, multiplexed calls."""

    def __init__(self, host, port, connect_timeout=5.0, secret=None):
        self.host, self.port = host, port
        self._sock = socket.create_connection((host, port), connect_timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        wrap = _socket_wrap()
        if wrap is not None:
            self._sock = wrap(self._sock, f"{host}:{port}")
        self._send_lock = lockdep.lock("RpcClient._send_lock")
        if secret is not None:
            # the server's first frame is the auth nonce; answer before
            # the reader thread starts interpreting frames as replies
            self._sock.settimeout(_AUTH_HANDSHAKE_TIMEOUT_S)
            try:
                nonce = _recv_frame(self._sock)
                _send_frame(self._sock, self._send_lock,
                            _auth_proof(secret, nonce))
                if _recv_frame(self._sock) != b"\x00ok":
                    raise ConnectionLost("bad auth confirmation")
                self._sock.settimeout(None)
            except (OSError, ConnectionLost) as e:
                # a server not configured for auth never sends a nonce:
                # fail fast with the real cause (and no leaked socket)
                # instead of surfacing as generic unreachability
                try:
                    self._sock.close()
                except OSError:
                    pass
                raise ConnectionLost(
                    f"auth handshake with {host}:{port} failed — secret "
                    f"mismatch or server not configured for auth: {e!r}"
                ) from e
        self._state_lock = lockdep.lock("RpcClient._state_lock")
        # seq -> (Future, expires_monotonic|None, method, deadline_s)
        self._pending = {}
        self._seq = 0
        self._closed = False
        # consecutive deadline expiries with NO intervening reply: a
        # black-holed link looks exactly like a slow one, so callers use
        # this to stop re-paying full deadlines on a dead connection
        # (see WEDGED_STRIKE_LIMIT). Single int under the GIL; the
        # reader thread writes, callers only compare against the limit.
        # flowlint: shared(GIL-atomic counter; a stale read delays one reconnect)
        self.deadline_strikes = 0
        # monotonic stamp of the last frame sent or received: the
        # keepalive pinger only probes links that have gone quiet
        # monotonic heartbeat for keepalive idleness: a single float
        # store under the GIL — a stale read only delays or duplicates
        # one advisory ping, so writers stay lockless by design.
        # flowlint: shared(GIL-atomic heartbeat; staleness is benign)
        self.last_activity = time.monotonic()
        self._reader = threading.Thread(
            target=self._read_loop, name="rpc-client-reader", daemon=True
        )
        self._reader.start()

    def _read_loop(self):
        reader = _FrameReader(self._sock)
        try:
            # short recv timeout = the deadline-sweep tick; a wedged or
            # silent peer can no longer park this thread forever
            self._sock.settimeout(_DEADLINE_TICK_S)
            while True:
                try:
                    frame = reader.recv_frame()
                except socket.timeout:
                    self._sweep_deadlines()
                    continue
                self.last_activity = time.monotonic()
                self.deadline_strikes = 0  # the link demonstrably moves data
                kind, seq, ok, payload = wire.loads(frame)
                with self._state_lock:
                    entry = self._pending.pop(seq, None)
                if entry is None:
                    continue  # cancelled/timed-out request
                fut = entry[0]
                if fut.done():
                    continue  # already deadline-settled
                if ok:
                    fut.set_result(payload)
                elif isinstance(payload, FDBError):
                    fut.set_exception(payload)
                else:
                    fut.set_exception(RemoteError(str(payload)))
        except (ConnectionLost, ConnectionError, OSError, ValueError) as e:
            self._fail_all(e)

    def _sweep_deadlines(self):
        """Settle every request past its deadline with DeadlineExceeded.

        The connection stays up: a slow reply to a swept seq is dropped
        by the reader, and unexpired requests keep waiting. Futures are
        settled OUTSIDE the state lock (FL003: callbacks may block)."""
        now = time.monotonic()
        expired = []
        with self._state_lock:
            for seq, entry in list(self._pending.items()):
                expires = entry[1]
                if expires is not None and now >= expires:
                    expired.append(entry)
                    del self._pending[seq]
        if expired:
            self.deadline_strikes += 1
        for fut, _expires, method, deadline_s in expired:
            if not fut.done():
                fut.set_exception(DeadlineExceeded(
                    method, deadline_s,
                    address=f"{self.host}:{self.port}",
                ))

    def _fail_all(self, exc):
        with self._state_lock:
            self._closed = True
            pending, self._pending = self._pending, {}
        try:
            self._sock.close()  # no fd leak across reconnect cycles
        except OSError:
            pass
        for entry in pending.values():
            fut = entry[0]
            if not fut.done():
                fut.set_exception(ConnectionLost(str(exc)))

    @property
    def alive(self):
        return not self._closed

    def call_async(self, method, *args, deadline_s=None) -> Future:
        fut = Future()
        expires = (
            time.monotonic() + deadline_s if deadline_s is not None else None
        )
        with self._state_lock:
            if self._closed:
                raise ConnectionLost("connection closed")
            self._seq += 1
            seq = self._seq
            self._pending[seq] = (fut, expires, method, deadline_s)
        # the thread's ambient SpanContext (a sampled client span) rides
        # as the optional v5 tracing frame; untraced calls keep the
        # 4-tuple form byte-for-byte
        ctx = span_mod.current()
        msg = ("q", seq, method, tuple(args)) if ctx is None \
            else ("q", seq, method, tuple(args), ctx)
        try:
            _send_frame(self._sock, self._send_lock, wire.dumps(msg))
            self.last_activity = time.monotonic()
        except (ConnectionError, OSError) as e:
            with self._state_lock:
                self._pending.pop(seq, None)
            self._fail_all(e)
            raise ConnectionLost(str(e)) from e
        except (ValueError, TypeError):
            # encoding failure / oversized request: the connection is fine,
            # only this call is bad — don't fail other in-flight requests
            with self._state_lock:
                self._pending.pop(seq, None)
            raise
        return fut

    def call(self, method, *args, timeout=None, deadline_s=None):
        return self.call_async(
            method, *args, deadline_s=deadline_s
        ).result(timeout=timeout)

    def close(self):
        with self._state_lock:
            self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        # the shutdown above unblocks the reader's recv; join so close()
        # returns with no thread still touching the dead socket
        if self._reader is not threading.current_thread():
            self._reader.join(timeout=5)


def connect_any(addresses, connect_timeout=5.0, secret=None):
    """Try each ``host:port`` in turn; first reachable wins (ref: the
    client walking the coordinator list in the cluster file)."""
    last = None
    for addr in addresses:
        host, _, port = addr.rpartition(":")
        try:
            return RpcClient(host, int(port), connect_timeout, secret=secret)
        except OSError as e:
            last = e
    raise ConnectionLost(
        f"no server reachable among {addresses!r}: {last}"
    )
