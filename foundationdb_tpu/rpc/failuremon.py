"""Per-endpoint failure monitor — shared health memory for real RPC.

Ref parity: fdbrpc/FailureMonitor.actor.cpp — every process keeps one
``IFailureMonitor`` that all its connections consult and feed: a
request timing out or a connection resetting marks the endpoint
failed; subsequent senders skip it instead of serially rediscovering
the outage; recovery is probed with exponentially spaced half-open
attempts rather than hammered.

One :class:`FailureMonitor` per process (``monitor()``), keyed by
``"host:port"`` address. The read router (`service._RemoteStorage`)
filters known-failed workers, the keepalive pinger marks idle links,
and the monitor's snapshot surfaces in ``cluster.health`` + the bench
e2e lines (``rpc_timeouts`` / ``endpoints_failed``).

Probe timing reads the injected clock (core/deterministic.py), so a
simulated monitor — if one is ever driven — replays with the seed.
Sims never touch the real transport, so production marks can't leak
nondeterminism into same-seed health docs: a sim's snapshot is empty.
"""

from foundationdb_tpu.core import deterministic
from foundationdb_tpu.utils import lockdep
from foundationdb_tpu.utils.trace import TraceEvent


class FailureMonitor:
    """Endpoint health table with half-open exponential recovery probes.

    ``available(addr)`` is the router's question: True for healthy
    endpoints, False for failed ones — EXCEPT that once per probe
    window a failed endpoint answers True exactly once (the half-open
    probe), so recovery is discovered without a thundering herd. The
    probe's outcome must be reported back via ``mark_ok`` /
    ``mark_failed`` to close the loop.
    """

    def __init__(self, probe_initial_s=0.25, probe_max_s=5.0):
        self.probe_initial_s = float(probe_initial_s)
        self.probe_max_s = float(probe_max_s)
        self._lock = lockdep.lock("FailureMonitor._lock")
        self._failed = {}  # addr -> {since, reason, probe_at, probe_delay}
        # cumulative counters for bench/health (never reset by marks)
        self._rpc_timeouts = 0
        self._endpoints_failed = 0

    def mark_failed(self, addr, reason=""):
        """An RPC against ``addr`` timed out / its connection died."""
        with self._lock:
            ent = self._failed.get(addr)
            now = deterministic.now()
            if ent is None:
                self._endpoints_failed += 1
                self._failed[addr] = {
                    "since": now,
                    "reason": str(reason)[:120],
                    "probe_at": now + self.probe_initial_s,
                    "probe_delay": self.probe_initial_s,
                }
                newly = True
            else:
                # a failed probe: widen the window exponentially
                delay = min(ent["probe_delay"] * 2.0, self.probe_max_s)
                ent["probe_delay"] = delay
                ent["probe_at"] = now + delay
                ent["reason"] = str(reason)[:120]
                newly = False
        if newly:
            TraceEvent("EndpointFailed", severity=30).detail(
                address=addr, reason=str(reason)[:120]).log()

    def note_timeout(self, addr, reason="deadline"):
        """A deadline expired against ``addr``: count it AND mark."""
        with self._lock:
            self._rpc_timeouts += 1
        self.mark_failed(addr, reason)

    def mark_ok(self, addr):
        """A call (or probe) against ``addr`` succeeded."""
        with self._lock:
            cleared = self._failed.pop(addr, None) is not None
        if cleared:
            TraceEvent("EndpointRecovered").detail(address=addr).log()

    def is_failed(self, addr):
        with self._lock:
            return addr in self._failed

    def available(self, addr):
        """Router check: may a request be sent to ``addr`` right now?

        Healthy → True. Failed → False, except exactly one True per
        probe window (half-open): claiming the probe pushes the next
        window out so concurrent callers don't all pile on.
        """
        with self._lock:
            ent = self._failed.get(addr)
            if ent is None:
                return True
            now = deterministic.now()
            if now >= ent["probe_at"]:
                delay = min(ent["probe_delay"] * 2.0, self.probe_max_s)
                ent["probe_delay"] = delay
                ent["probe_at"] = now + delay
                return True  # this caller carries the recovery probe
            return False

    def failed_addresses(self):
        with self._lock:
            return sorted(self._failed)

    def snapshot(self):
        """Deterministic-friendly health surface: states + counters
        only, no wall times (same-seed health docs must stay
        byte-identical, and sims never populate this table)."""
        with self._lock:
            return {
                "failed": {
                    addr: ent["reason"]
                    for addr, ent in sorted(self._failed.items())
                },
                "endpoints_failed": self._endpoints_failed,
                "rpc_timeouts": self._rpc_timeouts,
            }

    def counters(self):
        with self._lock:
            return {
                "rpc_timeouts": self._rpc_timeouts,
                "endpoints_failed": self._endpoints_failed,
            }

    def reset(self):
        """Test/bench isolation: forget marks AND counters."""
        with self._lock:
            self._failed.clear()
            self._rpc_timeouts = 0
            self._endpoints_failed = 0


_monitor = FailureMonitor()


def monitor():
    """The process-global monitor every connection shares."""
    return _monitor
