"""Wire serialization for the RPC transport.

Ref parity: the role flow's ObjectSerializer / flatbuffers-style wire
format plays in FlowTransport (flow/ObjectSerializer.h) — every value a
request or reply can carry has a stable, versioned binary form. The
format here is a compact tag-byte codec over the concrete types the
cluster protocol actually moves: primitives, containers, and the four
protocol structs (Mutation, KeySelector, CommitRequest, FDBError).

Big-endian length prefixes throughout; ints are 8-byte signed with a
bigint escape so versionstamp-scale values never truncate silently.
"""

import struct

from foundationdb_tpu.core.commit import CommitRequest
from foundationdb_tpu.core.errors import FDBError
from foundationdb_tpu.core.flatpack import FlatConflicts
from foundationdb_tpu.core.keys import KeySelector
from foundationdb_tpu.core.mutations import Mutation, Op

# v4: columnar commit frame (flat conflict blobs)
# v5: distributed tracing — an optional SpanContext frame on requests
#     (transport appends it to the "q" tuple; absent = untraced) and a
#     trailing span_context value on both CommitRequest frames
# v6: conflict repair — a trailing conflict_version on the FDBError
#     frame (the commit version whose writes rejected a reporting txn;
#     the client repair engine re-reads its conflicting keys there)
# v7: workload attribution — a trailing optional tag list on both
#     CommitRequest frames (set_tag labels; N = untagged), so the proxy
#     can attribute commits/aborts/conflicts per tag
PROTOCOL_VERSION = 7

# Every optional trailing frame the protocol has grown, by the version
# that introduced it. flowlint FL008 walks this table: each name must
# be mentioned in BOTH _enc and _dec (a decode-only frame is a frame
# nobody sends; an encode-only frame is unreadable skew) and carry a
# version-gate test reference under tests/. Growing the protocol means
# adding the row here FIRST — the lint then fails until both arms and
# a test exist.
OPTIONAL_FRAMES = {
    "flat_conflicts": 4,
    "span_context": 5,
    "conflict_version": 6,
    "tags": 7,
}

_OPS = list(Op)
_OP_INDEX = {op: i for i, op in enumerate(_OPS)}

_I64_MIN, _I64_MAX = -(1 << 63), (1 << 63) - 1


def _pack_len(buf, b):
    buf.append(struct.pack(">I", len(b)))
    buf.append(b)


def _enc(buf, v):
    t = type(v)
    if v is None:
        buf.append(b"N")
    elif t is bool:
        buf.append(b"T" if v else b"F")
    elif t is int:
        if _I64_MIN <= v <= _I64_MAX:
            buf.append(b"i")
            buf.append(struct.pack(">q", v))
        else:
            raw = v.to_bytes((v.bit_length() + 15) // 8, "big", signed=True)
            buf.append(b"g")
            _pack_len(buf, raw)
    elif t is float:
        buf.append(b"d")
        buf.append(struct.pack(">d", v))
    elif t is bytes:
        buf.append(b"b")
        _pack_len(buf, v)
    elif t is bytearray:
        buf.append(b"b")
        _pack_len(buf, bytes(v))
    elif t is str:
        buf.append(b"s")
        _pack_len(buf, v.encode("utf-8"))
    elif t is list:
        buf.append(b"l")
        buf.append(struct.pack(">I", len(v)))
        for item in v:
            _enc(buf, item)
    elif t is tuple:
        buf.append(b"u")
        buf.append(struct.pack(">I", len(v)))
        for item in v:
            _enc(buf, item)
    elif t is dict:
        buf.append(b"m")
        buf.append(struct.pack(">I", len(v)))
        for k, val in v.items():
            _enc(buf, k)
            _enc(buf, val)
    elif t is Mutation:
        buf.append(b"M")
        buf.append(struct.pack(">B", _OP_INDEX[v.op]))
        _pack_len(buf, v.key)
        _enc(buf, v.param)
    elif t is KeySelector:
        buf.append(b"K")
        _pack_len(buf, v.key)
        buf.append(b"T" if v.or_equal else b"F")
        buf.append(struct.pack(">i", v.offset))
    elif t is CommitRequest:
        if v.flat_conflicts is not None:
            # the columnar frame: conflict ranges travel ONLY as the
            # client's pre-encoded limb blobs — the server-side proxy
            # consumes them without re-parsing a single key, and the
            # byte-pair lists reconstruct lazily (CommitRequest
            # properties) on the rare paths that still want them
            buf.append(b"Q")
            _enc(buf, v.read_version)
            _enc(buf, list(v.mutations))
            _enc(buf, v.flat_conflicts)
            buf.append(b"T" if v.report_conflicting_keys else b"F")
            buf.append(b"T" if v.lock_aware else b"F")
            _enc(buf, v.idempotency_id)
            _enc(buf, v.span_context)  # v5: tracing context (N = none)
            _enc(buf, list(v.tags) if v.tags else None)  # v7: tags
            return
        buf.append(b"R")
        _enc(buf, v.read_version)
        _enc(buf, list(v.mutations))
        _enc(buf, [(bytes(b_), bytes(e_)) for b_, e_ in v.read_conflict_ranges])
        _enc(buf, [(bytes(b_), bytes(e_)) for b_, e_ in v.write_conflict_ranges])
        buf.append(b"T" if v.report_conflicting_keys else b"F")
        buf.append(b"T" if v.lock_aware else b"F")
        _enc(buf, v.idempotency_id)
        _enc(buf, v.span_context)  # v5: tracing context (N = none)
        _enc(buf, list(v.tags) if v.tags else None)  # v7: tags
    elif t is FlatConflicts:
        buf.append(b"C")
        buf.append(struct.pack(
            ">BIIII", v.num_limbs, v.read_points, v.read_ranges,
            v.write_points, v.write_ranges,
        ))
        _pack_len(buf, v.read_point_blob)
        _pack_len(buf, v.read_range_blob)
        _pack_len(buf, v.write_point_blob)
        _pack_len(buf, v.write_range_blob)
    elif isinstance(v, FDBError):
        buf.append(b"e")
        buf.append(struct.pack(">I", v.code))
        # optional conflicting-keys payload (report_conflicting_keys)
        _enc(buf, getattr(v, "conflicting_key_ranges", None))
        # v6: the rejecting commit version (conflict repair's read
        # version); N for errors that carry no conflict report
        _enc(buf, getattr(v, "conflict_version", None))
    else:
        raise TypeError(f"wire: cannot encode {type(v).__name__}: {v!r}")


def dumps(v) -> bytes:
    buf = []
    _enc(buf, v)
    return b"".join(buf)


class _Reader:
    __slots__ = ("data", "pos")

    def __init__(self, data):
        self.data = data
        self.pos = 0

    def take(self, n):
        p = self.pos
        if p + n > len(self.data):
            raise ValueError("wire: truncated message")
        self.pos = p + n
        return self.data[p : p + n]

    def take_len(self):
        (n,) = struct.unpack(">I", self.take(4))
        return self.take(n)


def _dec(r: _Reader):
    tag = r.take(1)
    if tag == b"N":
        return None
    if tag == b"T":
        return True
    if tag == b"F":
        return False
    if tag == b"i":
        return struct.unpack(">q", r.take(8))[0]
    if tag == b"g":
        return int.from_bytes(r.take_len(), "big", signed=True)
    if tag == b"d":
        return struct.unpack(">d", r.take(8))[0]
    if tag == b"b":
        return r.take_len()
    if tag == b"s":
        return r.take_len().decode("utf-8")
    if tag == b"l":
        (n,) = struct.unpack(">I", r.take(4))
        return [_dec(r) for _ in range(n)]
    if tag == b"u":
        (n,) = struct.unpack(">I", r.take(4))
        return tuple(_dec(r) for _ in range(n))
    if tag == b"m":
        (n,) = struct.unpack(">I", r.take(4))
        return {_dec(r): _dec(r) for _ in range(n)}
    if tag == b"M":
        (op_i,) = struct.unpack(">B", r.take(1))
        key = r.take_len()
        param = _dec(r)
        return Mutation(_OPS[op_i], key, param)
    if tag == b"K":
        key = r.take_len()
        or_equal = r.take(1) == b"T"
        (offset,) = struct.unpack(">i", r.take(4))
        return KeySelector(key, or_equal, offset)
    if tag == b"R":
        rv = _dec(r)
        muts = _dec(r)
        rcr = _dec(r)
        wcr = _dec(r)
        report = r.take(1) == b"T"
        lock_aware = r.take(1) == b"T"
        idmp = _dec(r)
        sctx = _dec(r)
        tags = _dec(r)
        return CommitRequest(rv, muts, rcr, wcr, report, lock_aware,
                             idempotency_id=idmp, span_context=sctx,
                             tags=tuple(tags) if tags else ())
    if tag == b"Q":
        rv = _dec(r)
        muts = _dec(r)
        flat = _dec(r)
        report = r.take(1) == b"T"
        lock_aware = r.take(1) == b"T"
        idmp = _dec(r)
        sctx = _dec(r)
        tags = _dec(r)
        # range lists None: reconstructed lazily from the blobs only if
        # a legacy consumer asks (CommitRequest._from_flat)
        return CommitRequest(rv, muts, None, None, report, lock_aware,
                             idempotency_id=idmp, flat_conflicts=flat,
                             span_context=sctx,
                             tags=tuple(tags) if tags else ())
    if tag == b"C":
        num_limbs, rp, rr, wp, wr = struct.unpack(">BIIII", r.take(17))
        return FlatConflicts(
            num_limbs, rp, r.take_len(), rr, r.take_len(),
            wp, r.take_len(), wr, r.take_len(),
        )
    if tag == b"e":
        (code,) = struct.unpack(">I", r.take(4))
        e = FDBError(code)
        ranges = _dec(r)
        if ranges is not None:
            e.conflicting_key_ranges = ranges
        cv = _dec(r)
        if cv is not None:
            e.conflict_version = cv
        return e
    raise ValueError(f"wire: unknown tag {tag!r}")


def loads(data: bytes):
    r = _Reader(data)
    v = _dec(r)
    if r.pos != len(data):
        raise ValueError("wire: trailing bytes")
    return v
