"""Storage servers as separate processes, pulling the mutation stream.

Ref parity: the reference's storage architecture — a storage server is
its own process that PULLS its mutations from the TLogs (the update
loop in fdbserver/storageserver.actor.cpp: peek the log cursor, apply
in version order, advance the durable/read frontier) and serves
versioned reads, waiting for a version it hasn't caught up to yet
(watchValue/getValue's version-wait; clients see `future_version` 1009
— retryable — if the wait times out).

Shape here:
- the lead process exposes its log over RPC (`tlog_peek`) plus a
  pop-hold protocol so the durability pump can never discard records a
  worker hasn't applied (ref: tag-partitioned pop: the log only pops
  below every cursor);
- `StorageWorker` bootstraps with a chunked snapshot at a pinned read
  version (hold first, then pin — no pop race), then tails the log,
  applying mutations in version order into a local StorageServer;
- reads on the worker wait for the requested version (bounded), so a
  client can read-balance across lead + workers with ordinary retry
  semantics; a stale hold from a dead worker is aged out lead-side so
  an abandoned cursor cannot pin the log forever.
"""

import itertools
import threading

import time

from foundationdb_tpu.core.errors import FDBError, err
from foundationdb_tpu.core.keys import key_successor
from foundationdb_tpu.core.mutations import Mutation, Op
from foundationdb_tpu.rpc.transport import (
    ConnectionLost,
    RemoteError,
    RpcClient,
    RpcServer,
)
from foundationdb_tpu.utils import lockdep
from foundationdb_tpu.utils.trace import TraceEvent

SYSTEM_END = b"\xff\xff"
WORKER_HOLD_TTL_S = 30.0  # a hold not refreshed this long is abandoned


def _intersect_ranges(a, b):
    """Intersection of two merged [begin, end) range lists."""
    out = []
    for ab, ae in a:
        for bb, be in b:
            lo, hi = max(ab, bb), min(ae, be)
            if lo < hi:
                out.append((lo, hi))
    return sorted(out)


class LogFeed:
    """Lead-side endpoints a worker pulls from (attach to the lead's
    RpcServer next to the ClusterService handlers)."""

    def __init__(self, cluster):
        self.cluster = cluster
        self._holds = {}  # name -> last refresh monotonic
        self._lock = lockdep.lock("LogFeed._lock")

    def handlers(self):
        return {
            "tlog_peek": self.tlog_peek,
            "tlog_floor": self.tlog_floor,
            "tlog_hold": self.tlog_hold,
            "tlog_release": self.tlog_release,
            "worker_register": self.worker_register,
            "list_workers": self.list_workers,
            "tag_ranges": self.tag_ranges,
        }

    def tag_ranges(self, tag):
        """The key ranges storage tag ``tag`` covers — a tag-scoped
        worker bootstraps exactly these (ref: a storage's keyServers
        subscription)."""
        return [tuple(r) for r in
                self.cluster.storage_owned_ranges(int(tag))]

    def _prune_stale(self):
        now = time.monotonic()
        with self._lock:
            stale = [
                n for n, ts in self._holds.items()
                if now - ts > WORKER_HOLD_TTL_S
            ]
            for n in stale:
                del self._holds[n]
        for n in stale:
            self.cluster.tlog.release_pop(n)
            TraceEvent("WorkerHoldExpired", severity=30).detail(name=n).log()

    def tlog_hold(self, name, version):
        self._prune_stale()
        self.cluster.tlog.hold_pop(name, version)
        with self._lock:
            self._holds[name] = time.monotonic()

    def tlog_release(self, name):
        self.cluster.tlog.release_pop(name)
        with self._lock:
            self._holds.pop(name, None)

    def tlog_peek(self, from_version, limit=512, wait_s=0.0, tag=None):
        """With ``wait_s``: park on the log's push condition until a
        record newer than from_version exists or the wait expires — a
        tailing worker long-polls instead of hammering 500 peek RPCs/s
        at an idle lead, and the parked thread costs zero CPU (the push
        path signals it). Served from the blocking pool.

        ``tag``: serve only that storage tag's stream — a tag-scoped
        worker pulls its shards' bytes, not the whole firehose (ref:
        TLog tag cursors)."""
        self._prune_stale()
        if wait_s and self.cluster.tlog.last_version <= from_version:
            self.cluster.tlog.wait_for_version(
                from_version + 1, timeout=min(wait_s, 5.0)
            )
        recs = self.cluster.tlog.peek(from_version, tag=tag)
        # floor travels WITH the records: a gap (records popped below the
        # floor before this worker applied them) must be detectable even
        # on a reply that carries newer records. The shard-map epoch
        # rides along too, so a tagged worker learns of ownership moves
        # from its next peek instead of polling the map.
        return (self.cluster.tlog._first_version,
                getattr(self.cluster, "shard_epoch", 0),
                [(v, list(muts)) for v, muts in recs[:limit]])

    def tlog_floor(self):
        """Oldest version still retained; a worker whose position is
        below this has a GAP (records popped unseen) and must
        re-bootstrap rather than silently tail past it."""
        return self.cluster.tlog._first_version

    # registry: who serves reads (clients discover via list_workers)
    _workers = None

    def worker_register(self, address, ranges=None):
        """``ranges``: the key ranges this worker serves (None = the
        whole keyspace); clients route reads by coverage."""
        with self._lock:
            if self._workers is None:
                self._workers = {}
            self._workers[address] = (time.monotonic(), ranges)
        TraceEvent("StorageWorkerJoined").detail(
            address=address, tagged=ranges is not None).log()

    def list_workers(self):
        """[(address, ranges-or-None), ...] of live workers."""
        with self._lock:
            if not self._workers:
                return []
            now = time.monotonic()
            return [
                (a, rg) for a, (ts, rg) in self._workers.items()
                if now - ts < WORKER_HOLD_TTL_S * 10
            ]


class StorageWorker:
    """One storage-role process: local versioned store + pull loop.

    ``serve()`` starts an RpcServer exposing the read surface
    (storage_get / get_range / resolve_selector, all version-waiting)
    and returns it; ``start()`` begins the bootstrap + tail thread.
    """

    _ids = itertools.count(1)

    def __init__(self, lead_address, window_versions=5_000_000,
                 chunk=1000, name=None, secret=None, tag=None):
        import os

        from foundationdb_tpu.server.storage import StorageServer

        self.lead_address = lead_address
        self.secret = secret
        # tag = a storage id: this worker subscribes to THAT tag's log
        # stream and bootstraps/serves only its owned ranges (ref: a
        # storage server peeking its own tag). None = full keyspace.
        self.tag = tag
        self.ranges = None  # fetched at bootstrap when tagged
        # what READS may be served: swapped atomically with the store
        # (self.ranges can run ahead during a re-bootstrap; serving
        # against it would expose moved-in shards before their data
        # arrives). None = full keyspace; [] = nothing yet.
        self._served_ranges = None if tag is None else []
        self._seen_epoch = -1
        self.bytes_pulled = 0
        # pid-qualified: two --join PROCESSES must never share a hold
        # name, or the faster one advances the cursor past the slower
        # one's position and the pump pops records it still needs
        self.name = name or f"worker-{os.getpid()}-{next(self._ids)}"
        self.chunk = chunk
        self.storage = StorageServer(window_versions=window_versions)
        self.window_versions = window_versions
        self.position = 0  # last applied log version
        self._stop = threading.Event()
        self._caught_up = threading.Event()
        self._detach_error = None  # set iff the pull loop died
        self._thread = None
        self._client = None
        self._lock = lockdep.lock("StorageWorker._lock")
        self._advertise = None  # our serve() address, re-registered on tick
        self._last_refresh = 0.0

    # ── lead RPC plumbing ──
    def _call(self, method, *args):
        with self._lock:
            if self._client is None or not self._client.alive:
                host, _, port = self.lead_address.rpartition(":")
                self._client = RpcClient(host, int(port),
                                         secret=self.secret)
            client = self._client
        return client.call(method, *args)

    # ── bootstrap + tail ──
    def start(self):
        self._thread = threading.Thread(
            target=self._run, name=self.name, daemon=True
        )
        self._thread.start()
        return self

    def _run(self):
        from foundationdb_tpu.core.errors import FDBError

        try:
            self._bootstrap()
            self._caught_up.set()
            while not self._stop.is_set():
                self._tail_once()
        except (ConnectionLost, RemoteError, OSError, FDBError) as e:
            # FDBError included: a too-slow bootstrap can get 1007 from
            # the lead — detach cleanly, don't die with a raw traceback
            self._detach_error = e
            self._caught_up.set()  # wake waiters; they see the error
            TraceEvent("StorageWorkerDetached", severity=30).detail(
                name=self.name, error=str(e)[:120]).log()

    def _bootstrap(self, attempts=3):
        """Snapshot at a pinned version into a FRESH store, then swap it
        in. A fresh store (not in-place apply) makes re-bootstrap after a
        log gap correct: keys deleted while we were behind do not
        survive as stale rows. Retries with a newer version if the
        snapshot outlives the lead's MVCC window (1007)."""
        from foundationdb_tpu.core.errors import FDBError
        from foundationdb_tpu.server.storage import StorageServer

        # hold FIRST (at 0), then pin the snapshot version: the pump can
        # not pop anything the tail will need, no matter how the grab
        # and the pump interleave
        self._call("tlog_hold", self.name, 0)
        if self.tag is not None:
            self.ranges = [tuple(r) for r in
                           self._call("tag_ranges", self.tag)]
        for attempt in range(attempts):
            rv = self._call("get_read_version")
            self._call("tlog_hold", self.name, rv)
            fresh = StorageServer(window_versions=self.window_versions)
            spans = self.ranges or [(b"", SYSTEM_END)]
            muts = []
            try:
                for span_b, span_e in spans:
                    begin = span_b
                    while True:
                        rows = self._call("get_range", begin,
                                          min(span_e, SYSTEM_END), rv,
                                          self.chunk, False)
                        muts.extend(Mutation(Op.SET, k, v) for k, v in rows)
                        if len(rows) < self.chunk:
                            break
                        begin = key_successor(rows[-1][0])
            except FDBError as e:
                if e.code == 1007 and attempt + 1 < attempts:
                    continue  # snapshot fell out of the window: re-pin
                raise
            if rv > 0:
                fresh.apply(rv, muts)
            self.storage = fresh  # atomic swap; readers see the new cut
            self._served_ranges = self.ranges  # now backed by the store
            self.position = rv
            self._last_refresh = time.monotonic()
            TraceEvent("StorageWorkerBootstrapped").detail(
                name=self.name, version=rv, rows=len(muts)).log()
            return

    def _tail_once(self):
        # long-poll: the lead blocks (cheap) until records exist, so an
        # idle worker costs ~4 RPCs/s, not 500. A tagged worker pulls
        # only its tag's stream (~its owned fraction of the bytes).
        floor, epoch, recs = self._call(
            "tlog_peek", self.position, 512, 0.25, self.tag
        )
        self.bytes_pulled += sum(
            len(m.key) + len(m.param or b"")
            for _, muts in recs for m in muts
        )
        if self.tag is not None and epoch != self._seen_epoch:
            # The shard map changed: DD moves copy data storage-to-
            # storage, NOT through this tag's stream, so moved-in
            # shards are missing locally. Shrink serving to the
            # still-owned intersection IMMEDIATELY (moved-away spans
            # must stop serving pre-move values), then re-bootstrap
            # onto the full new coverage (ref: fetchKeys on a
            # relocated shard). Reads routed here during the at-most-
            # one-peek-interval detection window may see pre-move
            # state — the same bounded metadata-propagation window the
            # reference closes with versioned shard ownership.
            self._seen_epoch = epoch
            fresh = [tuple(r) for r in self._call("tag_ranges", self.tag)]
            if fresh != self.ranges:
                TraceEvent("StorageWorkerRangesMoved").detail(
                    name=self.name, tag=self.tag).log()
                self._served_ranges = _intersect_ranges(
                    self._served_ranges or [], fresh
                )
                self._bootstrap()
                return
        if floor > self.position:
            # GAP: records in (position, floor] were popped before we
            # applied them (our hold aged out, or we were reborn) —
            # tailing past it would silently lose mutations
            TraceEvent("StorageWorkerGap", severity=30).detail(
                name=self.name, position=self.position, floor=floor).log()
            self._bootstrap()
            return
        for v, muts in recs:
            if v <= self.position:
                continue
            self.storage.apply(v, muts)
            self.position = v
        self.storage.advance_window(
            max(0, self.position - self.window_versions)
        )
        now = time.monotonic()
        if recs or now - self._last_refresh > WORKER_HOLD_TTL_S / 3:
            # refresh even when idle: a live worker's hold (and its
            # read-registry entry) must never age out just because no
            # commits flowed for a while
            self._call("tlog_hold", self.name, self.position)
            if self._advertise is not None:
                self._call("worker_register", self._advertise, self.ranges)
            self._last_refresh = now

    def wait_caught_up(self, timeout=30.0):
        """Block until the bootstrap finished. Failure is always a
        CODED retryable FDBError — never a raw TimeoutError — so a
        caller's on_error loop treats a slow or detached worker like
        any lagging storage (1037: behind, catch up and retry)."""
        if not self._caught_up.wait(timeout):
            raise err("process_behind",
                      f"{self.name} still bootstrapping (process_behind)")
        if self._detach_error is not None:
            raise err(
                "process_behind",
                f"{self.name} detached during bootstrap: "
                f"{str(self._detach_error)[:120]}",
            )

    # ── read surface (version-waiting, ref: waitForVersion) ──
    def _wait_version(self, rv, timeout=5.0):
        """Returns the storage object that satisfied the wait — reads
        must use THAT object, since a gap re-bootstrap swaps
        ``self.storage`` concurrently."""
        deadline = time.monotonic() + timeout
        while True:
            st = self.storage
            if st.version >= rv:
                return st
            if self._stop.is_set() or time.monotonic() > deadline:
                # behind and not catching up: the client retries (1009)
                raise err("future_version")
            time.sleep(0.0005)

    def _check_cover(self, span):
        """Authoritative ownership check: a tagged worker serves only
        what its CURRENT store covers (clients route by a coverage map
        they snapshot at connect; after a DD move that map is stale and
        this is the backstop that turns a mis-routed read into a
        retryable 1009 — served from the lead — instead of a silently
        stale value)."""
        served = self._served_ranges
        if served is None:
            return
        if span is None or not any(
            rb <= span[0] and span[1] <= re_ for rb, re_ in served
        ):
            raise err("future_version")

    def storage_get(self, key, rv):
        self._check_cover((key, key + b"\x00"))
        return self._wait_version(rv).get(key, rv)

    def get_range(self, begin, end, rv, limit, reverse):
        self._check_cover((begin, end))
        rows = self._wait_version(rv).get_range(
            begin, end, rv, limit=limit, reverse=reverse
        )
        return [(k, v) for k, v in rows]

    def resolve_selector(self, selector, rv):
        self._check_cover(None)  # selectors walk: full coverage only
        return self._wait_version(rv).resolve_selector(selector, rv)

    @staticmethod
    def _op_span(op):
        """The coverage span one batched read op needs (None = full
        keyspace — selector walks and selector-bounded ranges)."""
        if op[0] == "g":
            return (op[1], op[1] + b"\x00")
        if op[0] == "r" and isinstance(op[1], bytes) \
                and isinstance(op[2], bytes):
            return (op[1], op[2])
        return None

    def read_batch(self, ops):
        """Multiplexed multi-op serve with PER-OP error slots: a
        mis-routed key (coverage backstop) or a version this worker
        never catches answers 1009 for ITS slot only — the lead
        re-serves just those; the rest of the batch lands here. One
        version wait covers the batch (waits for the max rv), then
        the local store's vectorized serve runs under one lock."""
        ops = list(ops)
        out = [None] * len(ops)
        todo = []  # [(index, op)] — ops that passed the cover check
        for i, op in enumerate(ops):
            try:
                self._check_cover(self._op_span(op))
            except FDBError as e:
                out[i] = e
                continue
            todo.append((i, op))
        if todo:
            rv = max(
                op[3] if op[0] == "r" else op[2] for _, op in todo
            )
            try:
                st = self._wait_version(rv)
            except FDBError as e:
                for i, _ in todo:
                    out[i] = e
            else:
                slots = st.read_batch([op for _, op in todo])
                for (i, _), slot in zip(todo, slots):
                    out[i] = slot
        return out

    def worker_status(self):
        return {
            "name": self.name,
            "version": self.storage.version,
            "position": self.position,
            "caught_up": (self._caught_up.is_set()
                          and self._detach_error is None),
            "tag": self.tag,
            "bytes_pulled": self.bytes_pulled,
        }

    def handlers(self):
        return {
            "storage_get": self.storage_get,
            "get_range": self.get_range,
            "resolve_selector": self.resolve_selector,
            "read_batch": self.read_batch,
            "worker_status": self.worker_status,
            # liveness probe for the client failure monitor's keepalive
            "ping": lambda: "pong",
        }

    def serve(self, host="127.0.0.1", port=0):
        """Expose the read surface; registers with the lead."""
        server = RpcServer(
            host, port, self.handlers(),
            long_methods={"storage_get", "get_range", "resolve_selector",
                          "read_batch"},
            secret=self.secret,
        )
        self._advertise = server.address  # tail ticks re-register us
        self._call("worker_register", server.address, self.ranges)
        return server

    def close(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        try:
            self._call("tlog_release", self.name)
        except (ConnectionLost, RemoteError, OSError):
            pass
        if self._client is not None:
            self._client.close()
