"""Cluster service endpoints + the remote client.

Ref parity: the client↔server split in FoundationDB — fdbclient's
NativeAPI speaks to fdbserver processes found through the cluster file
(fdbclient/ClusterConnectionFile, MonitorLeader). Here `ClusterService`
exposes a running `server.cluster.Cluster`'s role interfaces as RPC
endpoints, and `RemoteCluster` implements the exact cluster surface
`txn/transaction.py` consumes (grv_proxy / read_storage / commit_proxy /
knobs / status), so `Database(RemoteCluster(...))` IS the remote client —
the whole transaction, layer, and directory stack runs against a real
network without a line of change.

Failure semantics on a dead connection (ref: NativeAPI's handling of
broken proxy connections):
- reads / GRVs: retry on a fresh connection; if no server is reachable
  the error surfaces as `transaction_too_old`-style retryable only after
  reconnect succeeds — otherwise ConnectionLost propagates (the cluster
  is gone, not the transaction).
- commit: NEVER auto-retried at this layer. A connection that dies with
  a commit outstanding returns `commit_unknown_result` (1021) — the
  transaction may or may not have committed, exactly the reference's
  contract; the client retry loop owns the disambiguation.
"""

import dataclasses
import itertools
import os
import string
import threading

import time

from foundationdb_tpu.core import deterministic
from foundationdb_tpu.core.errors import FDBError
from foundationdb_tpu.core.options import DEFAULT_KNOBS, Knobs
from foundationdb_tpu.rpc import failuremon
from foundationdb_tpu.rpc.transport import (
    WEDGED_STRIKE_LIMIT,
    ConnectionLost,
    DeadlineExceeded,
    RpcServer,
    connect_any,
)
from foundationdb_tpu.utils.backoff import Backoff
from foundationdb_tpu.txn.futures import FutureRange, FutureValue
from foundationdb_tpu.rpc.wire import PROTOCOL_VERSION
from foundationdb_tpu.utils import lockdep
from foundationdb_tpu.utils import span as span_mod
from foundationdb_tpu.utils.trace import SEV_ERROR, TraceEvent


# ───────────────────────────── cluster files ─────────────────────────────
def write_cluster_file(path, addresses, description="tpu", cluster_id=None):
    """``description:id@host:port,host:port`` (ref: ClusterConnectionFile
    format in fdbclient/ConnectionString)."""
    if cluster_id is None:
        # drawn from the injected stream so a seeded sim writes the same
        # cluster file every run (FL001: cluster-visible entropy)
        id_rng = deterministic.rng("cluster-id")
        cluster_id = "".join(
            id_rng.choice(string.ascii_lowercase + string.digits)
            for _ in range(8)
        )
    body = f"{description}:{cluster_id}@{','.join(addresses)}\n"
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(body)
    os.replace(tmp, path)
    return body.strip()


def parse_cluster_file(path):
    """Returns (description, cluster_id, [addresses])."""
    with open(path) as f:
        line = f.read().strip()
    head, _, addrs = line.partition("@")
    desc, _, cid = head.partition(":")
    addresses = [a.strip() for a in addrs.split(",") if a.strip()]
    if not addresses:
        raise ValueError(f"cluster file {path!r} has no addresses: {line!r}")
    return desc, cid, addresses


# ───────────────────────────── server side ───────────────────────────────
class ClusterService:
    """Endpoint table over a live Cluster (the fdbserver worker's RPC
    surface). One instance per served cluster; handlers are thread-safe
    to the same degree the underlying roles are (thread-mode clusters
    take their own locks)."""

    WATCH_TTL_S = 900  # orphaned watches (client gone) age out
    MAX_WATCH_WAIT_S = 30.0  # server-side clamp on one blocking chunk

    def __init__(self, cluster):
        self.cluster = cluster
        self._watches = {}  # watch_id -> (Watch, threading.Event, born)
        self._watch_ids = itertools.count(1)
        self._watch_lock = lockdep.lock("ClusterService._watch_lock")
        # The plain synchronous CommitProxy (commit_pipeline="sync") has
        # no internal synchronization — the in-process deployments that
        # use it are single-threaded. Concurrent RPC clients are not:
        # serialize their commits here. The "thread" pipeline's batching
        # proxy takes concurrent submissions natively (that's its job),
        # so it skips the lock and actually batches across clients.
        if getattr(cluster, "commit_pipeline", "sync") == "thread":
            self._commit_lock = None
        else:
            self._commit_lock = lockdep.lock("ClusterService._commit_lock")

    def handlers(self):
        return {
            "hello": self.hello,
            # failure-monitor keepalive: cheapest possible liveness probe
            # (ref: FailureMonitor's ping loop) — answers even while the
            # storage/commit paths are busy, so it measures process
            # liveness, not load
            "ping": lambda: "pong",
            "knobs": self.knobs,
            "status": self.status,
            # the metrics section alone (monitoring agents poll this
            # without paying for the whole status document)
            "metrics": self.metrics,
            # cluster doctor: verdict + probe bands + recovery timeline
            # + lag rollups alone (fdbcli `doctor`, tools/doctor.py)
            "health": self.health,
            # workload attribution: hot ranges + per-tag rollup alone
            # (fdbcli `top`, tools/heatmap.py split-point advice)
            "metrics_hot": self.metrics_hot,
            # device-path execution profile alone (fdbcli `profile`):
            # resolver dispatch/pad/fallback accounting + lane walls
            "device_profile": self.device_profile,
            # metrics history: the retention layer's per-metric windows
            # + verdict timeline alone (fdbcli `history`, the trend
            # consumers in tools/doctor.py and tools/heatmap.py)
            "history": self.history,
            # flight recorder: dump summary + newest black-box artifact
            # (tools/flight.py post-mortems against a live cluster)
            "flight": self.flight,
            # continuous consistency scan: round/progress/verdict alone
            # (fdbcli `scan status`, tools/doctor.py --scan), plus the
            # kill-switch control behind fdbcli `scan on|off`
            "consistency_scan": self.consistency_scan,
            "set_consistency_scan": self.set_consistency_scan,
            "get_read_version": self.get_read_version,
            "storage_get": self.storage_get,
            "resolve_selector": self.resolve_selector,
            "get_range": self.get_range,
            "read_batch": self.read_batch,
            "commit": self.commit,
            "commit_batch": self.commit_batch,
            "watch_register": self.watch_register,
            "watch_poll": self.watch_poll,
            "watch_wait": self.watch_wait,
            # exclusion returns DD move records (arbitrary role objects);
            # the wire carries just the relocation count
            "exclude_storage": lambda sid: len(
                self.cluster.exclude_storage(sid) or ()
            ),
            "include_storage": self.cluster.include_storage,
            "list_excluded": self.cluster.list_excluded,
            "consistency_check": self.cluster.consistency_check,
            "estimated_range_size": self.cluster.estimated_range_size_bytes,
            "range_split_points": self.cluster.range_split_points,
            "lock_database": self.cluster.lock_database,
            "unlock_database": self.cluster.unlock_database,
            "lock_uid": self.cluster.lock_uid,
            # distributed tracing config (fdbcli `tracing`, the
            # \xff\xff/tracing/ special keys against a remote cluster)
            "tracing_config": self.cluster.tracing_config,
            "set_tracing": self._set_tracing,
            "set_tenant_mode": self.cluster.set_tenant_mode,
            "configure": self._configure,
            "tenant_mode": self.cluster.tenant_mode,
            "set_tag_quota": self.cluster.set_tag_quota,
            "feed_register": self.cluster.change_feeds.register,
            "feed_read": self.cluster.change_feeds.read,
            "feed_pop": self.cluster.change_feeds.pop,
            "feed_deregister": self.cluster.change_feeds.deregister,
            "feed_list": self.cluster.change_feeds.list,
        }

    def hello(self, client_protocol):
        if client_protocol != PROTOCOL_VERSION:
            raise FDBError.from_name("incompatible_protocol_version")
        return {
            "protocol": PROTOCOL_VERSION,
            "generation": self.cluster.generation,
        }

    def knobs(self):
        return dataclasses.asdict(self.cluster.knobs)

    def status(self):
        return self.cluster.status()

    def metrics(self):
        return self.cluster.metrics_status()

    def health(self):
        return self.cluster.health_status()

    def metrics_hot(self, top=None):
        return self.cluster.hot_ranges_status(top=top)

    def device_profile(self):
        return self.cluster.device_profile_status()

    def history(self):
        return self.cluster.history_status()

    def flight(self):
        return self.cluster.flight_status()

    def consistency_scan(self):
        return self.cluster.consistency_scan_status()

    def set_consistency_scan(self, on):
        return self.cluster.set_consistency_scan(bool(on))

    def get_read_version(self, priority="default", tags=()):
        return self.cluster.grv_proxy.get_read_version(
            priority, tags=tuple(tags)
        )

    def storage_get(self, key, rv):
        return self.cluster.read_storage(key).get(key, rv)

    def resolve_selector(self, selector, rv):
        return self.cluster.read_storage().resolve_selector(selector, rv)

    def get_range(self, begin, end, rv, limit, reverse):
        rows = self.cluster.read_storage().get_range(
            begin, end, rv, limit=limit, reverse=reverse
        )
        return [(k, v) for k, v in rows]

    def read_batch(self, ops):
        """One multiplexed read RPC (the client ReadBatcher's flush):
        N coalesced reads, decoded once, served under ONE storage lock
        acquisition (StorageServer.read_batch). Slots are per-op —
        FDBError values ride the wire natively, so one too-old key
        fails alone, never the batch."""
        ops = list(ops)
        sp = span_mod.from_context(
            "storage.read_batch", span_mod.current(), ops=len(ops)
        )
        try:
            st = self.cluster.read_storage()
            rb = getattr(st, "read_batch", None)
            if rb is not None:
                return rb(ops)
            # storage tier without a vectorized serve: same slots, one
            # op at a time (semantics identical, just more crossings)
            out = []
            for op in ops:
                try:
                    if op[0] == "g":
                        out.append(
                            self.cluster.read_storage(op[1]).get(
                                op[1], op[2]
                            )
                        )
                    elif op[0] == "r":
                        out.append([
                            (k, v) for k, v in st.get_range(
                                op[1], op[2], op[3],
                                limit=op[4], reverse=op[5],
                            )
                        ])
                    elif op[0] == "s":
                        out.append(st.resolve_selector(op[1], op[2]))
                    else:
                        raise FDBError.from_name(
                            "client_invalid_operation"
                        )
                except FDBError as e:
                    out.append(e)
            return out
        finally:
            sp.finish()

    def commit(self, request):
        # the proxy returns (never raises) FDBError verdicts; the wire
        # carries them as values so the client transaction sees the exact
        # in-process contract
        if self._commit_lock is not None:
            with self._commit_lock:
                return self.cluster.commit_proxy.commit(request)
        return self.cluster.commit_proxy.commit(request)

    def _configure(self, commit_proxies=None, resolvers=None):
        """Live reconfiguration over the wire (fdbcli `configure`);
        returns the achieved shape so a remote operator can confirm."""
        return self.cluster.configure(commit_proxies=commit_proxies,
                                      resolvers=resolvers)

    def _set_tracing(self, sample_rate=None, enabled=None):
        return self.cluster.set_tracing(sample_rate=sample_rate,
                                        enabled=enabled)

    def commit_batch(self, requests):
        """A client-batched window of commits in ONE RPC (the remote
        BatchingCommitProxy's flush): decoded once, pipelined once —
        per-commit RPCs round-trip-bound multi-process deployments
        (ref: clients streaming batched commits at the proxy).

        Span accounting: this route bypasses any server-side batching
        wrapper (deliberately — the window is already batched), so when
        the bare proxy has ceded commit_e2e ownership to that wrapper,
        nobody else would record the span; record it here (decode →
        reply, the server-side view of the client's window)."""
        from foundationdb_tpu.utils import metrics as metrics_mod

        target = getattr(self.cluster.commit_proxy, "inner",
                         self.cluster.commit_proxy)
        owner = target.inners[0] if hasattr(target, "inners") else target
        t0 = metrics_mod.now() \
            if getattr(owner, "spans_owned_externally", False) \
            and metrics_mod.enabled() else None
        try:
            if self._commit_lock is not None:
                with self._commit_lock:
                    return target.commit_batch(requests)
            return target.commit_batch(requests)
        finally:
            if t0 is not None:
                owner._m_e2e.record(max(0.0, metrics_mod.now() - t0))

    def watch_register(self, key, seen_value):
        w = self.cluster.read_storage(key).watch(key, seen_value)
        fired = threading.Event()
        w.on_fire(fired.set)
        # on_fire's fired-check and its callback append are not atomic
        # against a concurrent commit's _fire (which runs on another pool
        # thread): re-checking after registration closes the window where
        # _fire iterated the callback list before ours landed
        if w.fired:
            fired.set()
        wid = next(self._watch_ids)
        now = time.monotonic()
        with self._watch_lock:
            self._watches[wid] = (w, fired, now)
            if len(self._watches) % 256 == 0:
                self._sweep_locked(now)
        return wid

    def _sweep_locked(self, now):
        """Drop aged-out watches whose client never came back for them —
        they pin both this registry and storage._watches forever
        otherwise (a disconnect leaves no signal at this layer)."""
        dead = [
            wid for wid, (_, _, born) in self._watches.items()
            if now - born > self.WATCH_TTL_S
        ]
        for wid in dead:
            del self._watches[wid]

    def _watch_fired(self, entry):
        w, fired, _ = entry
        return w.fired or fired.is_set()

    def watch_poll(self, wid):
        with self._watch_lock:
            entry = self._watches.get(wid)
            if entry is None:
                return True  # forgotten watches count as fired (re-read)
            if self._watch_fired(entry):
                del self._watches[wid]  # one-shot, like the reference
                return True
        return False

    def watch_wait(self, wid, timeout):
        with self._watch_lock:
            entry = self._watches.get(wid)
        if entry is None:
            return True
        if timeout is None or timeout > self.MAX_WATCH_WAIT_S:
            timeout = self.MAX_WATCH_WAIT_S  # a client cannot park a
            # server thread forever; waiters re-issue chunks
        entry[1].wait(timeout=timeout)
        if self._watch_fired(entry):
            with self._watch_lock:
                self._watches.pop(wid, None)
            return True
        return False


def serve_cluster(cluster, host="127.0.0.1", port=0, max_workers=16,
                  secret=None):
    """Expose a cluster on the network; returns the RpcServer. Also
    attaches the log-feed endpoints storage-worker processes pull from
    (rpc/storageworker.py). ``secret`` enables the transport's
    shared-secret handshake — required before listening on a
    non-loopback interface (the surface includes management access)."""
    from foundationdb_tpu.rpc.storageworker import LogFeed

    # test/bench chaos arming by knob: a non-empty seed wraps every NEW
    # client socket this process opens in the seeded fault injector
    # (rpc/chaos.py stays un-imported on the default "" path)
    chaos_seed = getattr(cluster.knobs, "rpc_chaos_seed", "")
    if chaos_seed:
        from foundationdb_tpu.rpc import chaos

        chaos.arm(chaos_seed)
    service = ClusterService(cluster)
    server = RpcServer(host, port, service.handlers(),
                       max_workers=max_workers,
                       long_methods={"watch_wait"}, secret=secret)
    # tlog_peek long-polls; it must not occupy the short-RPC pool
    server.add_handlers(LogFeed(cluster).handlers(),
                        long_methods={"tlog_peek"})
    TraceEvent("RpcServerStarted").detail(address=server.address).log()
    return server


# ───────────────────────────── client side ───────────────────────────────
# RPC deadline classes: every method maps to one of the four per-class
# deadline knobs (rpc_deadline_*_s). Unlisted methods are admin-class —
# management/status calls tolerate the longest bound. watch_wait blocks
# server-side in 5s chunks, safely under the admin deadline.
_RPC_CLASS = {
    "storage_get": "read",
    "resolve_selector": "read",
    "get_range": "read",
    "read_batch": "read",
    "ping": "read",
    "get_read_version": "grv",
    "commit": "commit",
    "commit_batch": "commit",
}


def _class_deadline(knobs, rpc_class):
    return {
        "read": knobs.rpc_deadline_read_s,
        "grv": knobs.rpc_deadline_grv_s,
        "commit": knobs.rpc_deadline_commit_s,
        "admin": knobs.rpc_deadline_admin_s,
    }[rpc_class]
class _RemoteWatch:
    """Client handle satisfying the Watch surface _WatchHandle polls."""

    __slots__ = ("_rc", "_wid", "_fired")

    def __init__(self, rc, wid):
        self._rc = rc
        self._wid = wid
        self._fired = False

    @property
    def fired(self):
        if not self._fired:
            try:
                self._fired = bool(self._rc._call("watch_poll", self._wid))
            except ConnectionLost:
                # server gone: treat as fired so the waiter re-reads (and
                # gets the real error from the read path)
                self._fired = True
        return self._fired

    def wait_remote(self, timeout=None):
        """Block until fired, in bounded server-side chunks (a pool worker
        on the server blocks for at most CHUNK_S per RPC, so parked
        watches cannot starve the handler pool)."""
        CHUNK_S = 5.0
        deadline = None if timeout is None else time.monotonic() + timeout
        while not self._fired:
            chunk = CHUNK_S
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                chunk = min(chunk, remaining)
            try:
                self._fired = bool(
                    self._rc._call("watch_wait", self._wid, chunk)
                )
            except ConnectionLost:
                self._fired = True  # server gone: re-read via the read path
        return True


class _RemoteChangeFeeds:
    """Client stub for the change-feed registry endpoints."""

    __slots__ = ("_rc",)

    def __init__(self, rc):
        self._rc = rc

    def register(self, feed_id, begin, end):
        return self._rc._call("feed_register", feed_id, begin, end)

    def read(self, feed_id, begin_version, end_version=None, limit=0):
        return self._rc._call(
            "feed_read", feed_id, begin_version, end_version, limit
        )

    def pop(self, feed_id, version):
        return self._rc._call("feed_pop", feed_id, version)

    def deregister(self, feed_id):
        return self._rc._call("feed_deregister", feed_id)

    def list(self):
        return self._rc._call("feed_list")


class _RemoteGrvProxy:
    __slots__ = ("_rc",)

    def __init__(self, rc):
        self._rc = rc

    def get_read_version(self, priority="default", tags=()):
        return self._rc._call("get_read_version", priority, tuple(tags))


class _CoalescingGrvProxy:
    """Client-side read-version batching (ref: NativeAPI's
    readVersionBatcher): concurrent default-priority transactions share
    GRV RPCs instead of paying one wire round trip each. A request
    rides the NEXT rpc to START after it arrives — a version granted by
    an rpc already in flight could miss a commit that completed after
    that rpc began, which would break external consistency."""

    __slots__ = ("_rc", "_cond", "_started", "_done", "_last", "_leader",
                 "_max_wanted")

    def __init__(self, rc):
        self._rc = rc
        self._cond = lockdep.condition("_CoalescingGrvProxy._cond")
        self._started = 0  # GRV rounds begun
        self._done = 0  # GRV rounds completed
        self._last = None  # value of the newest completed round
        self._max_wanted = 0
        self._leader = False

    def get_read_version(self, priority="default", tags=()):
        if tags or priority != "default":
            # tagged/priority requests carry their own admission
            # semantics: never coalesced into an untagged round
            return self._rc._call("get_read_version", priority,
                                  tuple(tags))
        cond = self._cond
        with cond:
            if self._leader:
                want = self._started + 1  # the NEXT round covers me
                if want > self._max_wanted:
                    self._max_wanted = want
                cond.wait_for(lambda: self._done >= want)
                v = self._last
                if v is not None:
                    return v
                # my round's rpc failed: fall through to a direct call
            else:
                self._leader = True
                want = None
        if want is not None:
            return self._rc._call("get_read_version", "default", ())
        # leader: run rounds until no one is waiting for a newer one
        while True:
            with cond:
                self._started += 1
            try:
                v = self._rc._call("get_read_version", "default", ())
            except BaseException:
                with cond:
                    # release EVERY registered waiter, not just the next
                    # round's: no leader survives to run later rounds,
                    # so a waiter parked on want > done+1 would hang
                    # forever (round-5 review). They see _last None and
                    # fall back to direct calls.
                    self._done = max(self._done + 1, self._max_wanted)
                    self._started = self._done
                    self._last = None
                    self._leader = False
                    cond.notify_all()
                raise
            with cond:
                self._done += 1
                self._last = v
                cond.notify_all()
                # exit decision under the SAME lock registrations take:
                # either a waiter already wants a newer round (loop) or
                # later arrivals will see _leader False and lead
                if self._max_wanted <= self._done:
                    self._leader = False
                    return v


class _RemoteCommitProxy:
    __slots__ = ("_rc",)

    def __init__(self, rc):
        self._rc = rc

    @property
    def knobs(self):
        # the client-side BatchingCommitProxy wrapper sizes its batches
        # from the SERVER's knobs
        return self._rc.knobs

    def commit(self, request):
        try:
            return self._rc._call_once("commit", request)
        except ConnectionLost:
            # the request may have reached the server: 1021, not a retry
            return FDBError.from_name("commit_unknown_result")
        except FDBError as e:
            if e.code != 1021:
                raise
            # deadline-expired commit (converted in _call_once): same
            # maybe-committed contract, returned as a verdict because
            # the proxy surface never raises
            return e

    def commit_batch(self, requests):
        try:
            return self._rc._call_once("commit_batch", list(requests))
        except ConnectionLost:
            return [FDBError.from_name("commit_unknown_result")
                    for _ in requests]
        except FDBError as e:
            if e.code != 1021:
                raise
            return [FDBError.from_name("commit_unknown_result")
                    for _ in requests]


class _RemoteStorage:
    """Read-side surface (router analog) over the wire.

    With worker read-balancing enabled (``RemoteCluster(...,
    read_workers=True)``), reads round-robin across the lead and any
    registered storage-worker processes (ref: LoadBalance over storage
    interfaces); a worker that vanishes is dropped and the read retried
    on the lead. Watches and writes always go to the lead.
    """

    __slots__ = ("_rc",)

    def __init__(self, rc):
        self._rc = rc

    def _read(self, method, *args, span=None):
        from foundationdb_tpu.rpc.transport import RemoteError

        worker = self._rc._next_worker(span)
        if worker is not None:
            try:
                result = worker.call(
                    method, *args,
                    deadline_s=self._rc._deadline_for(method),
                )
                self._rc._worker_ok(worker)
                return result
            except DeadlineExceeded:
                # the worker is wedged, not dead: with the monitor on,
                # mark it — the router skips it until a half-open probe
                # clears; every other caller pays NOTHING. Monitor off
                # (the pre-monitor behavior): it stays in rotation and
                # each round-robin hit re-pays the deadline.
                if self._rc._monitor_enabled():
                    failuremon.monitor().note_timeout(
                        f"{worker.host}:{worker.port}",
                        f"{method} deadline",
                    )
            except (ConnectionLost, OSError, RemoteError):
                # dead socket OR a handler that faults server-side: this
                # worker is not serving; stop routing to it
                self._rc._drop_worker(worker)
            except FDBError as e:
                if e.code != 1009:
                    raise
                # future_version = the worker is lagging. Serve this read
                # from the lead; a worker that keeps lagging (frozen tail
                # thread) strikes out and is dropped rather than adding a
                # version-wait stall to every round-robin hit forever.
                self._rc._worker_strike(worker)
        return self._rc._call(method, *args)

    def get(self, key, rv):
        return self._read("storage_get", key, rv,
                          span=(key, key + b"\x00"))

    def resolve_selector(self, selector, rv):
        # selectors can walk past their anchor key: only a worker
        # serving the WHOLE keyspace may resolve one (span=None)
        return self._read("resolve_selector", selector, rv)

    def get_range(self, begin, end, rv, limit=0, reverse=False):
        return self._read("get_range", begin, end, rv, limit, reverse,
                          span=(begin, end))

    # ── async forms: futures settled by the connection's ReadBatcher
    # (txn/futures.py) — N outstanding reads ride one read_batch RPC ──
    def get_async(self, key, rv, finalize=None, ctx=None):
        b = self._rc.read_batcher
        fut = FutureValue(batcher=b, finalize=finalize)
        b.submit(("g", key, rv), fut, ctx)
        return fut

    def get_range_async(self, begin, end, rv, limit=0, reverse=False,
                        finalize=None, ctx=None):
        b = self._rc.read_batcher
        fut = FutureRange(batcher=b, finalize=finalize)
        b.submit(("r", begin, end, rv, limit, reverse), fut, ctx)
        return fut

    def resolve_selector_async(self, selector, rv, finalize=None,
                               ctx=None):
        b = self._rc.read_batcher
        fut = FutureValue(batcher=b, finalize=finalize)
        b.submit(("s", selector, rv), fut, ctx)
        return fut

    def watch(self, key, seen_value):
        wid = self._rc._call("watch_register", key, seen_value)
        return _RemoteWatch(self._rc, wid)


class RemoteCluster:
    """The client-side cluster: same attribute surface as
    server.cluster.Cluster, every role call an RPC."""

    def __init__(self, addresses, connect_timeout=5.0, read_workers=False,
                 secret=None, commit_pipeline="sync",
                 commit_batch_max=None):
        if isinstance(addresses, str):
            addresses = [addresses]
        self.addresses = list(addresses)
        self._connect_timeout = connect_timeout
        self._secret = secret
        self._lock = lockdep.lock("RemoteCluster._lock")
        self._client = None
        self._closed = False
        self._knobs = None
        self._workers = []  # RpcClients to storage-worker processes
        self._worker_rr = 0
        self._worker_strikes = {}  # client -> consecutive 1009 lags
        self._read_batcher = None  # lazy: built on first async read
        # jittered reconnect pacing shared by every idempotent retry on
        # this handle (flow Backoff parity; reset on success)
        self._reconnect_backoff = Backoff(initial_s=0.01, max_s=0.5)
        self.grv_proxy = _RemoteGrvProxy(self)
        self.commit_proxy = _RemoteCommitProxy(self)
        self.change_feeds = _RemoteChangeFeeds(self)
        self._storage = _RemoteStorage(self)
        self._connect()
        # keepalive pinger: probes links that have gone quiet so the
        # failure monitor learns about a wedged peer from the ping, not
        # from the next real request's deadline (ref: FailureMonitor's
        # ping loop). Cadence is jittered off the "ping-cadence" named
        # stream; rpc_ping_interval_s == 0 disables the thread.
        self._ping_stop = threading.Event()
        self._ping_thread = None
        if DEFAULT_KNOBS.rpc_ping_interval_s > 0:
            self._ping_thread = threading.Thread(
                target=self._ping_loop, name="rpc-keepalive", daemon=True
            )
            self._ping_thread.start()
        self.commit_pipeline = commit_pipeline
        if commit_pipeline == "thread":
            # concurrent client threads share GRV rounds too (ref:
            # NativeAPI batching read-version requests)
            self.grv_proxy = _CoalescingGrvProxy(self)
            # CLIENT-side commit batching (ref: NativeAPI batching
            # commits toward the proxies): concurrent transactions in
            # this process share commit_batch RPCs — one wire round
            # trip per WINDOW instead of per commit, which is what
            # makes a multi-process deployment throughput-bound on the
            # server pipeline rather than on per-commit RTTs. Also
            # enables commit_async (submit) against remote clusters.
            from foundationdb_tpu.server.batcher import BatchingCommitProxy

            self.commit_proxy = BatchingCommitProxy(
                self.commit_proxy, max_batch=commit_batch_max,
            )
        if read_workers:
            self.refresh_workers()

    @classmethod
    def from_cluster_file(cls, path, **kw):
        _, _, addresses = parse_cluster_file(path)
        return cls(addresses, **kw)

    def _ping_loop(self):
        rng = deterministic.rng("ping-cadence")
        while True:
            interval = self._effective_knobs().rpc_ping_interval_s
            if interval <= 0:
                # knob disabled server-side: stay parked but re-check
                if self._ping_stop.wait(2.0):
                    return
                continue
            # jittered cadence (0.5x..1.5x) so a fleet of clients does
            # not ping a server in lockstep; the draw rides the named
            # stream, so seeded runs schedule identically
            if self._ping_stop.wait(interval * (0.5 + rng.random())):
                return
            try:
                self._ping_idle_links(interval)
            except Exception as e:
                # the pinger is advisory: it must never kill itself —
                # a failed probe round just runs again next tick
                TraceEvent("KeepalivePingRoundFailed",
                           severity=SEV_ERROR).detail(
                    error=type(e).__name__).log()

    def _ping_idle_links(self, interval):
        from foundationdb_tpu.rpc.transport import RemoteError

        if not self._monitor_enabled():
            return
        with self._lock:
            clients = [self._client] + [c for c, _ in self._workers]
        mon = failuremon.monitor()
        for c in clients:
            if c is None or not c.alive:
                continue
            if time.monotonic() - c.last_activity < interval:
                continue  # link is carrying traffic; liveness is known
            addr = f"{c.host}:{c.port}"
            try:
                c.call("ping",
                       deadline_s=min(1.0, self._deadline_for("ping")))
                mon.mark_ok(addr)
            except DeadlineExceeded:
                mon.note_timeout(addr, "keepalive ping")
            except (ConnectionLost, OSError) as e:
                mon.mark_failed(addr, f"keepalive: {e}")
            except RemoteError:
                pass  # peer predates the ping endpoint: no health signal

    def _connect(self):
        with self._lock:
            if self._closed:
                # a closed handle must stay closed: a racing waiter thread
                # must not silently resurrect the connection
                raise ConnectionLost("RemoteCluster is closed")
            if self._client is not None and self._client.alive:
                return self._client
            if self._client is not None:
                self._client.close()  # release the dead socket's fd
            self._client = connect_any(
                self.addresses, self._connect_timeout, secret=self._secret
            )
            try:
                # the admin deadline bounds the handshake: a freshly
                # accepted but black-holed connection must surface as
                # unreachable, not park the caller forever
                hello = self._client.call(
                    "hello", PROTOCOL_VERSION,
                    deadline_s=self._deadline_for("hello"),
                )
            except DeadlineExceeded as e:
                self._client.close()
                raise ConnectionLost(
                    f"handshake with {self._client.host}:"
                    f"{self._client.port} timed out: {e}"
                ) from e
            generation = hello["generation"]
            prior = getattr(self, "server_generation", None)
            if prior is not None and generation != prior:
                # the cluster recovered behind our back: cached knobs may
                # be stale. Read versions pinned before the recovery need
                # no client-side fencing — the recovered storage rejects
                # them TOO_OLD server-side.
                self._knobs = None
                TraceEvent("ClusterGenerationChanged").detail(
                    old=prior, new=generation).log()
            self.server_generation = generation
            return self._client

    def _effective_knobs(self):
        """Cached server knobs when we have them, DEFAULT_KNOBS before —
        NEVER the ``knobs`` property: the deadline for the knobs fetch
        itself must not recurse into a knobs fetch."""
        return self._knobs if self._knobs is not None else DEFAULT_KNOBS

    def _deadline_for(self, method):
        return _class_deadline(
            self._effective_knobs(), _RPC_CLASS.get(method, "admin")
        )

    def _monitor_enabled(self):
        kn = self._effective_knobs()
        return kn.failure_monitor

    def _call_once(self, method, *args):
        """One attempt, no reconnect — the commit path's no-double-send
        rule. Every attempt carries its class deadline; an expiry is
        converted here: commit-class → commit_unknown_result (1021, the
        request MAY have reached the server), anything else →
        process_behind (1037, plainly retryable) — and the endpoint is
        marked in the failure monitor either way."""
        client = self._client
        if client is None or not client.alive:
            client = self._connect()
        addr = f"{client.host}:{client.port}"
        try:
            result = client.call(
                method, *args, deadline_s=self._deadline_for(method)
            )
        except DeadlineExceeded as e:
            failuremon.monitor().note_timeout(addr, f"{method} deadline")
            if client.deadline_strikes >= WEDGED_STRIKE_LIMIT:
                # a black-holed link looks exactly like a slow one until
                # several consecutive deadlines expire with no frame in
                # either direction: stop re-paying the deadline on every
                # retry — kill the socket so the NEXT attempt reconnects
                # fresh (connection-level escape; the retry itself still
                # belongs to the caller's on_error loop)
                TraceEvent("RpcLinkWedged", severity=SEV_ERROR).detail(
                    address=addr, method=method,
                    strikes=client.deadline_strikes).log()
                client.close()
            if _RPC_CLASS.get(method, "admin") == "commit":
                raise FDBError.from_name("commit_unknown_result") from e
            raise FDBError.from_name("process_behind") from e
        except (ConnectionLost, OSError) as e:
            failuremon.monitor().mark_failed(addr, f"{method}: {e}")
            raise ConnectionLost(str(e)) from e
        failuremon.monitor().mark_ok(addr)
        return result

    def _call(self, method, *args):
        """Idempotent call: one transparent reconnect+retry (reads, GRVs,
        watches are all safe to re-send), with a jittered backoff sleep
        before the reconnect so a fleet of clients doesn't stampede a
        recovering server (flow Backoff parity; resets on success)."""
        try:
            result = self._call_once(method, *args)
        except ConnectionLost:
            self._reconnect_backoff.sleep()
            self._connect()  # raises ConnectionLost if nobody is reachable
            result = self._call_once(method, *args)
        self._reconnect_backoff.reset()
        return result

    @property
    def knobs(self):
        if self._knobs is None:
            self._knobs = Knobs(**self._call("knobs"))
        return self._knobs

    @property
    def read_batcher(self):
        """This connection's read multiplexer (txn/futures.py), built
        lazily so read-free clients never pay the knobs fetch or the
        flusher thread. Thread-mode pipelines get the windowed flusher;
        sync/manual flush synchronously inside submit (deterministic —
        a sim's RPC sequence is a pure function of its schedule)."""
        rb = self._read_batcher
        if rb is not None:
            return rb
        kn = self.knobs  # outside _lock: _call reconnects under it
        from foundationdb_tpu.txn.futures import ReadBatcher

        with self._lock:
            if self._read_batcher is None:
                self._read_batcher = ReadBatcher(
                    self._send_read_batch,
                    max_keys=kn.read_batch_max_keys,
                    window_s=kn.read_batch_window_ms / 1e3,
                    thread=(self.commit_pipeline == "thread"),
                    # a batch retried once on the lead may pay the read
                    # deadline twice before the watchdog should step in
                    deadline_s=2 * kn.rpc_deadline_read_s,
                )
            return self._read_batcher

    @staticmethod
    def _batch_span(ops):
        """Bounding [begin, end) of a batch's ops, or None when any op
        needs full keyspace coverage (selectors walk) — the coverage
        key for routing a whole batch at one tag-scoped worker."""
        lo = hi = None
        for op in ops:
            if op[0] == "g":
                b, e = op[1], op[1] + b"\x00"
            elif op[0] == "r" and isinstance(op[1], bytes) \
                    and isinstance(op[2], bytes):
                b, e = op[1], op[2]
            else:
                return None
            if lo is None or b < lo:
                lo = b
            if hi is None or e > hi:
                hi = e
        return None if lo is None else (lo, hi)

    def _send_read_batch(self, ops):
        """One multiplexed read RPC (the ReadBatcher's send): worker
        round-robin by the batch's bounding span; a lagging worker's
        per-op 1009 slots are re-served from the lead and the worker
        strikes (the _RemoteStorage._read policy, batch-shaped)."""
        from foundationdb_tpu.rpc.transport import RemoteError

        ops = list(ops)
        worker = self._next_worker(self._batch_span(ops))
        if worker is not None:
            try:
                slots = worker.call(
                    "read_batch", ops,
                    deadline_s=self._deadline_for("read_batch"),
                )
            except DeadlineExceeded:
                # wedged worker: mark (monitor on) and serve the whole
                # batch from the lead — same policy as _RemoteStorage
                if self._monitor_enabled():
                    failuremon.monitor().note_timeout(
                        f"{worker.host}:{worker.port}",
                        "read_batch deadline",
                    )
            except (ConnectionLost, OSError, RemoteError):
                self._drop_worker(worker)
            else:
                lagging = [
                    i for i, s in enumerate(slots)
                    if isinstance(s, FDBError) and s.code == 1009
                ]
                if not lagging:
                    self._worker_ok(worker)
                    return slots
                self._worker_strike(worker)
                redo = self._call(
                    "read_batch", [ops[i] for i in lagging]
                )
                for i, slot in zip(lagging, redo):
                    slots[i] = slot
                return slots
        return self._call("read_batch", ops)

    def read_storage(self, key=b""):
        return self._storage

    def status(self):
        return self._call("status")

    def metrics_status(self):
        return self._call("metrics")

    def health_status(self):
        doc = self._call("health")
        # overlay THIS client's endpoint-health view (the server's own
        # monitor can't see our links): states + counters only
        if isinstance(doc, dict):
            doc["rpc_client"] = failuremon.monitor().snapshot()
        return doc

    def hot_ranges_status(self, top=None):
        return self._call("metrics_hot", top)

    def device_profile_status(self):
        return self._call("device_profile")

    def history_status(self):
        return self._call("history")

    def flight_status(self):
        return self._call("flight")

    def consistency_scan_status(self):
        return self._call("consistency_scan")

    def set_consistency_scan(self, on):
        return self._call("set_consistency_scan", bool(on))

    # management surface (the special key space's commit-time handles)
    def exclude_storage(self, sid):
        return self._call("exclude_storage", sid)

    def include_storage(self, sid):
        return self._call("include_storage", sid)

    def list_excluded(self):
        return self._call("list_excluded")

    def consistency_check(self, max_keys_per_shard=None):
        return self._call("consistency_check", max_keys_per_shard)

    def estimated_range_size_bytes(self, begin, end):
        return self._call("estimated_range_size", begin, end)

    def range_split_points(self, begin, end, chunk_size):
        return self._call("range_split_points", begin, end, chunk_size)

    def lock_database(self, uid=b"lock"):
        return self._call("lock_database", uid)

    def unlock_database(self):
        return self._call("unlock_database")

    def lock_uid(self):
        return self._call("lock_uid")

    def set_tenant_mode(self, mode):
        return self._call("set_tenant_mode", mode)

    def configure(self, commit_proxies=None, resolvers=None):
        return self._call("configure", commit_proxies, resolvers)

    def tenant_mode(self):
        return self._call("tenant_mode")

    def set_tag_quota(self, tag, tps):
        return self._call("set_tag_quota", tag, tps)

    def tracing_config(self):
        return self._call("tracing_config")

    def set_tracing(self, sample_rate=None, enabled=None):
        out = self._call("set_tracing", sample_rate, enabled)
        # the sampling knob lives server-side in the knobs doc: drop the
        # cached copy so this client's next transaction sees the change
        self._knobs = None
        return out

    # ── storage-worker read balancing ──
    def refresh_workers(self):
        """Discover registered storage-worker processes and open read
        connections (round-robined with the lead thereafter). Each
        entry may carry the worker's served key ranges (tag-scoped
        workers — rpc/storageworker.py); reads route by coverage."""
        from foundationdb_tpu.rpc.transport import connect_any

        entries = self._call("list_workers")
        clients = []
        addresses = []
        for entry in entries:
            if isinstance(entry, (list, tuple)):
                addr, ranges = entry
                ranges = ([tuple(r) for r in ranges]
                          if ranges is not None else None)
            else:  # legacy bare-address registration
                addr, ranges = entry, None
            addresses.append(addr)
            try:
                clients.append((connect_any(
                    [addr], self._connect_timeout, secret=self._secret
                ), ranges))
            except ConnectionLost:
                continue
        with self._lock:
            old, self._workers = self._workers, clients
            for c, _ in old:
                self._worker_strikes.pop(c, None)
            # retire rather than close: a concurrent reader may be
            # mid-call on an old client — closing now would abort a
            # healthy read. Retired clients close on the NEXT refresh
            # (in-flight calls are long finished by then) or at close().
            retiring, self._retired_workers = (
                getattr(self, "_retired_workers", []), [c for c, _ in old]
            )
        for c in retiring:
            c.close()
        return addresses

    @staticmethod
    def _covers(ranges, span):
        """Whether a worker serving ``ranges`` can answer a read over
        ``span`` ([begin, end), or None = requires the full keyspace).
        Ranges arrive merged, so containment in ONE range suffices."""
        if ranges is None:
            return True
        if span is None:
            return False
        b, e = span
        return any(rb <= b and e <= re_ for rb, re_ in ranges)

    def _next_worker(self, span=None):
        """Round-robin over lead + covering workers: returns None for
        'the lead's turn' (callers fall through to _call). With the
        failure monitor on, known-failed workers are skipped instead of
        serially timed out against — except for the one caller per probe
        window that ``available`` elects to carry the recovery probe."""
        monitor_on = self._monitor_enabled()
        mon = failuremon.monitor() if monitor_on else None
        with self._lock:
            eligible = [
                c for c, ranges in self._workers
                if self._covers(ranges, span)
                and (mon is None or mon.available(f"{c.host}:{c.port}"))
            ]
            if not eligible:
                return None
            self._worker_rr = (self._worker_rr + 1) % (len(eligible) + 1)
            if self._worker_rr == 0:
                return None
            return eligible[self._worker_rr - 1]

    def _drop_worker(self, client):
        with self._lock:
            self._workers = [
                (c, r) for c, r in self._workers if c is not client
            ]
            self._worker_strikes.pop(client, None)
        client.close()

    WORKER_STRIKE_LIMIT = 3
    WORKER_REFRESH_MIN_S = 1.0

    def _worker_ok(self, client):
        with self._lock:
            self._worker_strikes.pop(client, None)
        # a successful read doubles as the recovery probe's verdict
        failuremon.monitor().mark_ok(f"{client.host}:{client.port}")

    def _worker_strike(self, client):
        with self._lock:
            n = self._worker_strikes.get(client, 0) + 1
            self._worker_strikes[client] = n
        if n >= self.WORKER_STRIKE_LIMIT:
            self._drop_worker(client)
            # A struck-out worker may be healthy with a STALE coverage
            # map on our side: a DD move makes its ownership backstop
            # answer 1009 for spans we still think it serves. Re-snapshot
            # the registry (throttled) so workers rejoin with fresh
            # ranges instead of staying evicted for the session.
            now = time.monotonic()
            if now - getattr(self, "_last_worker_refresh", 0.0) \
                    >= self.WORKER_REFRESH_MIN_S:
                self._last_worker_refresh = now
                try:
                    self.refresh_workers()
                except (ConnectionLost, OSError):
                    pass  # lead unreachable: reads already fall back

    def connection_string(self):
        return ",".join(self.addresses)

    def database(self):
        from foundationdb_tpu.txn.database import Database

        return Database(self)

    def close(self):
        self._ping_stop.set()
        if self._ping_thread is not None:
            self._ping_thread.join(timeout=1)
        rb = self._read_batcher
        if rb is not None:
            rb.close()  # settles queued reads retryably (FL002)
        if hasattr(self.commit_proxy, "close"):
            self.commit_proxy.close()  # client-side batcher thread
        with self._lock:
            self._closed = True
            if self._client is not None:
                self._client.close()
                self._client = None
            workers, self._workers = self._workers, []
            retired = getattr(self, "_retired_workers", [])
            self._retired_workers = []
        for c, _ in workers:
            c.close()
        for c in retired:
            c.close()
