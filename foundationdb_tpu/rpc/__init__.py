"""fdbrpc analog: wire serialization, framed transport, endpoints, and
the client↔server process model (ref: fdbrpc/FlowTransport.actor.cpp,
fdbrpc/fdbrpc.h). The deterministic simulation keeps its own in-process
message model (sim/network.py); this package is the REAL network."""

from foundationdb_tpu.rpc.service import (  # noqa: F401
    ClusterService,
    RemoteCluster,
    parse_cluster_file,
    serve_cluster,
    write_cluster_file,
)
from foundationdb_tpu.rpc.transport import RpcClient, RpcServer  # noqa: F401
