"""Coordinators over the network: the disk-Paxos quorum as real
processes.

Ref parity: fdbserver/Coordination.actor.cpp — coordinators are
standalone processes named in the cluster file; the recovering master
reaches them over the transport to read and lock the coordinated
state. `CoordinatorService` exposes one disk-backed Coordinator replica
as RPC endpoints; `RemoteCoordinator` is the proposer-side stub whose
connection failures ARE the unreachable-coordinator signal
(CoordinatorDown), so `CoordinationQuorum` runs unchanged over any mix
of local and remote replicas — majorities tolerate minority process
death exactly as in-process quorums tolerate killed replicas.

Ballot striding across independent proposer processes uses a random
64-bit proposer id with a 2^64 stride: ballots never collide without
needing the proposers to know each other. The id is drawn from the
injectable determinism registry (core/deterministic.py) so a seeded
simulation replays the same proposer ids run after run.
"""

from foundationdb_tpu.core import deterministic
from foundationdb_tpu.rpc.transport import (
    ConnectionLost,
    RemoteError,
    RpcClient,
)
from foundationdb_tpu.server.coordination import (
    Coordinator,
    CoordinationQuorum,
    CoordinatorDown,
)

BALLOT_STRIDE = 1 << 64


def draw_proposer_id():
    """A fresh 64-bit proposer id from the injected entropy stream —
    deterministic under a sim seed, OS-random in production."""
    return deterministic.rng("proposer-id").getrandbits(64)


class CoordinatorService:
    """RPC endpoint table over one Coordinator replica (runs inside an
    fdbserver-style process; see tools/fdbserver.py)."""

    def __init__(self, path=None):
        self.replica = Coordinator(path)

    def handlers(self):
        return {
            "coord_prepare": self.replica.prepare,
            "coord_accept": self.replica.accept,
            "coord_read": self.replica.read,
        }


class RemoteCoordinator:
    """Proposer-side stub for one coordinator process.

    Lazily (re)connects per call; any transport failure surfaces as
    CoordinatorDown, which the quorum treats as that replica being
    unreachable — a minority of dead processes is tolerated."""

    def __init__(self, address, connect_timeout=3.0, call_timeout=10.0,
                 secret=None):
        self.address = address
        self._connect_timeout = connect_timeout
        self._call_timeout = call_timeout
        self._secret = secret
        self._client = None
        self.alive = True  # parity with the in-process replica surface

    def _call(self, method, *args):
        try:
            if self._client is None or not self._client.alive:
                host, _, port = self.address.rpartition(":")
                self._client = RpcClient(
                    host, int(port), self._connect_timeout,
                    secret=self._secret,
                )
            return self._client.call(
                method, *args, timeout=self._call_timeout
            )
        except (ConnectionLost, OSError, TimeoutError, RemoteError) as e:
            # RemoteError too: a replica whose handler faults server-side
            # (full disk mid-fsync) is as unavailable as a dead one — the
            # quorum must ride over it, not crash the recovering master
            raise CoordinatorDown(
                f"coordinator {self.address} unreachable: {e}"
            ) from e

    def prepare(self, ballot):
        ok, promised, accepted, accepted_ballot = self._call(
            "coord_prepare", ballot
        )
        return ok, promised, accepted, accepted_ballot

    def accept(self, ballot, value):
        return self._call("coord_accept", ballot, value)

    def read(self):
        ballot, value = self._call("coord_read")
        return ballot, value

    def close(self):
        if self._client is not None:
            self._client.close()
            self._client = None


def remote_quorum(addresses, proposer_id=None, secret=None):
    """A CoordinationQuorum over coordinator processes at ``addresses``
    (each a ``host:port`` whose RpcServer registers CoordinatorService
    handlers). Proposer ids are drawn at random from a 64-bit space so
    independent recovering processes stride disjoint ballot sequences."""
    if proposer_id is None:
        proposer_id = draw_proposer_id()
    coords = [RemoteCoordinator(a, secret=secret) for a in addresses]
    return CoordinationQuorum(
        coords, proposer_id=proposer_id, n_proposers=BALLOT_STRIDE
    )
