"""Chaos transport — seeded fault injection on REAL sockets.

Sim chaos (sim/network.py) proves the protocol; this module proves the
LIVE stack: a wrapper around actual client sockets that injects the
failure modes a production network serves up — added latency, dropped
frames, duplicated frames, byte-trickle, close-mid-frame, and a
permanent per-connection black hole (the wedged-peer shape the deadline
sweep exists for).

Faults are BUGGIFY-site-keyed (sim/buggify.py's two-level scheme rides
the chaos seed): a site is activated for the whole run with
``SITE_ACTIVATED_P``, then fires per-send with its own probability, so
whole failure modes appear/disappear across seeds exactly like sim
BUGGIFY. The seed (``arm(seed)`` / the ``rpc_chaos_seed`` knob /
``FDB_TPU_CHAOS_SEED``) fully determines site activation and per-
connection draw streams — a failing run is reproducible from its
logged seed + activated-site list alone.

Never importable into the default path: ``transport.SOCKET_WRAP`` stays
``None`` until ``arm()`` runs; nothing imports this module otherwise.

Injection is at ``sendall`` granularity — transport sends exactly one
frame per ``sendall`` — so every fault is a whole-frame event except
``close_mid_frame``/``trickle``, which deliberately split one. The
first few sends of a connection (the auth handshake) are exempt: chaos
targets the steady-state RPC path, not connection establishment.
"""

import random
import time
import zlib

from foundationdb_tpu.rpc import transport
from foundationdb_tpu.sim.buggify import Buggify
from foundationdb_tpu.utils import lockdep
from foundationdb_tpu.utils.trace import TraceEvent

# (site, per-send fire probability) — activation per run is two-level
SITES = (
    ("chaos.delay", 0.10),
    ("chaos.drop_frame", 0.05),
    ("chaos.dup_frame", 0.05),
    ("chaos.trickle", 0.05),
    ("chaos.close_mid_frame", 0.02),
    ("chaos.blackhole", 0.01),
)
SITE_ACTIVATED_P = 0.75
_FIRE_P = dict(SITES)
# auth handshake frames (proof + confirmation ack) pass untouched
_HANDSHAKE_GRACE_SENDS = 2


class _ChaosState:
    def __init__(self, seed):
        self.seed = str(seed)
        # Buggify wants an integer seed; the knob/env accepts any
        # string, so fold it through a stable checksum (NOT hash():
        # PYTHONHASHSEED would break seed-reproducibility)
        self.bug = Buggify(
            seed=zlib.crc32(self.seed.encode()), enabled=True,
            site_activated_p=SITE_ACTIVATED_P,
        )
        # pre-touch every site (fire_p=0 never fires) so
        # activated_sites() is complete the moment chaos arms — the
        # run's log line carries the full reproduction recipe up front
        for site, _p in SITES:
            self.bug(site, fire_p=0.0)
        self._lock = lockdep.lock("chaos._ChaosState._lock")
        self._conn_count = 0
        self.stats = {}  # site -> injection count

    def next_conn(self):
        with self._lock:
            self._conn_count += 1
            return self._conn_count

    def note(self, site):
        with self._lock:
            self.stats[site] = self.stats.get(site, 0) + 1

    def snapshot(self):
        with self._lock:
            return dict(sorted(self.stats.items()))


_state = None  # set by arm()


class ChaosSocket:
    """Fault-injecting proxy over one real client socket.

    Only ``sendall`` is intercepted; everything else (recv, timeouts,
    close, shutdown) delegates, so the transport's framing, auth, and
    deadline machinery run unmodified against the injected faults.
    """

    def __init__(self, sock, address, state):
        self._sock = sock
        self._address = address
        self._chaos = state
        conn = state.next_conn()
        # per-connection draw stream derived from (seed, conn index):
        # deterministic given the seed and connection order
        self._rng = random.Random(f"{state.seed}:conn:{conn}")
        self._sends = 0
        self._blackholed = False

    def __getattr__(self, name):
        return getattr(self._sock, name)

    def sendall(self, data):
        self._sends += 1
        if self._sends <= _HANDSHAKE_GRACE_SENDS:
            return self._sock.sendall(data)
        bug, rng = self._chaos.bug, self._rng
        if self._blackholed:
            self._chaos.note("chaos.blackhole")
            return None  # swallowed; the peer never hears from us again
        if bug("chaos.blackhole", fire_p=_FIRE_P["chaos.blackhole"]):
            self._blackholed = True
            self._chaos.note("chaos.blackhole")
            return None
        if bug("chaos.close_mid_frame",
               fire_p=_FIRE_P["chaos.close_mid_frame"]):
            self._chaos.note("chaos.close_mid_frame")
            half = max(1, len(data) // 2)
            try:
                self._sock.sendall(data[:half])
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass
            raise ConnectionResetError(
                f"chaos: closed {self._address} mid-frame")
        if bug("chaos.drop_frame", fire_p=_FIRE_P["chaos.drop_frame"]):
            self._chaos.note("chaos.drop_frame")
            return None  # frame lost; the request's deadline will fire
        if bug("chaos.delay", fire_p=_FIRE_P["chaos.delay"]):
            self._chaos.note("chaos.delay")
            time.sleep(rng.uniform(0.001, 0.03))
        if bug("chaos.trickle", fire_p=_FIRE_P["chaos.trickle"]):
            self._chaos.note("chaos.trickle")
            step = rng.randint(3, 17)
            for i in range(0, len(data), step):
                self._sock.sendall(data[i:i + step])
                time.sleep(0.0002)
            return None
        self._sock.sendall(data)
        if bug("chaos.dup_frame", fire_p=_FIRE_P["chaos.dup_frame"]):
            # the peer sees the same request seq twice — idempotency,
            # not luck, must prevent double-apply
            self._chaos.note("chaos.dup_frame")
            self._sock.sendall(data)
        return None


def arm(seed):
    """Arm chaos: every NEW client socket gets the seeded injector."""
    global _state
    _state = _ChaosState(seed)
    transport.SOCKET_WRAP = (
        lambda sock, address: ChaosSocket(sock, address, _state)
    )
    TraceEvent("ChaosArmed").detail(
        seed=_state.seed,
        activated_sites=",".join(_state.bug.activated_sites()),
    ).log()
    return _state


def disarm():
    """Back to the clean transport (existing wrapped sockets keep
    their injectors until those connections die)."""
    global _state
    transport.SOCKET_WRAP = None
    _state = None


def armed():
    return _state is not None


def activated_sites():
    return _state.bug.activated_sites() if _state is not None else []


def stats():
    return _state.snapshot() if _state is not None else {}
