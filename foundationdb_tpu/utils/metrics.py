"""Cluster metrics: counters, gauges, and latency bands per role.

Ref parity: fdbserver/Stats.h (CounterCollection, LatencySample,
LatencyBands) + the per-role metrics that Status.actor.cpp aggregates
into the status json document. Every role owns a named
:class:`MetricsRegistry`; hot paths record through pre-resolved handles
(one lock, a few float ops), and ``snapshot()`` produces the JSON-ready
dict that rides the role's ``status()`` RPC up into
``\\xff\\xff/status/json``.

Determinism: the registry draws its wall clock from
``core.deterministic.now()`` and the reservoir's eviction choices from
the ``metrics-reservoir`` named stream, so a seeded simulation produces
byte-identical snapshots for the same schedule (FL001: no ambient
entropy or ``time.time`` here). Durations are measured as differences
of the injected clock — under the sim's step clock a span inside one
step is exactly 0.0, which is what "deterministic latency" means there;
in production the clock is the real wall clock.

Overhead: the module-level ``set_enabled(False)`` kill switch turns
every ``record``/``inc``/``set`` into an early return — the
``BENCH_MODE=metrics_smoke`` bench runs the ycsb e2e both ways and
asserts the enabled run stays within 2% of the disabled one.
"""

import threading

from foundationdb_tpu.core import deterministic
from foundationdb_tpu.utils import lockdep

_enabled = True


def set_enabled(on):
    """Process-wide kill switch (the metrics_smoke overhead probe)."""
    global _enabled
    _enabled = bool(on)


def enabled():
    return _enabled


def now():
    """The injected clock every metric timestamp/duration uses (sim:
    the step clock; production: the wall clock)."""
    return deterministic.now()


class Counter:
    """Monotonic counter (ref: Stats.h Counter). ``inc`` is a single
    GIL-atomic add on an int — a torn read costs a momentarily stale
    snapshot, never a lost invariant, so no lock on the hot path."""

    __slots__ = ("name", "_v")

    def __init__(self, name):
        self.name = name
        self._v = 0

    def inc(self, n=1):
        if not _enabled:
            return
        self._v += n

    def add_base(self, n):
        """Fold a prior incarnation's total in (recovery carryover) —
        bypasses the kill switch: carried history is not new overhead."""
        self._v += n

    @property
    def value(self):
        return self._v


class Gauge:
    """Last-written value (ref: the status json's point-in-time gauges:
    target tps, queue depths, versions)."""

    __slots__ = ("name", "_v")

    def __init__(self, name):
        self.name = name
        self._v = 0

    def set(self, v):
        if not _enabled:
            return
        self._v = v

    @property
    def value(self):
        return self._v


class LatencySample:
    """Reservoir sample yielding p50/p90/p99/max (ref: Stats.h
    LatencySample / LatencyBands). A fixed-size reservoir keeps memory
    bounded no matter how long the run; once full, each new observation
    replaces a uniformly random slot with probability K/count — the
    classic reservoir invariant, drawn from the ``metrics-reservoir``
    deterministic stream so seeded sims replay identical samples. The
    true count/total/max are tracked exactly (percentiles come from the
    reservoir; ``max`` never lies), so p50 ≤ p90 ≤ p99 ≤ max holds by
    construction."""

    __slots__ = ("name", "_k", "_res", "_count", "_total", "_max", "_rng",
                 "_lock")

    def __init__(self, name, reservoir=512):
        self.name = name
        self._k = reservoir
        self._res = []
        self._count = 0
        self._total = 0.0
        self._max = 0.0
        self._rng = deterministic.rng("metrics-reservoir")
        self._lock = lockdep.lock("LatencySample._lock")

    def record(self, seconds):
        if not _enabled:
            return
        s = float(seconds)
        with self._lock:
            self._count += 1
            self._total += s
            if s > self._max:
                self._max = s
            if len(self._res) < self._k:
                self._res.append(s)
            else:
                j = self._rng.randrange(self._count)
                if j < self._k:
                    self._res[j] = s

    @property
    def count(self):
        return self._count

    def total_seconds(self):
        return self._total

    def _percentile(self, ordered, q):
        if not ordered:
            return 0.0
        i = min(len(ordered) - 1, int(q * (len(ordered) - 1) + 0.5))
        return ordered[i]

    def bands_ms(self):
        """{count, mean_ms, p50_ms, p90_ms, p99_ms, max_ms} — the
        latency-band snapshot every consumer (status json, bench lines)
        shares. Monotone: percentiles index one sorted reservoir and
        max is the exact running max (≥ any reservoir entry)."""
        with self._lock:
            res = sorted(self._res)
            count, total, mx = self._count, self._total, self._max
        return {
            "count": count,
            "mean_ms": round(total / count * 1e3, 3) if count else 0.0,
            "p50_ms": round(self._percentile(res, 0.50) * 1e3, 3),
            "p90_ms": round(self._percentile(res, 0.90) * 1e3, 3),
            "p99_ms": round(self._percentile(res, 0.99) * 1e3, 3),
            "max_ms": round(mx * 1e3, 3),
        }

    def absorb(self, other):
        """Fold another sample in (recovery carryover / fleet rollups):
        counts and totals add exactly; the reservoirs concatenate and
        re-trim, which keeps every percentile inside the union's true
        range (an approximation, like any reservoir)."""
        with other._lock:
            o_res = list(other._res)
            o_count, o_total, o_max = other._count, other._total, other._max
        with self._lock:
            self._count += o_count
            self._total += o_total
            self._max = max(self._max, o_max)
            self._res.extend(o_res)
            if len(self._res) > self._k:
                # deterministic trim: keep an evenly strided subset of
                # the sorted union (preserves the distribution's shape)
                merged = sorted(self._res)
                step = len(merged) / self._k
                self._res = [merged[int(i * step)] for i in range(self._k)]


def merged_bands_ms(samples):
    """One latency-band dict over several LatencySamples (fleet rollup:
    the cluster's commit p99 across every proxy)."""
    samples = [s for s in samples if s is not None]
    if not samples:
        return LatencySample("empty").bands_ms()
    acc = LatencySample(samples[0].name, reservoir=512)
    for s in samples:
        acc.absorb(s)
    return acc.bands_ms()


class MetricsRegistry:
    """Named per-role metric collection (ref: CounterCollection). Roles
    create (or are handed) one at construction; the cluster keeps
    registries ALIVE across role recruitment so recovery never rewinds
    a counter. Handles are cached by name — the hot path never pays a
    dict lookup if the caller keeps the returned object."""

    def __init__(self, role, index=0):
        self.role = role
        self.index = index
        self._lock = lockdep.lock("MetricsRegistry._lock")
        self._counters = {}
        self._gauges = {}
        self._latencies = {}

    def counter(self, name):
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name):
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name)
            return g

    def latency(self, name, reservoir=512):
        with self._lock:
            s = self._latencies.get(name)
            if s is None:
                s = self._latencies[name] = LatencySample(
                    name, reservoir=reservoir
                )
            return s

    def get_latency(self, name):
        """The sample if it exists (rollups must not create empties)."""
        return self._latencies.get(name)

    def snapshot(self):
        """JSON-ready snapshot: the role's status() RPC payload."""
        with self._lock:
            counters = {n: c.value for n, c in self._counters.items()}
            gauges = {n: g.value for n, g in self._gauges.items()}
            lats = list(self._latencies.items())
        return {
            "role": self.role,
            "id": self.index,
            "time": now(),
            "counters": counters,
            "gauges": gauges,
            "latency_ms": {n: s.bands_ms() for n, s in lats},
        }

    def absorb(self, other):
        """Fold a retiring registry's history in (a configure() that
        shrinks a fleet must not lose the orphaned members' totals)."""
        with other._lock:
            o_counters = dict(other._counters)
            o_lats = dict(other._latencies)
        for n, c in o_counters.items():
            self.counter(n).add_base(c.value)
        for n, s in o_lats.items():
            self.latency(n).absorb(s)
