"""Structured trace events — the framework's observability spine.

Ref parity: flow/Trace.cpp TraceEvent. The reference emits XML/JSON
trace files per role with severity, type, time, and arbitrary detail
fields; tooling greps them for forensics. Ours keeps the same shape
(one JSON object per line) with a process-wide sink, a per-event fluent
detail API, and severity filtering. In simulation the clock is the
simulated clock, keeping traces deterministic for a given seed.
"""

import io
import json
import os
import threading
import time

from foundationdb_tpu.utils import lockdep
from collections import deque

SEV_DEBUG = 5
SEV_INFO = 10
SEV_WARN = 20
SEV_WARN_ALWAYS = 30
SEV_ERROR = 40

_SEV_NAMES = {
    SEV_DEBUG: "debug",
    SEV_INFO: "info",
    SEV_WARN: "warn",
    SEV_WARN_ALWAYS: "warn_always",
    SEV_ERROR: "error",
}


class TraceLog:
    """Process-wide sink for TraceEvents (ref: g_traceLog).

    File sinks ROLL (ref: flow/Trace.cpp rolled trace files): when the
    open file passes ``max_file_bytes``, it rotates to ``path.1`` (older
    rolls shift to ``.2`` … ``.roll_count``, the oldest is deleted) so a
    long bench or sim run never grows one unbounded file. The in-memory
    ring buffer is kept ALONGSIDE any open file sink, so ``events()``
    keeps working for tests even when a path is set.
    """

    def __init__(self, path=None, min_severity=SEV_INFO, clock=time.time,
                 max_file_bytes=None, roll_count=None, type_budget=None,
                 suppression_interval_s=None):
        self._lock = lockdep.lock("TraceLog._lock")
        self._path = path
        self._file = None
        self._file_bytes = 0
        self.max_buffered = 10_000
        # a bounded deque IS the ring: append past maxlen evicts the
        # oldest in O(1) (the old list-trim was O(n) per hot event)
        self._buffer = deque(maxlen=self.max_buffered)
        self.min_severity = min_severity
        self.clock = clock
        self.closed = False
        self.max_file_bytes = (
            max_file_bytes if max_file_bytes is not None
            else int(os.environ.get("FDB_TPU_TRACE_ROLL_BYTES", 10_000_000))
        )
        self.roll_count = (
            roll_count if roll_count is not None
            else int(os.environ.get("FDB_TPU_TRACE_ROLL_COUNT", 4))
        )
        # per-type rate suppression (ref: flow/Trace.cpp event
        # suppression): identical event types past the per-interval
        # budget are DROPPED and counted, so a hot-loop SEV_ERROR can
        # no longer flood the ring and roll every file. 0 disables.
        # The default sits well above legitimate traffic (a 1%-sampled
        # tracing e2e emits ~6k Span events per 5s) — this is a flood
        # breaker, not a sampler.
        self.type_budget = (
            type_budget if type_budget is not None
            else int(os.environ.get("FDB_TPU_TRACE_TYPE_BUDGET", 20_000))
        )
        self.suppression_interval_s = (
            suppression_interval_s if suppression_interval_s is not None
            else float(os.environ.get("FDB_TPU_TRACE_SUPPRESS_INTERVAL",
                                      5.0))
        )
        self._type_counts = {}
        self._window_start = None
        self.suppressed_events = 0
        self.suppressed_by_type = {}

    def open(self, path):
        with self._lock:
            self._path = path
            self.closed = False
            if self._file:
                self._file.close()
            self._file = open(path, "a", buffering=1)
            self._file_bytes = self._file.tell()

    def close(self):
        with self._lock:
            self.closed = True
            if self._file:
                self._file.close()
                self._file = None

    def _roll_locked(self):
        """Rotate path → path.1 → … → path.roll_count (oldest dropped).
        roll_count 0 truncates in place — bounded either way."""
        self._file.close()
        self._file = None
        if self.roll_count > 0:
            oldest = f"{self._path}.{self.roll_count}"
            if os.path.exists(oldest):
                os.remove(oldest)
            for i in range(self.roll_count - 1, 0, -1):
                src = f"{self._path}.{i}"
                if os.path.exists(src):
                    os.replace(src, f"{self._path}.{i + 1}")
            os.replace(self._path, f"{self._path}.1")
        else:
            os.remove(self._path)
        self._file = open(self._path, "a", buffering=1)
        self._file_bytes = 0

    def _suppress_locked(self, event):
        """Whether this event exceeds its type's per-interval budget
        (drop + count). The window rides the sink's injected clock, so
        sim suppression decisions replay deterministically."""
        if not self.type_budget:
            return False
        t = event.get("time")
        if t is None:
            t = self.clock()
        if (self._window_start is None
                or t - self._window_start >= self.suppression_interval_s):
            self._window_start = t
            self._type_counts = {}
        type_ = event["type"]
        n = self._type_counts.get(type_, 0) + 1
        self._type_counts[type_] = n
        if n <= self.type_budget:
            return False
        self.suppressed_events += 1
        self.suppressed_by_type[type_] = (
            self.suppressed_by_type.get(type_, 0) + 1
        )
        return True

    def emit(self, event):
        if event["severity"] < self.min_severity:
            return
        # serialization is deferred until a file sink provably needs a
        # line: ring-only sinks (tests, benches) skip json.dumps — a
        # measured per-event cost at tracing-level volumes
        line = None
        if self._path is not None:
            line = json.dumps(event, separators=(",", ":"), default=repr)
        with self._lock:
            if self.closed:
                return  # interpreter teardown / explicit close: drop
            if self._suppress_locked(event):
                return
            if self._file is None and self._path is not None:
                self._file = open(self._path, "a", buffering=1)
                self._file_bytes = self._file.tell()
            if self._file is not None:
                if line is None:  # path set concurrently with open()
                    line = json.dumps(event, separators=(",", ":"),
                                      default=repr)
                data = line + "\n"
                self._file.write(data)
                self._file_bytes += len(data)
                if (self.max_file_bytes
                        and self._file_bytes >= self.max_file_bytes):
                    self._roll_locked()
            # the ring buffer fills regardless of the file sink, so
            # events() serves tests and forensics either way (deque
            # maxlen: the oldest half is long gone, newest retained)
            self._buffer.append(event)

    def events(self, type_=None):
        """Ring-buffered events (file sink or not), newest last."""
        with self._lock:
            return [
                e for e in self._buffer if type_ is None or e["type"] == type_
            ]

    def clear(self):
        with self._lock:
            self._buffer.clear()
            # fresh forensics window: suppression counts restart with
            # the buffer (cumulative suppressed_events totals remain),
            # so back-to-back sim runs sharing the process see
            # identical suppression decisions
            self._type_counts = {}
            self._window_start = None


_global = TraceLog(
    path=os.environ.get("FDB_TPU_TRACE_FILE"),
    min_severity=int(os.environ.get("FDB_TPU_TRACE_SEVERITY", SEV_INFO)),
)


def global_trace_log():
    return _global


class StageStats:
    """Cumulative wall-time counters for a multi-stage pipeline (the
    commit path's pack / resolve / apply stages). The batcher feeds it
    from two threads — the producer times stage A+B, the apply worker
    times stage C — so accumulation is lock-protected; reads take a
    consistent snapshot. The bench surfaces ``summary()`` so per-stage
    cost (and which stage is critical-path) lands in the artifact."""

    def __init__(self, registry=None):
        self._lock = lockdep.lock("StageStats._lock")
        self._total_s = {}
        self._count = {}
        # optional metrics registry: every add() also records into a
        # per-stage LatencySample, so the bench's stage means gain
        # latency BANDS in status json without a second timing site
        self._registry = registry
        self._bands = {}

    def add(self, stage, seconds):
        with self._lock:
            self._total_s[stage] = self._total_s.get(stage, 0.0) + seconds
            self._count[stage] = self._count.get(stage, 0) + 1
        if self._registry is not None:
            band = self._bands.get(stage)
            if band is None:
                band = self._bands[stage] = self._registry.latency(
                    f"stage_{stage}"
                )
            band.record(seconds)

    def mean_ms(self, stage):
        with self._lock:
            n = self._count.get(stage, 0)
            return (self._total_s.get(stage, 0.0) / n * 1e3) if n else 0.0

    def summary(self):
        """{stage: mean ms per observation} for every recorded stage."""
        with self._lock:
            return {
                s: round(self._total_s[s] / self._count[s] * 1e3, 3)
                for s in self._total_s if self._count.get(s)
            }


class TraceEvent:
    """Fluent structured event (ref: TraceEvent(\"Type\").detail(...).log()).

    Usage::

        TraceEvent("CommitBatch", severity=SEV_INFO).detail(
            txns=32, version=cv).log()

    Events also log on ``with``-exit or garbage collection, mirroring the
    reference's log-on-destruct.
    """

    def __init__(self, type_, severity=SEV_INFO, log=None):
        self.type = type_
        self.severity = severity
        self._details = {}
        self._log = log if log is not None else _global
        self._logged = False

    def detail(self, **kwargs):
        self._details.update(kwargs)
        return self

    def error(self, exc):
        self.severity = max(self.severity, SEV_ERROR)
        self._details["error"] = str(exc)
        return self

    def log(self):
        if self._logged:
            return
        self._logged = True
        self._log.emit(
            {
                "type": self.type,
                "severity": self.severity,
                "sev_name": _SEV_NAMES.get(self.severity, str(self.severity)),
                "time": self._log.clock(),
                **{
                    k: (v.decode("latin-1") if isinstance(v, bytes) else v)
                    for k, v in self._details.items()
                },
            }
        )

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc is not None:
            self.error(exc)
        self.log()
        return False

    def __del__(self):
        # Log-on-destruct, EXCEPT at interpreter shutdown: a GC pass
        # after the global sink closed (or after module globals were
        # torn down to None) must never print spurious errors from a
        # half-dead runtime. ``closed`` is the explicit signal; the
        # broad guards cover teardown states where even attribute
        # access on the sink can fail.
        try:
            log = self._log
            if log is None or getattr(log, "closed", False):
                return
            self.log()
        except Exception:
            pass
