"""Distributed tracing spans — per-request hop attribution.

Ref parity: fdbclient/Tracing.actor.cpp (Span/OTELSpan) plus the
``g_traceBatch`` TransactionDebug events the reference stitches by
debugID across GRV proxy → commit proxy → resolver → tlog. A sampled
transaction carries a SpanContext on every hop (the wire's optional
tracing frame, ``CommitRequest.span_context`` on the commit path, and a
thread-ambient context for in-process calls); each role opens a child
span around its work and finished spans emit as ``type="Span"``
TraceEvents, so they ride the existing sinks/rolling/forensics of
``utils/trace.py`` and the critical-path tool
(``tools/tracing.py``) reconstructs the tree offline.

Determinism (FL001): trace/span ids draw from the ``span-id`` named
stream and sampling decisions from ``span-sample``, both on the
``core/deterministic.py`` seam; begin/end stamps come off the injected
clock. Two same-seed sims therefore emit byte-identical Span streams.

Overhead: with tracing off (``sample_rate`` 0 and no per-transaction
force) every call site degrades to :data:`NULL` — a shared no-op span
whose methods return immediately — so the commit hot path pays a couple
of attribute calls per transaction (``BENCH_MODE=tracing_smoke`` gates
the enabled-at-default-rate cost at ≤2%). Promotion of UNSAMPLED
traffic follows the metrics subsystem's per-window lesson (PR 4: even
one extra clock stamp per transaction busts a 2% budget at tens of
thousands of commits/sec):

- **aborts** promote per-transaction on the ERROR path only
  (:func:`promote_lite` — zero cost on the happy path; the record
  carries the error class and retry count, not durations);
- **slow commits** promote per BATCH WINDOW: the batcher/proxy already
  stamp every window's submit→settle span for the commit_e2e band, and
  a window outliving ``tracing_slow_commit_ms`` emits a
  ``commit.window`` span from those same stamps
  (:func:`slow_window_span` — no new clock reads anywhere).

Full hop-level trees come from sampled or forced transactions.
"""

import threading

from foundationdb_tpu.core import deterministic
from foundationdb_tpu.utils import trace as trace_mod

# named deterministic streams: a seeded sim mints identical ids and
# sampling decisions every run (flowlint FL001 — a raw uuid4/random
# span id here would make seed replays diverge)
_ID_STREAM = "span-id"
_SAMPLE_STREAM = "span-sample"

now = deterministic.now  # the injected clock every span stamp uses

# process-wide gauges (GIL-atomic ints, the metrics Counter idiom):
# sampled = root transaction spans that will emit (drawn or promoted),
# emitted = Span TraceEvents actually written
_spans_sampled = 0
_spans_emitted = 0


def spans_sampled():
    return _spans_sampled


def spans_emitted():
    return _spans_emitted


# The named stream OBJECTS are cached here after first use: the
# registry hands back the same persistent random.Random per name
# forever (deterministic.seed() re-seeds the objects in place), so
# caching skips the registry lock on every id/sampling draw — a
# measured hot-path cost at tens of thousands of transactions/sec.
_id_stream = None
_sample_stream = None


def _new_id():
    global _id_stream
    s = _id_stream
    if s is None:
        s = _id_stream = deterministic.rng(_ID_STREAM)
    return s.getrandbits(64)


def should_sample(rate):
    """One sampling draw from the seeded stream. rate<=0 never draws
    (tracing off must not perturb the stream's sequence) and rate>=1
    never draws either (always on)."""
    if rate <= 0.0:
        return False
    if rate >= 1.0:
        return True
    global _sample_stream
    s = _sample_stream
    if s is None:
        s = _sample_stream = deterministic.rng(_SAMPLE_STREAM)
    return s.random() < rate


# ── ambient context ──────────────────────────────────────────────────
# The thread's current SpanContext — a (trace_id, span_id, sampled)
# tuple, exactly what the wire's tracing frame carries. In-process
# calls (sync GRV, the commit pipeline's role calls) read it instead of
# threading a parameter through every signature; the RPC transport
# installs it on the handler thread from the incoming frame.
_tls = threading.local()


def current():
    return getattr(_tls, "ctx", None)


def set_current(ctx):
    """Install ``ctx`` as this thread's ambient context; returns the
    prior value so callers restore in a finally."""
    prior = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    return prior


class _NullSpan:
    """The shared no-op span: every tracing call site holds one of
    these when tracing is off, so the hot path cost is a method call
    that returns immediately. Falsy, children are itself, context is
    None (nothing propagates)."""

    __slots__ = ()
    sampled = False
    trace_id = 0
    span_id = 0
    parent_id = 0

    def child(self, name, **attrs):
        return self

    def attr(self, **kw):
        return self

    def finish(self, end=None, **attrs):
        pass

    def context(self):
        return None

    def __bool__(self):
        return False

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


NULL = _NullSpan()


class Span:
    """One timed hop of a trace (ref: Span in Tracing.actor.cpp).

    Finished spans emit a ``type="Span"`` TraceEvent at :meth:`finish`.
    Ids ride the deterministic seam; stamps ride the injected clock.
    Every constructed Span is an emitting one — the unsampled hot path
    constructs nothing (see :data:`NULL` and :func:`promote_lite`).
    """

    __slots__ = ("name", "trace_id", "span_id", "parent_id",
                 "begin", "end", "attrs_d", "_log")

    sampled = True  # class-level: a constructed Span always emits

    def __init__(self, name, trace_id=None, parent_id=0, log=None,
                 begin=None):
        self.name = name
        self.trace_id = trace_id if trace_id is not None else _new_id()
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.begin = begin if begin is not None else now()
        self.end = None
        self.attrs_d = None
        self._log = log

    def child(self, name, **attrs):
        sp = Span(name, trace_id=self.trace_id, parent_id=self.span_id,
                  log=self._log)
        if attrs:
            sp.attrs_d = dict(attrs)
        return sp

    def attr(self, **kw):
        d = self.attrs_d
        if d is None:
            self.attrs_d = dict(kw)
        else:
            d.update(kw)
        return self

    def context(self):
        """The wire-propagatable SpanContext of THIS span (children on
        other hops parent to it)."""
        return (self.trace_id, self.span_id, True)

    def finish(self, end=None, **attrs):
        if self.end is not None:
            return  # idempotent: a span settles exactly once
        self.end = now() if end is None else end
        if attrs:
            self.attr(**attrs)
        self._emit()

    def _emit(self):
        global _spans_emitted
        _spans_emitted += 1
        # the event dict is built directly (no TraceEvent fluent
        # object): span emission runs at trace volume, and the extra
        # allocation + detail-merge + destructor guard were measurable
        log = self._log if self._log is not None \
            else trace_mod.global_trace_log()
        ev = {
            "type": "Span",
            "severity": trace_mod.SEV_INFO,
            "sev_name": "info",
            "time": log.clock(),
            "span": self.name,
            "trace": "%016x" % self.trace_id,
            "sid": "%016x" % self.span_id,
            "parent": "%016x" % self.parent_id,
            "begin": round(self.begin, 6),
            "end": round(self.end, 6),
            "dur_ms": round((self.end - self.begin) * 1e3, 3),
        }
        if self.attrs_d:
            ev.update(self.attrs_d)
        log.emit(ev)

    def __bool__(self):
        return True

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc is not None:
            self.attr(error=str(exc)[:200])
        self.finish()
        return False


def transaction_span(sample_rate, forced=False, log=None):
    """The client transaction's root span: an emitting span when the
    per-transaction force or the sampling draw hits, else :data:`NULL`
    (the draw is inlined — this runs once per transaction). Unsampled
    promotion is reconstruction-based (:func:`promote_lite`,
    :func:`slow_window_span`), not object-based."""
    global _spans_sampled, _sample_stream
    if not forced:
        if sample_rate <= 0.0:
            return NULL
        if sample_rate < 1.0:
            s = _sample_stream
            if s is None:
                s = _sample_stream = deterministic.rng(_SAMPLE_STREAM)
            if s.random() >= sample_rate:
                return NULL
    _spans_sampled += 1
    return Span("transaction", log=log)


def promote_lite(begin, end, commit_begin=None, error_code=None,
                 retries=0, log=None):
    """Retrospective promotion of an UNSAMPLED transaction that turned
    out to matter (an abort, or a late force): the happy path kept no
    state, so the record is reconstructed here — the one-in-a-thousand
    pays for its trace, the other 999 paid nothing."""
    global _spans_sampled
    _spans_sampled += 1
    root = Span("transaction", log=log, begin=begin)
    root.attr(promoted=1, retries=retries)
    status = "committed" if error_code is None else "error"
    if commit_begin is not None:
        csp = root.child("txn.commit")
        csp.begin = commit_begin
        if error_code is not None:
            csp.attr(error_code=error_code)
        csp.finish(end=end, status=status)
    root.finish(end=end, status=status)
    return root


def slow_window_span(begin, end, txns, log=None):
    """The per-WINDOW slow-commit promotion: a batch window whose
    submit→settle span outlived ``tracing_slow_commit_ms`` emits one
    ``commit.window`` record built from the stamps the commit_e2e
    latency band already took — slow-commit attribution with zero
    added clock reads on the hot path (every member of the window
    shares the reported latency, so window granularity is honest)."""
    global _spans_sampled
    _spans_sampled += 1
    root = Span("commit.window", log=log, begin=begin)
    root.finish(end=end, promoted=1, txns=txns)
    return root


def from_context(name, ctx, log=None, **attrs):
    """A server-side span continuing an incoming SpanContext; NULL when
    the context is absent or unsampled (roles only trace sampled
    traces)."""
    if ctx is None or not ctx[2]:
        return NULL
    sp = Span(name, trace_id=ctx[0], parent_id=ctx[1], log=log)
    if attrs:
        sp.attrs_d = dict(attrs)
    return sp


def emit_span(name, ctx, begin=None, end=None, **attrs):
    """Construct-and-finish a span with explicit stamps — the synthetic
    stage spans the batcher derives from its StageStats timings."""
    sp = from_context(name, ctx)
    if sp is NULL:
        return NULL
    if begin is not None:
        sp.begin = begin
    sp.finish(end=end, **attrs)
    return sp


def first_request_context(requests):
    """The first SAMPLED ``span_context`` carried by an iterable of
    commit requests, or None — how a batch/group picks the trace it
    attributes shared work to."""
    for r in requests:
        c = getattr(r, "span_context", None)
        if c is not None and c[2]:
            return c
    return None


def batch_span(requests, name="proxy.batch", log=None):
    """A span for a whole commit batch: parented to the FIRST sampled
    member's context and LINKING every sampled member span id (ref:
    the reference's batch-level span adding each txn's token as a
    link) — the one place a shared-version batch meets its member
    transactions' traces."""
    first = None
    links = None
    for r in requests:
        c = getattr(r, "span_context", None)
        if c is not None and c[2]:
            if first is None:
                first = c
                links = []
            links.append("%016x" % c[1])
    if first is None:
        return NULL
    sp = from_context(name, first, log=log)
    sp.attrs_d = {"links": links, "txns": len(requests)}
    return sp
