"""Device-path execution profiler: per-dispatch accounting for the
resolver's jit/pallas path.

Ref parity: flow/Profiler.actor.cpp (the sampling profiler whose doc
rides status json) + the device-side counters Status.actor.cpp folds
into ``cluster.*``. The resolver's device path is the one layer the
metrics/heatmap/trace stack never reached: this module records, per
dispatch, the bucket size chosen vs the txns actually live (pad
waste), live-vs-padded entry counts per conflict side (pr/pw/rr/rw),
jit retraces per shape signature, staging-ring reuse vs realloc,
host→device transfer bytes, per-lane dispatch wall + verdict-reduce
wall for the mesh fleet (lane-utilization skew — ROADMAP item 4's
direct measurement), and a structured ``fallback_cause`` taxonomy
(pallas_to_jit, flat_to_legacy, sharded_to_local, over_capacity,
too_old_rv) replacing the bare fallback counters.

FL004: every capture site is HOST-side — around the jit call, never
inside a traced function. The flowlint fixtures in
tests/test_flowlint.py pin that a profiler hook inside a jit-reachable
fn trips the lint.

Determinism: durations use ``core.deterministic.now()`` (the metrics.py
clock contract) — under the sim's step clock a span inside one step is
exactly 0.0, so two same-seed sims emit byte-identical profiler docs;
in production the clock is the real wall clock. Everything else is
integer counters.

Overhead: the module-level ``set_enabled(False)`` kill switch turns
every ``record_*`` into an early return — ``BENCH_MODE=profile_smoke``
runs the ycsb e2e both ways (interleaved pairs, median compare) and
gates at ≤2% overhead, the metrics_smoke protocol.
"""

import threading

from foundationdb_tpu.core import deterministic
from foundationdb_tpu.utils import lockdep

_enabled = True

# the closed taxonomy: snapshot() emits every cause (zeros included) so
# the doc's shape is stable and benchdiff can align rounds field-field
FALLBACK_CAUSES = (
    "pallas_to_jit",   # pallas ring kernel unavailable/failed -> jit
    "flat_to_legacy",  # flat batch mixed with legacy / width mismatch
    "sharded_to_local",  # mesh lanes clamped below the requested fleet
    "over_capacity",   # flat batch exceeds a lane cap -> decode+repack
    "too_old_rv",      # read version below the resolver's fenced base
)

SIDES = ("pr", "pw", "rr", "rw")


def set_enabled(on):
    """Process-wide kill switch (the profile_smoke overhead probe)."""
    global _enabled
    _enabled = bool(on)


def enabled():
    return _enabled


def now():
    """The injected clock every profiler duration uses (sim: the step
    clock; production: the wall clock)."""
    return deterministic.now()


class DeviceProfile:
    """Per-resolver device-path profile. The cluster owns one per
    resolver index (like the PR-4 registries) and re-hands it across
    respawn/recovery/configure so history never rewinds; ``absorb``
    bypasses the kill switch because carried history is not new
    overhead."""

    def __init__(self, name, index=0):
        self.name = name
        self.index = index
        self._lock = lockdep.lock("DeviceProfile._lock")
        # dispatch accounting
        self.dispatches = 0
        self.batches_live = 0
        self.batch_slots = 0
        self.txns_live = 0
        self.txn_slots = 0
        self.bucket_histogram = {}  # str(B) -> dispatches at bucket B
        # per-side entry occupancy: live vs padded slots
        self.entries_live = {s: 0 for s in SIDES}
        self.entry_slots = {s: 0 for s in SIDES}
        # compile-cache events: new shape signatures seen by the
        # dispatch callable (ops/conflict.count_retraces)
        self.recompiles = 0
        self.compile_keys = {}  # str(key) -> count
        # staging ring (resolver/packing.py _flat_staging)
        self.staging_reuse_hits = 0
        self.staging_reuse_misses = 0
        # host->device transfer estimate (sum of packed array nbytes)
        self.transfer_bytes = 0
        # walls (deterministic clock; 0.0 under the sim step clock)
        self.dispatch_wall_s = 0.0
        self.verdict_reduce_wall_s = 0.0
        # mesh lanes: accumulated per-lane dispatch wall (hash-sharded
        # mode / legacy host fan-out) OR per-lane routed-entry counts
        # (range-sharded mode — known at split time, before the device
        # runs). One profile only ever fills one of the two: skew over
        # mixed units would be meaningless.
        self.lane_walls_s = []
        self.lane_entries = []
        self.lane_dispatches = 0
        # fallback-cause taxonomy
        self.fallback_causes = {c: 0 for c in FALLBACK_CAUSES}
        # kernel-route dispatch records: which per-batch step body
        # actually executed ("pallas_scan" | "pallas_ring" | "jit"),
        # counted per live batch served — the ground truth behind
        # bench.py's pallas_kernel_step stamp (the params flag alone is
        # the REQUEST; a silent pallas_to_jit fallback must flip it)
        self.kernel_routes = {}

    # ── capture sites (all host-side, all gated) ──

    def record_dispatch(self, bucket, live_batches, live_txns, txn_slots,
                        entries_live=None, entry_slots=None,
                        transfer_bytes=0, wall_s=0.0):
        if not _enabled:
            return
        with self._lock:
            self.dispatches += 1
            self.batches_live += int(live_batches)
            self.batch_slots += int(bucket)
            self.txns_live += int(live_txns)
            self.txn_slots += int(txn_slots)
            b = str(int(bucket))
            self.bucket_histogram[b] = self.bucket_histogram.get(b, 0) + 1
            if entries_live:
                for s in SIDES:
                    self.entries_live[s] += int(entries_live.get(s, 0))
            if entry_slots:
                for s in SIDES:
                    self.entry_slots[s] += int(entry_slots.get(s, 0))
            self.transfer_bytes += int(transfer_bytes)
            self.dispatch_wall_s += float(wall_s)

    def record_compile(self, key):
        if not _enabled:
            return
        with self._lock:
            self.recompiles += 1
            k = str(key)
            self.compile_keys[k] = self.compile_keys.get(k, 0) + 1

    def record_fallback(self, cause, n=1):
        if not _enabled:
            return
        with self._lock:
            self.fallback_causes[cause] = (
                self.fallback_causes.get(cause, 0) + int(n))

    def record_kernel_route(self, route, n=1):
        """One successful dispatch served by ``route`` (n = live
        batches it carried). Recorded at the call sites' success edge
        only — a dispatch that engaged the Pallas fallback records its
        cause, not a route."""
        if not _enabled:
            return
        with self._lock:
            self.kernel_routes[route] = (
                self.kernel_routes.get(route, 0) + int(n))

    def record_staging(self, hit):
        if not _enabled:
            return
        with self._lock:
            if hit:
                self.staging_reuse_hits += 1
            else:
                self.staging_reuse_misses += 1

    def record_lanes(self, walls_s):
        """Per-lane dispatch walls for ONE mesh dispatch (index = lane,
        stable device order) — accumulated so skew reflects the run."""
        if not _enabled:
            return
        with self._lock:
            if len(self.lane_walls_s) < len(walls_s):
                self.lane_walls_s.extend(
                    0.0 for _ in range(len(walls_s) - len(self.lane_walls_s)))
            for i, w in enumerate(walls_s):
                self.lane_walls_s[i] += float(w)
            self.lane_dispatches += 1

    def record_lane_counts(self, counts):
        """Per-lane routed-entry counts for ONE dispatch (range-sharded
        mesh: the ShardRouter split, or the legacy proxy fan-out's
        clipped sub-batches). Same lane_skew_pct rollup as the wall
        instrument — balance in entries instead of seconds."""
        if not _enabled:
            return
        with self._lock:
            if len(self.lane_entries) < len(counts):
                self.lane_entries.extend(
                    0 for _ in range(len(counts) - len(self.lane_entries)))
            for i, c in enumerate(counts):
                self.lane_entries[i] += int(c)
            self.lane_dispatches += 1

    def record_verdict_reduce(self, wall_s):
        if not _enabled:
            return
        with self._lock:
            self.verdict_reduce_wall_s += float(wall_s)

    # ── carryover + rollup ──

    def absorb(self, other):
        """Fold a prior incarnation's totals in (respawn / recovery /
        configure shrink). Bypasses the kill switch: carried history is
        not new overhead."""
        with other._lock:
            o = {
                "dispatches": other.dispatches,
                "batches_live": other.batches_live,
                "batch_slots": other.batch_slots,
                "txns_live": other.txns_live,
                "txn_slots": other.txn_slots,
                "bucket_histogram": dict(other.bucket_histogram),
                "entries_live": dict(other.entries_live),
                "entry_slots": dict(other.entry_slots),
                "recompiles": other.recompiles,
                "compile_keys": dict(other.compile_keys),
                "staging_reuse_hits": other.staging_reuse_hits,
                "staging_reuse_misses": other.staging_reuse_misses,
                "transfer_bytes": other.transfer_bytes,
                "dispatch_wall_s": other.dispatch_wall_s,
                "verdict_reduce_wall_s": other.verdict_reduce_wall_s,
                "lane_walls_s": list(other.lane_walls_s),
                "lane_entries": list(other.lane_entries),
                "lane_dispatches": other.lane_dispatches,
                "fallback_causes": dict(other.fallback_causes),
                "kernel_routes": dict(other.kernel_routes),
            }
        with self._lock:
            self.dispatches += o["dispatches"]
            self.batches_live += o["batches_live"]
            self.batch_slots += o["batch_slots"]
            self.txns_live += o["txns_live"]
            self.txn_slots += o["txn_slots"]
            for k, v in o["bucket_histogram"].items():
                self.bucket_histogram[k] = (
                    self.bucket_histogram.get(k, 0) + v)
            for s in SIDES:
                self.entries_live[s] += o["entries_live"].get(s, 0)
                self.entry_slots[s] += o["entry_slots"].get(s, 0)
            self.recompiles += o["recompiles"]
            for k, v in o["compile_keys"].items():
                self.compile_keys[k] = self.compile_keys.get(k, 0) + v
            self.staging_reuse_hits += o["staging_reuse_hits"]
            self.staging_reuse_misses += o["staging_reuse_misses"]
            self.transfer_bytes += o["transfer_bytes"]
            self.dispatch_wall_s += o["dispatch_wall_s"]
            self.verdict_reduce_wall_s += o["verdict_reduce_wall_s"]
            if len(self.lane_walls_s) < len(o["lane_walls_s"]):
                self.lane_walls_s.extend(
                    0.0 for _ in range(len(o["lane_walls_s"])
                                       - len(self.lane_walls_s)))
            for i, w in enumerate(o["lane_walls_s"]):
                self.lane_walls_s[i] += w
            if len(self.lane_entries) < len(o["lane_entries"]):
                self.lane_entries.extend(
                    0 for _ in range(len(o["lane_entries"])
                                     - len(self.lane_entries)))
            for i, c in enumerate(o["lane_entries"]):
                self.lane_entries[i] += c
            self.lane_dispatches += o["lane_dispatches"]
            for c, v in o["fallback_causes"].items():
                self.fallback_causes[c] = (
                    self.fallback_causes.get(c, 0) + v)
            for r, v in o["kernel_routes"].items():
                self.kernel_routes[r] = self.kernel_routes.get(r, 0) + v

    def snapshot(self):
        """JSON-ready doc (sorted, stably rounded). ``pad_waste_pct``
        is the slot share PADDING burned: 1 - live/slots over every
        dispatch; ``lane_skew_pct`` is (max-min)/max over the
        accumulated per-lane loads — walls when the wall instrument
        filled, routed-entry counts otherwise — 0 when balanced or
        single-lane."""
        with self._lock:
            lanes = list(self.lane_walls_s)
            entries = list(self.lane_entries)
            skew_src = [float(x) for x in (lanes or entries)]
            txn_slots = self.txn_slots
            txns_live = self.txns_live
            hits, misses = (self.staging_reuse_hits,
                            self.staging_reuse_misses)
            pad_waste = (
                round((1.0 - txns_live / txn_slots) * 100, 2)
                if txn_slots else 0.0)
            lane_max = max(skew_src) if skew_src else 0.0
            lane_skew = (
                round((lane_max - min(skew_src)) / lane_max * 100, 2)
                if lane_max > 0 else 0.0)
            return {
                "name": self.name,
                "id": self.index,
                "dispatches": self.dispatches,
                "batches_live": self.batches_live,
                "batch_slots": self.batch_slots,
                "txns_live": txns_live,
                "txn_slots": txn_slots,
                "pad_waste_pct": pad_waste,
                "bucket_histogram": dict(sorted(
                    self.bucket_histogram.items(),
                    key=lambda kv: int(kv[0]))),
                "entries_live": dict(self.entries_live),
                "entry_slots": dict(self.entry_slots),
                "recompiles": self.recompiles,
                "compile_keys": dict(sorted(self.compile_keys.items())),
                "staging_reuse_hits": hits,
                "staging_reuse_misses": misses,
                "staging_reuse_rate": round(
                    hits / max(hits + misses, 1), 3),
                "transfer_bytes": self.transfer_bytes,
                "dispatch_wall_ms": round(self.dispatch_wall_s * 1e3, 3),
                "verdict_reduce_wall_ms": round(
                    self.verdict_reduce_wall_s * 1e3, 3),
                "lanes": max(len(lanes), len(entries)),
                "lane_dispatches": self.lane_dispatches,
                "lane_walls_ms": [round(w * 1e3, 3) for w in lanes],
                "lane_entries": entries,
                "lane_skew_pct": lane_skew,
                "fallback_causes": dict(sorted(
                    self.fallback_causes.items())),
                "kernel_routes": dict(sorted(
                    self.kernel_routes.items())),
            }


def merged_snapshot(profiles):
    """One aggregate doc over several DeviceProfiles (the cluster-wide
    ``cluster.device.aggregate`` rollup)."""
    acc = DeviceProfile("aggregate")
    for p in profiles:
        if p is not None:
            acc.absorb(p)
    return acc.snapshot()
