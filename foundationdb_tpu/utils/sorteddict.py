"""Minimal SortedDict stand-in for containers without sortedcontainers.

The storage overlay and the kvstore engines need exactly one thing
beyond ``dict``: ordered key iteration over a half-open range
(``irange``). This shim keeps a lazily rebuilt sorted-key cache —
invalidated whenever the key SET changes, untouched by value updates —
and answers ``irange`` with bisect over it. Iteration returns a slice
copy, which is strictly safer than sortedcontainers' live view under
the "list() before mutating" discipline the call sites already follow.

Complexity trades away from the real library (O(n log n) re-sort after
an insert/delete burst instead of O(log n) per op), which is fine for
the in-process cluster sizes tests and sims run at; deployments with
sortedcontainers installed never load this module (see the gated
imports in server/storage.py and server/kvstore.py).
"""

from bisect import bisect_left, bisect_right


class SortedDict(dict):
    __slots__ = ("_sorted",)

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._sorted = None

    # ── mutations that can change the key set invalidate the cache ──
    def __setitem__(self, key, value):
        if self._sorted is not None and key not in self:
            self._sorted = None
        super().__setitem__(key, value)

    def __delitem__(self, key):
        super().__delitem__(key)
        self._sorted = None

    def pop(self, *args):
        self._sorted = None
        return super().pop(*args)

    def popitem(self):
        self._sorted = None
        return super().popitem()

    def clear(self):
        self._sorted = None
        super().clear()

    def update(self, *args, **kwargs):
        self._sorted = None
        super().update(*args, **kwargs)

    def setdefault(self, key, default=None):
        if self._sorted is not None and key not in self:
            self._sorted = None
        return super().setdefault(key, default)

    # ── the ordered view ──
    def _keys_sorted(self):
        if self._sorted is None:
            self._sorted = sorted(super().keys())
        return self._sorted

    def irange(self, minimum=None, maximum=None, inclusive=(True, True),
               reverse=False):
        ks = self._keys_sorted()
        if minimum is None:
            lo = 0
        elif inclusive[0]:
            lo = bisect_left(ks, minimum)
        else:
            lo = bisect_right(ks, minimum)
        if maximum is None:
            hi = len(ks)
        elif inclusive[1]:
            hi = bisect_right(ks, maximum)
        else:
            hi = bisect_left(ks, maximum)
        span = ks[lo:hi]
        return reversed(span) if reverse else iter(span)
