r"""Time-series metrics history + flight recorder — the cluster's black box.

Ref parity: flow/TDMetric.actor.h (continuously-logged metric channels
with bounded history) and the latency/message history Status.actor.cpp
retains, so one status read shows where the cluster has BEEN, not just
where it is. Every point-in-time doc we already publish — the metric
registries, the workload heatmaps, the device profile, the health
verdict — gets a trajectory here:

* ``CounterSeries`` — per-window counter deltas → rates. Samples come
  from the CLUSTER-owned observability stores (metrics registries,
  heatmaps, device profiles), which already survive txn-system
  recovery, resolver respawn, and ``configure()`` shrink via their
  absorb/adopt semantics — so a window total never goes backwards; a
  defensive high-water clamp covers the one source that can rewind
  (a freshly recruited storage server's per-process registry).
* ``GaugeSeries`` — per-window sampled value, with ring-wide
  last/min/max rollups.
* ``LatencySeries`` — a latency band's p99 trajectory.
* ``HistoryCollector`` — cluster-owned; cuts one window per cadence
  interval off the injected clock, first-window offset jittered via
  the named "history-cadence" deterministic stream (the FL001 seam:
  same-seed sims cut identical windows, real fleets de-align).
  Thread-mode clusters drive it from a daemon loop; sims call
  ``maybe_collect()`` from their scheduler, exactly like the latency
  prober and the region streamer.
* ``FlightRecorder`` — the black box: a health-verdict transition, a
  txn-system recovery, or a probe-SLO breach dumps a bounded artifact
  (recent windows, verdict timeline, recovery timeline, trace-ring
  tail, activated SimBuggifySites) into an in-memory ring, optionally
  to a JSON file under ``knobs.flight_dir``, and onto the
  ``\xff\xff/status/flight`` special key. Artifacts replay
  byte-identically across same-seed sims: every stamp is
  injected-clock time and serialization is sorted-key.

``set_enabled(False)`` is the module kill switch (BENCH_MODE=
history_smoke measures the enabled-vs-disabled cost against the ≤2%
budget): ``maybe_collect`` becomes a cheap no-op while already-
collected windows stay readable — turning history off must not blind
the reader.
"""

import json
import os
import threading
from collections import deque

from foundationdb_tpu.core import deterministic
from foundationdb_tpu.utils import lockdep
from foundationdb_tpu.utils import metrics as metrics_mod

_enabled = True
_enabled_mu = threading.Lock()


def set_enabled(on):
    """Process-wide collector kill switch (history_smoke measures the
    delta). Collected windows stay readable either way."""
    global _enabled
    with _enabled_mu:
        _enabled = bool(on)


def enabled():
    return _enabled


def _jsonable(obj):
    """A JSON-ready deep copy: bytes and other odd detail values become
    their repr, deterministically — flight artifacts must serialize to
    identical bytes under a seed, so the sanitizer never consults
    anything but the value itself."""
    return json.loads(json.dumps(obj, sort_keys=True, default=repr))


# ── per-metric rings ─────────────────────────────────────────────────
class CounterSeries:
    """Bounded ring of per-window samples for ONE monotone counter:
    each window keeps the sampled total and the rate the delta implies.
    The high-water clamp enforces the cluster-owned stores' no-rewind
    contract on sources that lack it (per-process storage registries
    reset when a dead server is recruited)."""

    __slots__ = ("name", "_ring", "_high")

    def __init__(self, name, capacity):
        self.name = name
        self._ring = deque(maxlen=capacity)
        self._high = None

    def push(self, t, total, dt):
        total = float(total)
        if self._high is not None and total < self._high:
            total = self._high  # never rewind a window
        delta = 0.0 if self._high is None else total - self._high
        self._high = total
        self._ring.append({
            "t": round(t, 6),
            "total": round(total, 6),
            "rate": round(delta / max(dt, 1e-9), 3),
        })

    def windows(self):
        return [dict(r) for r in self._ring]


class GaugeSeries:
    """Bounded ring of per-window gauge samples; the snapshot carries
    last/min/max rollups over the retained windows."""

    __slots__ = ("name", "_ring")

    def __init__(self, name, capacity):
        self.name = name
        self._ring = deque(maxlen=capacity)

    def push(self, t, value):
        self._ring.append({"t": round(t, 6),
                           "value": round(float(value), 6)})

    def windows(self):
        return [dict(r) for r in self._ring]

    def rollup(self):
        vals = [r["value"] for r in self._ring]
        if not vals:
            return {"last": None, "min": None, "max": None}
        return {"last": vals[-1], "min": min(vals), "max": max(vals)}


class LatencySeries:
    """Bounded ring of a latency band's p99 per window — the
    trajectory trend-aware doctor alerts read."""

    __slots__ = ("name", "_ring")

    def __init__(self, name, capacity):
        self.name = name
        self._ring = deque(maxlen=capacity)

    def push(self, t, p99_ms):
        self._ring.append({"t": round(t, 6),
                           "p99_ms": round(float(p99_ms), 6)})

    def windows(self):
        return [dict(r) for r in self._ring]


# ── trend detection (tools/doctor.py --trend + the probe_trend
#    degraded reason in the health verdict) ──────────────────────────
def rising_p99(rows, windows=3, min_rise_pct=5.0):
    """A monotone p99 rise across the last ``windows`` windows →
    ``{from_ms, to_ms, rise_pct, windows}``, else None. Strictly
    increasing nonzero values with a total rise past ``min_rise_pct``
    — the threshold keeps reservoir warm-up wiggle from alerting."""
    if windows < 2 or len(rows) < windows:
        return None
    vals = [r["p99_ms"] for r in rows[-windows:]]
    if any(v <= 0 for v in vals):
        return None
    if any(b <= a for a, b in zip(vals, vals[1:])):
        return None
    rise_pct = (vals[-1] - vals[0]) / vals[0] * 100.0
    if rise_pct < min_rise_pct:
        return None
    return {"from_ms": round(vals[0], 3), "to_ms": round(vals[-1], 3),
            "rise_pct": round(rise_pct, 2), "windows": windows}


def trend_alerts_from_doc(history_doc, windows=3, min_rise_pct=5.0,
                          names=("probe_grv", "probe_commit")):
    """Doc-shaped trend scan (works on a REMOTE history doc): one
    alert per probe hop whose p99 rose monotonically — the early
    warning that fires before the instant SLO threshold breaches."""
    series = (history_doc or {}).get("series", {}).get(
        "latency_p99_ms") or {}
    alerts = []
    for name in names:
        hit = rising_p99(series.get(name) or [], windows, min_rise_pct)
        if hit is not None:
            alerts.append({"name": name, **hit})
    return alerts


def live_rates(history_doc):
    """{counter: rate} from each series' most recent window — the
    delta between the two most recent samples, which is what ``fdbcli
    status`` shows instead of raw lifetime counters."""
    out = {}
    for name, rows in sorted(((history_doc or {}).get("series", {})
                              .get("counters") or {}).items()):
        if rows:
            out[name] = rows[-1]["rate"]
    return out


# ── the collector ────────────────────────────────────────────────────
HEAT_DIMS = ("conflict", "read", "write")


class HistoryCollector:
    """Cluster-owned retention layer: one fixed-cadence window samples
    every role's MetricsRegistry (via the cluster-level counter sums),
    the KeyRangeHeatmaps, the DeviceProfiles, the ratekeeper gauges,
    and the health verdict. Pull-based like the latency prober:
    ``maybe_collect()`` fires at most once per knob cadence off the
    injected clock; thread-mode clusters drive it from a daemon loop,
    sims/tests call it from their own schedule."""

    def __init__(self, cluster):
        self.cluster = cluster
        cap = cluster.knobs.history_windows
        self._counters = {}
        self._gauges = {}
        self._latencies = {}
        self.heat = {dim: deque(maxlen=cap) for dim in HEAT_DIMS}
        self.verdicts = deque(maxlen=cap)
        self.transitions = deque(maxlen=cap)
        self.windows_collected = 0
        # jittered first-window offset off the named deterministic
        # stream (FL001): same-seed sims cut the same windows; a real
        # fleet's collectors never thunder in step
        self._rng = deterministic.rng("history-cadence")
        # flowlint: shared(single-driver protocol: thread mode collects ONLY from the daemon loop, sims ONLY from their scheduler — never both, one writer at a time)
        self._next_due = None
        self._last_t = None
        # leaf lock: held only while mutating/copying the rings, never
        # while sampling the cluster (no lock-order edges)
        self._mu = lockdep.lock("HistoryCollector._mu")
        self.recorder = FlightRecorder(cluster)
        self._stop = threading.Event()
        self._thread = None

    # ── cadence ──────────────────────────────────────────────────────
    def maybe_collect(self):
        """Cut one window if the cadence elapsed; returns True iff a
        window was collected."""
        if not enabled() or not self.cluster.knobs.history_enabled:
            return False
        cadence = self.cluster.knobs.history_cadence_s
        now = deterministic.now()
        if self._next_due is None:
            self._next_due = now + cadence * self._rng.random()
            return False
        if now < self._next_due:
            return False
        # fixed cadence: a late arrival stays on the original grid
        # (no drift), missed windows are skipped rather than
        # burst-collected, and the next due time is strictly in the
        # future so an immediate re-poll never double-collects
        missed = max(0.0, now - self._next_due)
        self._next_due += cadence * (1 + int(missed // cadence))
        if self._next_due <= now:  # float-boundary guard
            self._next_due += cadence
        self.collect_now()
        return True

    def collect_now(self):
        """One window: sample everything (no lock held), then append to
        the per-metric rings and hand the window to the flight
        recorder. Returns the window timestamp."""
        c = self.cluster
        t = deterministic.now()
        dt = max((t - self._last_t) if self._last_t is not None
                 else c.knobs.history_cadence_s, 1e-9)
        health = c.health_status()

        counters = {
            "txn_committed": c._sum_counter("commit_proxy",
                                            "txn_committed"),
            "txn_conflicted": (
                c._sum_counter("commit_proxy", "abort_not_committed")
                + c._sum_counter("commit_proxy",
                                 "abort_transaction_too_old")),
            "txn_started": c._sum_counter("grv_proxy", "grv_grants"),
            "reads": sum(
                s.metrics.counter("point_reads").value
                + s.metrics.counter("range_reads").value
                + s.metrics.counter("batched_reads").value
                for s in c.storages),
            "probes": c._sum_counter("prober", "probes"),
            "probe_failures": c._sum_counter("prober", "probe_failures"),
            "tlog_pushes": health["lag"]["tlog_pushes"],
            "admit_denied": (health["ratekeeper"]["admit_denied_tag"]
                             + health["ratekeeper"]["admit_denied_budget"]),
            "recoveries": health["recovery"]["count"],
            "device_dispatches": sum(
                p.dispatches for p in c._device_store.values()),
        }
        # commit-pipeline stage busy-seconds: per-window rates give the
        # hottest-stage trajectory (tools/flight.py derives it)
        for stage in ("pack", "dispatch", "resolve", "apply"):
            total = 0.0
            for reg in c._role_registries("commit_proxy"):
                s = reg.get_latency(f"stage_{stage}")
                if s is not None:
                    total += s.total_seconds()
            counters[f"stage_{stage}_s"] = round(total, 6)

        rk = c.ratekeeper.history_sample()
        gauges = {
            "target_tps": rk["target_tps"],
            "saturation": rk["saturation"],
            "grv_queue_depth": health["lag"]["grv_queue_depth"],
            "tlog_queue_depth": health["lag"]["tlog_queue_depth"],
            "storage_lag_versions":
                health["lag"]["durability_lag_versions_max"],
            "storages_live": sum(
                1 for r in health["lag"]["storages"] if r["alive"]),
        }

        p99s = {
            "probe_grv": health["probe"]["grv"].get("p99_ms", 0.0),
            "probe_read": health["probe"]["read"].get("p99_ms", 0.0),
            "probe_commit": health["probe"]["commit"].get("p99_ms", 0.0),
            "commit_e2e": metrics_mod.merged_bands_ms(
                [r.get_latency("commit_e2e")
                 for r in c._role_registries("commit_proxy")])["p99_ms"],
            "grv_grant": metrics_mod.merged_bands_ms(
                [r.get_latency("grv_grant")
                 for r in c._role_registries("grv_proxy")])["p99_ms"],
        }

        hot = c.hot_ranges_status(top=c.knobs.history_heat_top)

        cap = c.knobs.history_windows
        with self._mu:
            for name, total in counters.items():
                s = self._counters.get(name)
                if s is None:
                    s = self._counters[name] = CounterSeries(name, cap)
                s.push(t, total, dt)
            for name, value in gauges.items():
                g = self._gauges.get(name)
                if g is None:
                    g = self._gauges[name] = GaugeSeries(name, cap)
                g.push(t, value)
            for name, p99 in p99s.items():
                ls = self._latencies.get(name)
                if ls is None:
                    ls = self._latencies[name] = LatencySeries(name, cap)
                ls.push(t, p99)
            for dim in HEAT_DIMS:
                self.heat[dim].append({
                    "t": round(t, 6),
                    "total": hot["totals"][dim]["heat"],
                    "rows": hot["hot_ranges"][dim],
                })
            prev = self.verdicts[-1]["verdict"] if self.verdicts else None
            if prev is not None and prev != health["verdict"]:
                self.transitions.append({
                    "t": round(t, 6), "from": prev,
                    "to": health["verdict"],
                })
            self.verdicts.append({
                "t": round(t, 6), "verdict": health["verdict"],
                "reasons": list(health["reasons"]),
            })
            self._last_t = t
            self.windows_collected += 1
        self.recorder.observe(t, health, self)
        return t

    # ── trend hook (the probe_trend degraded reason) ─────────────────
    def trend_alerts(self):
        """Live monotone-p99-rise scan over the in-memory rings — the
        health verdict's early-warning input. Empty while fewer than
        ``doctor_trend_windows`` windows exist."""
        k = self.cluster.knobs
        alerts = []
        with self._mu:
            for name in ("probe_grv", "probe_commit"):
                ls = self._latencies.get(name)
                if ls is None:
                    continue
                hit = rising_p99(list(ls._ring), k.doctor_trend_windows,
                                 k.doctor_trend_min_rise_pct)
                if hit is not None:
                    alerts.append({"name": name, **hit})
        return alerts

    # ── reporting ────────────────────────────────────────────────────
    def recent_windows(self, n):
        """The last ``n`` windows of every series — the flight
        artifact's history section."""
        with self._mu:
            return {
                "counters": {
                    name: s.windows()[-n:]
                    for name, s in sorted(self._counters.items())},
                "gauges": {
                    name: g.windows()[-n:]
                    for name, g in sorted(self._gauges.items())},
                "latency_p99_ms": {
                    name: ls.windows()[-n:]
                    for name, ls in sorted(self._latencies.items())},
            }

    def recent_verdicts(self, n):
        with self._mu:
            return [dict(v) for v in list(self.verdicts)[-n:]]

    def status(self):
        """The ``\\xff\\xff/metrics/history`` document (``history`` RPC
        / ``fdbcli history`` / cluster.history)."""
        k = self.cluster.knobs
        with self._mu:
            series = {
                "counters": {
                    name: s.windows()
                    for name, s in sorted(self._counters.items())},
                "gauges": {
                    name: {"windows": g.windows(), **g.rollup()}
                    for name, g in sorted(self._gauges.items())},
                "latency_p99_ms": {
                    name: ls.windows()
                    for name, ls in sorted(self._latencies.items())},
            }
            heat = {dim: [dict(w) for w in ring]
                    for dim, ring in self.heat.items()}
            verdicts = [dict(v) for v in self.verdicts]
            transitions = [dict(v) for v in self.transitions]
            n = self.windows_collected
        return {
            "enabled": enabled() and bool(k.history_enabled),
            "cadence_s": k.history_cadence_s,
            "capacity": k.history_windows,
            "windows": min(n, k.history_windows),
            "windows_collected": n,
            "series": series,
            "heat": heat,
            "verdicts": verdicts,
            "transitions": transitions,
            "trend_alerts": self.trend_alerts(),
            "flight": self.recorder.summary(),
        }

    # ── background driver (thread-mode clusters only) ────────────────
    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="history-collector", daemon=True
        )
        self._thread.start()

    def _loop(self):
        from foundationdb_tpu.utils.trace import SEV_ERROR, TraceEvent

        # wake at half the cadence so a window lands within ~1.5x of
        # its due time even when the loop and the schedule de-phase
        interval = max(self.cluster.knobs.history_cadence_s / 2, 0.05)
        while not self._stop.wait(interval):
            try:
                self.maybe_collect()
            except Exception as e:
                # the collector must never take the cluster down — but
                # a broken window is forensics-worthy, not silence
                TraceEvent("HistoryCollectError", severity=SEV_ERROR) \
                    .detail(error=repr(e))

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)


# ── the flight recorder ──────────────────────────────────────────────
class FlightRecorder:
    """The black box. ``observe()`` runs after every window; three
    edge-triggered conditions dump a bounded artifact: a health-verdict
    TRANSITION (either direction — the end of an incident is forensics
    too), a txn-system recovery (the timeline count advanced), and a
    probe-SLO breach (p99 crossed ``doctor_probe_p99_ms``; hysteresis
    re-arms only after it drops back under). Artifacts land in an
    in-memory ring (the ``\\xff\\xff/status/flight`` special key reads
    the newest) and, when ``knobs.flight_dir`` is set, as
    ``flight-<seq>.json`` files with sorted keys — byte-identical
    across same-seed sims."""

    def __init__(self, cluster):
        self.cluster = cluster
        self.artifacts = deque(maxlen=cluster.knobs.flight_max_dumps)
        self.dump_count = 0
        self.last_triggers = []
        self.dir = cluster.knobs.flight_dir or None
        self._prev_verdict = None
        self._prev_recoveries = None
        self._probe_breached = set()
        # leaf lock around the artifact ring; file IO stays outside it
        self._mu = lockdep.lock("FlightRecorder._mu")

    def observe(self, t, health, collector):
        """Trigger scan for one window; dumps at most one artifact (a
        window with several triggers records them all on it)."""
        triggers = []
        verdict = health["verdict"]
        if self._prev_verdict is not None and verdict != self._prev_verdict:
            triggers.append(f"verdict:{self._prev_verdict}->{verdict}")
        self._prev_verdict = verdict
        rc = health["recovery"]["count"]
        if self._prev_recoveries is not None and rc > self._prev_recoveries:
            recs = health["recovery"]["records"]
            triggers.append(
                "recovery:" + (recs[-1]["trigger"] if recs else "unknown"))
        self._prev_recoveries = rc
        slo = self.cluster.knobs.doctor_probe_p99_ms
        for hop in ("grv", "commit"):
            p99 = health["probe"][hop].get("p99_ms", 0.0) or 0.0
            if p99 > slo:
                if hop not in self._probe_breached:
                    self._probe_breached.add(hop)
                    triggers.append(f"probe_slo:{hop}")
            else:
                self._probe_breached.discard(hop)
        if triggers:
            self.dump(t, triggers, health, collector)
        return triggers

    def dump(self, t, triggers, health, collector):
        kn = self.cluster.knobs
        sites_fn = getattr(self.cluster, "buggify_sites", None)
        artifact = {
            "flight_schema": 1,
            "seq": self.dump_count,
            "t": round(t, 6),
            "triggers": list(triggers),
            "generation": self.cluster.generation,
            "verdict": health["verdict"],
            "reasons": list(health["reasons"]),
            "windows": collector.recent_windows(kn.flight_windows),
            "verdict_timeline": collector.recent_verdicts(
                kn.flight_windows),
            "recovery": _jsonable(health["recovery"]),
            "trace_tail": self._trace_tail(kn.flight_trace_tail),
            "buggify_sites": sorted(sites_fn()) if callable(sites_fn)
            else [],
        }
        if self.dir:
            os.makedirs(self.dir, exist_ok=True)
            path = os.path.join(
                self.dir, f"flight-{self.dump_count:04d}.json")
            with open(path, "w") as f:
                # sorted keys + no wall-time stamps: the same seed
                # writes the same bytes — the chaos-test contract
                f.write(json.dumps(artifact, sort_keys=True, indent=1,
                                   default=repr))
            artifact["path"] = path
        with self._mu:
            self.artifacts.append(artifact)
            self.dump_count += 1
            self.last_triggers = list(triggers)
        return artifact

    @staticmethod
    def _trace_tail(n):
        from foundationdb_tpu.utils.trace import global_trace_log

        events = global_trace_log().events()
        return [_jsonable(e) for e in events[-n:]]

    def latest(self):
        with self._mu:
            return self.artifacts[-1] if self.artifacts else None

    def summary(self):
        with self._mu:
            return {
                "dumps": self.dump_count,
                "retained": len(self.artifacts),
                "last_triggers": list(self.last_triggers),
                "dir": self.dir,
            }
