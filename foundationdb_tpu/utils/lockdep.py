"""Runtime lockdep witness — dynamic lock-order validation.

Ref parity: the Linux kernel's lockdep validator, applied to the role
flow's single-threaded actor model plays in the reference: FDB needs no
lock-order discipline because flow serializes everything onto one
loop; this port multithreads, so the discipline is checked instead.
The static half is flowlint FL006 (analysis/rules/fl006_lockorder.py):
every potential acquisition-order edge, from the whole-program AST.
This module is the dynamic half: every ACTUAL acquisition-order edge,
from running code. The contract binding them: the dynamic edge set is
a subset of the static graph (the static analysis over-approximates;
anything it missed is a resolver bug worth fixing).

Design, mirroring lockdep proper:

* **Classes, not instances.** Edges are keyed by the lock's declared
  name (``"Cluster._recovery_mu"``), so one witness covers every
  instance of a class — the same reduction that keeps lockdep's graph
  finite.
* **Adjacency, not closure.** On acquire, one edge is recorded:
  top-of-stack -> new (re-held names are skipped). Transitive order
  shows as a path, exactly like the static graph's edges.
* **Freeze after convergence.** After ``_FREEZE_AFTER`` consecutive
  acquisitions discover no new edge, per-acquire bookkeeping stops
  entirely — the wrappers check one module flag and forward straight
  to the inner primitive. A steady-state workload pays one global
  read per lock operation, which is what keeps the lockdep_smoke
  budget (≤2% e2e overhead enabled) honest.
* **Deterministic witness.** :func:`witness_doc` is canonical (sorted,
  no timestamps, no ids): two same-seed sim runs emit byte-identical
  documents.

Disabled (the default), the factories return plain ``threading``
primitives — zero wrapper cost. Enable with :func:`enable` or the
``FDB_TPU_LOCKDEP=1`` environment variable.
"""

import json
import os
import threading

__all__ = [
    "lock", "rlock", "condition", "enable", "disable", "enabled",
    "reset", "edge_set", "cycle_count", "cycles", "witness_doc",
    "acquisition_count",
]

_FREEZE_AFTER = 10_000

_enabled = os.environ.get("FDB_TPU_LOCKDEP", "") not in ("", "0")

# witness state — _graph_mu guards mutation; reads of _edges ride the
# GIL (dict membership is atomic) for the fast path
_graph_mu = threading.Lock()
_edges = {}    # (a, b) -> True
_cycles = []   # [(a, ..., a)] acquisition paths that closed a cycle
_acquisitions = 0
_quiet_streak = 0   # acquisitions since the last new edge
_frozen = False
_epoch = 0          # bumped by reset(): invalidates every held stack

_tls = threading.local()


def _held():
    # freezing mid-stack skips the matching release notes, so a stack
    # can go stale; reset() bumps the epoch and every thread drops its
    # stale stack lazily on next use (TLS is unreachable cross-thread)
    if getattr(_tls, "epoch", -1) != _epoch:
        _tls.epoch = _epoch
        _tls.stack = []
    return _tls.stack


def enabled():
    return _enabled


def enable():
    """Turn the witness on for locks created FROM NOW ON (existing
    plain primitives stay plain — enable before building the cluster)."""
    global _enabled
    _enabled = True


def disable():
    global _enabled
    _enabled = False


def reset():
    """Drop all recorded state (tests; between bench arms)."""
    global _acquisitions, _quiet_streak, _frozen, _epoch
    with _graph_mu:
        _edges.clear()
        del _cycles[:]
        _acquisitions = 0
        _quiet_streak = 0
        _frozen = False
        _epoch += 1


def _find_path(src, dst):
    """A path src -> ... -> dst through recorded edges, or None."""
    # tiny graphs: plain BFS under _graph_mu
    prev = {src: None}
    frontier = [src]
    while frontier:
        nxt = []
        for node in frontier:
            for (a, b) in _edges:
                if a == node and b not in prev:
                    prev[b] = node
                    if b == dst:
                        path = [b]
                        while path[-1] is not None:
                            p = prev[path[-1]]
                            if p is None:
                                break
                            path.append(p)
                        return list(reversed(path))
                    nxt.append(b)
        frontier = nxt
    return None


def _note_acquire(name):
    """Record top-of-stack -> name, detect cycles, then push."""
    global _acquisitions, _quiet_streak, _frozen
    _acquisitions += 1
    st = _held()
    if name in st:
        # reentrant (RLock) or sibling instance of a held class: no
        # self-edges — matches the static walk dropping re-held ids
        st.append(name)
        return
    top = st[-1] if st else None
    if top is None:
        # nothing held: no edge to record, but the streak still counts
        # — convergence means "no new edge lately", and unnested
        # acquires are most of a steady-state workload
        _quiet_streak += 1
        if _quiet_streak >= _FREEZE_AFTER:
            _frozen = True
        st.append(name)
        return
    key = (top, name)
    if key in _edges:  # GIL-safe fast path: dict hit, no mutex
        _quiet_streak += 1
        if _quiet_streak >= _FREEZE_AFTER:
            _frozen = True
        st.append(name)
        return
    with _graph_mu:
        if key not in _edges:
            # would the reverse order already be reachable? then this
            # acquisition closes a potential-deadlock cycle
            back = _find_path(name, top)
            _edges[key] = True
            _quiet_streak = 0
            if back is not None:
                _cycles.append(tuple(back + [name]))
    st.append(name)


def _note_release(name):
    st = _held()
    # defensive scan: release order need not mirror acquire order
    for i in range(len(st) - 1, -1, -1):
        if st[i] == name:
            del st[i]
            return


class _DepLock:
    """Instrumented Lock/RLock: records acquisition order per thread.

    Delegates ``_release_save`` / ``_acquire_restore`` / ``_is_owned``
    so a ``threading.Condition`` built over it (via :func:`condition`)
    waits correctly.
    """

    __slots__ = ("_inner", "name", "_acq", "_rel")

    def __init__(self, inner, name):
        self._inner = inner
        self.name = name
        # pre-bound inner methods: the frozen fast path is one global
        # read + one C call, no attribute chain
        self._acq = inner.acquire
        self._rel = inner.release

    def acquire(self, blocking=True, timeout=-1):
        got = self._acq(blocking, timeout)
        if got and not _frozen:
            _note_acquire(self.name)
        return got

    def release(self):
        self._rel()
        if not _frozen:
            _note_release(self.name)

    def __enter__(self):
        self._acq()
        if not _frozen:
            _note_acquire(self.name)
        return self

    def __exit__(self, t, v, tb):
        self._rel()
        if not _frozen:
            _note_release(self.name)
        return False

    def locked(self):
        return self._inner.locked()

    # Condition plumbing: wait() releases and re-acquires through these
    def _release_save(self):
        state = self._inner._release_save() if hasattr(
            self._inner, "_release_save") else self._inner.release()
        if not _frozen:
            _note_release(self.name)
        return state

    def _acquire_restore(self, state):
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        if not _frozen:
            _note_acquire(self.name)

    def _is_owned(self):
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        # plain Lock heuristic, as threading.Condition does it
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __repr__(self):
        return f"<DepLock {self.name} {self._inner!r}>"


def lock(name):
    """A named mutex: ``threading.Lock`` when the witness is off, an
    instrumented wrapper when on. ``name`` is the lock's CLASS identity
    ("Owner._attr") — it must match the static model's derived id."""
    if not _enabled:
        return threading.Lock()
    return _DepLock(threading.Lock(), name)


def rlock(name):
    if not _enabled:
        return threading.RLock()
    return _DepLock(threading.RLock(), name)


def condition(name, lock=None):
    """A condition over ``lock`` (or a fresh mutex named ``name``).
    Passing the owner's mutex ALIASES the condition to it — same node
    in the witness graph, matching the static model's Condition
    aliasing."""
    if not _enabled:
        return threading.Condition(lock)
    if lock is None:
        lock = _DepLock(threading.Lock(), name)
    return threading.Condition(lock)


def acquisition_count():
    return _acquisitions


def edge_set():
    """Frozen set of (a, b) acquisition-order edges observed so far."""
    with _graph_mu:
        return frozenset(_edges)


def cycle_count():
    with _graph_mu:
        return len(_cycles)


def cycles():
    with _graph_mu:
        return list(_cycles)


def witness_doc():
    """Canonical JSON witness: sorted edges + cycles, no timestamps —
    two same-seed runs produce byte-identical documents."""
    with _graph_mu:
        doc = {
            "edges": sorted([list(e) for e in _edges]),
            "cycles": sorted([list(c) for c in _cycles]),
        }
    return json.dumps(doc, sort_keys=True, separators=(",", ":"))
