"""Runtime fault-coverage witness — which coded-error fabrication
sites actually fire.

Ref parity: the reference's simulation culture only works because its
chaos provably REACHES the error paths — ``flow/Error.h`` codes are
fabricated at known sites and the swarm's value is measured by which of
them it exercises. The static half here is flowlint FL011
(analysis/rules/fl011_faultsites.py): every coded-error fabrication
site in the tree, enumerated from the AST into the checked-in
``analysis/faultsites.txt``. This module is the dynamic half: every
site that ACTUALLY fabricated an ``FDBError`` while the witness was
on, keyed by the same site id — ``module.dotted:qualname:code`` — so
the two sets diff directly. The binding contract (pinned by
``tests/test_flowlint_v3.py``): the dynamic fired set is a subset of
the static table; anything outside it is an enumerator bug worth
fixing.

Design, mirroring ``utils/lockdep.py``:

* **Kill switch.** Off (the default), ``FDBError.__init__`` pays one
  module-global read and nothing else. Enable with :func:`enable` or
  ``FDB_TPU_FAULTCOV=1``.
* **GIL-atomic counters.** ``note()`` bumps a per-site int in a plain
  dict — no mutex on the fabrication path. Under real threads a racing
  increment can be lost (counts are approximate); the fired SET is
  exact, and under the single-threaded deterministic sim the counts
  are exact too.
* **Attribution by frame walk.** The fabrication site is the first
  frame outside ``core/errors.py`` (``err`` → ``from_name`` →
  ``__init__`` are plumbing, not fabrication). Comprehension and
  lambda frames are skipped outward so attribution lands on the
  enclosing ``def`` — the same owner the static enumerator assigns.
  Frames outside the package (tests, bench) and the excluded
  propagation seam ``rpc/wire.py`` (it *deserializes* coded errors
  arriving off the wire — fabricated elsewhere) are not counted.
* **Deterministic witness.** :func:`witness_doc` is canonical (sorted,
  no timestamps): two same-seed sim runs emit byte-identical
  documents.

Qualnames come from :func:`qualname_index` — a per-file AST map built
lazily on first sighting and shared with the static rule, so both
sides derive ``ClassName.method`` / ``outer.inner`` identically by
construction (Python 3.10 has no ``co_qualname``).
"""

import ast
import json
import os
import sys

__all__ = [
    "enable", "disable", "enabled", "reset", "note",
    "fired", "counts", "fired_codes", "witness_doc",
    "qualname_index", "site_id", "EXCLUDED_MODULES",
]

_enabled = os.environ.get("FDB_TPU_FAULTCOV", "") not in ("", "0")

# module.dotted ids whose frames never count as fabrication sites:
# core.errors is the constructor plumbing itself; rpc.wire DECODES
# coded errors that crossed the wire (propagation, not fabrication);
# analysis.* builds Finding objects about errors, it never raises them
EXCLUDED_MODULES = frozenset({"core.errors", "rpc.wire"})
_EXCLUDED_PREFIXES = ("analysis.",)

# frames that are lexical sugar, not owners: attribute to the
# enclosing def, exactly like the AST enumerator does
_SKIP_CO_NAMES = frozenset({
    "<listcomp>", "<setcomp>", "<dictcomp>", "<genexpr>", "<lambda>",
})

_counts = {}        # site id -> fire count
_qualnames = {}     # abspath -> {firstlineno: qualname} (lazy, cached)
_module_ids = {}    # abspath -> module.dotted or None (lazy, cached)

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ERRORS_FILE = os.path.join(_PKG_DIR, "core", "errors.py")
_SELF_FILE = os.path.abspath(__file__)


def enabled():
    return _enabled


def enable():
    global _enabled
    _enabled = True


def disable():
    global _enabled
    _enabled = False


def reset():
    """Drop all recorded fires (tests; between bench arms). The lazy
    qualname cache survives — it is derived from source, not runs."""
    _counts.clear()


def qualname_index(tree):
    """``{lineno: qualname}`` for every (Async)FunctionDef in ``tree``,
    qualnames as dotted owner chains (``ClassName.method``,
    ``outer.inner``). Each def registers BOTH its ``def`` line and its
    decorator lines: CPython's ``co_firstlineno`` points at the first
    decorator when one exists, the AST's ``lineno`` at the ``def``."""
    index = {}

    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = prefix + child.name if prefix else child.name
                index.setdefault(child.lineno, qn)
                for dec in child.decorator_list:
                    index.setdefault(dec.lineno, qn)
                walk(child, qn + ".")
            elif isinstance(child, ast.ClassDef):
                cp = prefix + child.name if prefix else child.name
                walk(child, cp + ".")
            else:
                walk(child, prefix)

    walk(tree, "")
    return index


def _module_id(filename):
    """``server.storage`` for a file under the package dir, else None
    (tests, bench, site-packages — not fabrication we enumerate)."""
    mid = _module_ids.get(filename)
    if mid is not None or filename in _module_ids:
        return mid
    try:
        rel = os.path.relpath(filename, _PKG_DIR)
    except ValueError:           # different drive (windows)
        rel = ".."
    if rel.startswith("..") or not rel.endswith(".py"):
        mid = None
    else:
        mid = rel[:-3].replace(os.sep, ".")
        if mid.endswith(".__init__"):
            mid = mid[: -len(".__init__")]
    _module_ids[filename] = mid
    return mid


def _file_qualnames(filename):
    qn = _qualnames.get(filename)
    if qn is None:
        try:
            with open(filename, encoding="utf-8") as f:
                tree = ast.parse(f.read())
            qn = qualname_index(tree)
        except (OSError, SyntaxError):
            qn = {}
        _qualnames[filename] = qn
    return qn


def site_id(module, qualname, code):
    return f"{module}:{qualname}:{code}"


def note(code):
    """Called by ``FDBError.__init__`` when the witness is on: walk out
    of core/errors.py to the fabrication frame and bump its counter."""
    try:
        frame = sys._getframe(2)  # note -> __init__ -> caller
    except ValueError:
        return
    while frame is not None:
        fn = frame.f_code.co_filename
        if fn == _ERRORS_FILE or fn == _SELF_FILE or \
                frame.f_code.co_name in _SKIP_CO_NAMES:
            frame = frame.f_back
            continue
        break
    if frame is None:
        return
    filename = frame.f_code.co_filename
    module = _module_id(filename)
    if module is None or module in EXCLUDED_MODULES or \
            module.startswith(_EXCLUDED_PREFIXES):
        return
    # module-level raises have co_firstlineno 1 and co_name "<module>"
    # — the fallback is already the right owner label
    qualname = _file_qualnames(filename).get(
        frame.f_code.co_firstlineno, frame.f_code.co_name)
    site = f"{module}:{qualname}:{code}"
    _counts[site] = _counts.get(site, 0) + 1


def fired():
    """Frozen set of site ids that fired so far."""
    return frozenset(_counts)


def counts():
    """``{site id: fire count}`` snapshot (counts approximate under
    real threads, exact under the single-threaded sim)."""
    return dict(_counts)


def fired_codes():
    """Frozen set of int error codes that fired so far."""
    out = set()
    for site in _counts:
        try:
            out.add(int(site.rsplit(":", 1)[1]))
        except ValueError:
            continue
    return frozenset(out)


def witness_doc():
    """Canonical JSON witness: sorted site->count map, no timestamps —
    two same-seed sim runs produce byte-identical documents."""
    doc = {"fired": {site: _counts[site] for site in sorted(_counts)}}
    return json.dumps(doc, sort_keys=True, separators=(",", ":"))
