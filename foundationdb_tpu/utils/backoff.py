"""Jittered exponential backoff — the one retry-delay policy.

Ref parity: flow's ``Backoff`` (flow/genericactors.actor.h) — delay
starts small, grows by a factor per failure, caps at a max, resets on
success, and is jittered so a fleet of clients retrying against the
same recovering process doesn't re-arrive in lockstep. Every retry
sleep in the repo routes through this class; ad-hoc ``time.sleep`` of
a hand-grown delay variable is a flowlint finding (FL001's
manual-backoff extension).

Jitter rides the ``"backoff-jitter"`` named deterministic stream
(core/deterministic.py), so same-seed sims draw identical retry
schedules and production gets real desynchronization for free.

The module-level retry counter feeds the bench e2e lines
(``backoff_retries``): a cheap, lock-guarded tally of every jittered
sleep actually taken, snapshot-deltaed per run.
"""

import time

from foundationdb_tpu.core import deterministic
from foundationdb_tpu.utils import lockdep

_JITTER_STREAM = "backoff-jitter"

_count_lock = lockdep.lock("backoff._count_lock")
_retries = 0


def retry_count():
    """Cumulative process-wide count of backoff sleeps taken."""
    with _count_lock:
        return _retries


def _note_retry():
    global _retries
    with _count_lock:
        _retries += 1


class Backoff:
    """Exponential backoff with seeded jitter, cap, reset-on-success.

    ``delay()`` returns the next jittered delay and advances the
    schedule; ``sleep()`` additionally takes the sleep and bumps the
    process retry counter. ``reset()`` re-arms the schedule after a
    success, matching flow's ``Backoff::onSuccess``.
    """

    def __init__(self, initial_s=0.01, max_s=1.0, growth=2.0,
                 jitter=0.1):
        if growth < 1.0:
            raise ValueError(f"growth must be >= 1.0, got {growth}")
        self.initial_s = float(initial_s)
        self.max_s = float(max_s)
        self.growth = float(growth)
        self.jitter = float(jitter)
        self._current = self.initial_s
        self.attempts = 0  # failures seen since the last reset

    @property
    def current(self):
        """The next un-jittered delay (what ``delay()`` would base on)."""
        return min(self._current, self.max_s)

    def delay(self):
        """Next jittered delay in seconds; advances the schedule."""
        base = min(self._current, self.max_s)
        self._current = min(self._current * self.growth, self.max_s)
        self.attempts += 1
        if self.jitter <= 0.0:
            return base
        # uniform in [1-j, 1+j): desynchronizes a retrying fleet while
        # keeping the expected delay equal to the un-jittered schedule
        u = deterministic.rng(_JITTER_STREAM).random()
        return base * (1.0 + self.jitter * (2.0 * u - 1.0))

    def sleep(self):
        """Take the next backoff sleep; returns the delay slept."""
        d = self.delay()
        _note_retry()
        if d > 0.0:
            time.sleep(d)
        return d

    def reset(self):
        """Success: the next failure starts from ``initial_s`` again."""
        self._current = self.initial_s
        self.attempts = 0
