"""Workload-attribution heatmaps: bounded, decayed key-range histograms.

Ref parity: fdbserver/StorageMetrics.actor.cpp (byte-sampled per-key
metrics and ``getReadHotRanges``) + the per-range conflict attribution
that fdbclient/TagThrottle.actor.cpp's throttling decisions lean on.
Every producer (commit proxy conflict charging, storage read/write
sampling) owns a :class:`KeyRangeHeatmap`; ``cluster.status()``
aggregates their snapshots under ``cluster.workload.hot_ranges`` and
``tools/heatmap.py`` turns the cumulative heat into split-point advice.

Determinism: decay timestamps ride ``core.deterministic.now()`` (the
sim's step clock when seeded) and the storage sampling draws ride the
``key-sample`` named stream, so two same-seed simulations emit
byte-identical hot-range snapshots (FL001: no ambient entropy here).

Overhead: the module-level ``set_enabled(False)`` kill switch turns
every ``charge`` into an early return — ``BENCH_MODE=heatmap_smoke``
runs the ycsb e2e both ways and gates the difference at 2%, the same
protocol as metrics_smoke.
"""

import heapq
import struct
import threading

from foundationdb_tpu.core import deterministic
from foundationdb_tpu.utils import lockdep

_enabled = True


def set_enabled(on):
    """Process-wide kill switch (the heatmap_smoke overhead probe)."""
    global _enabled
    _enabled = bool(on)


def enabled():
    return _enabled


def entry_key(entry):
    """Flat limb entry → raw key (core/flatpack.py layout: the key
    zero-padded to 4·L bytes followed by >I(len)). The commit proxy
    charges raw ENTRIES — order-isomorphic to keys, zero decode on the
    abort path, the same trick as server/scheduler.py — and snapshots
    pay this decode only when someone actually reads the heatmap."""
    return entry[: struct.unpack(">I", entry[-4:])[0]]


class KeyRangeHeatmap:
    """Bounded decayed histogram over an ordered byte keyspace.

    Buckets are anchor keys kept in sorted order; bucket *i* owns the
    range [anchor_i, anchor_{i+1}) and the last bucket runs to the end
    of the keyspace. ``charge(key, w)`` credits the bucket anchored at
    ``key`` — new anchors insert freely until ``max_buckets``, then the
    adjacent pair with the least combined heat coalesces (the lower
    anchor absorbs the upper's range and weight), so state stays
    bounded forever while hot anchors survive the merges.

    Heat decays exponentially with ``half_life_s`` off the injected
    deterministic clock, applied lazily per bucket: a bucket's stored
    (weight, stamp) pair reads as ``weight * 2**-((now-stamp)/hl)``.

    ``decode`` maps stored bucket keys to real keys at snapshot time
    (identity by default); total weight is conserved by merges and
    ``absorb`` — a recovery or fleet shrink never rewinds heat.
    """

    def __init__(self, name, max_buckets=64, half_life_s=30.0,
                 decode=None):
        self.name = name
        self._k = max(2, int(max_buckets))
        self._hl = float(half_life_s)
        self._decode = decode if decode is not None else (lambda k: k)
        self._lock = lockdep.lock("KeyRangeHeatmap._lock")
        self._w = {}  # anchor bytes -> weight at stamp
        self._t = {}  # anchor bytes -> decay stamp
        self._charges = 0  # exact lifetime event count (never decays)

    # ── hot path ──
    def charge(self, key, weight=1.0):
        if not _enabled or weight <= 0.0:
            return
        now = deterministic.now()
        with self._lock:
            self._charges += 1
            w = self._w.get(key)
            if w is not None:
                self._w[key] = w * self._decay(now - self._t[key]) + weight
                self._t[key] = now
            else:
                self._w[key] = weight
                self._t[key] = now
                # amortized bound: let anchors overshoot to 4k and fold
                # back to k in one coalesce. Coalescing on every
                # over-cap insert was measured at ~10% e2e overhead
                # under uniform-key sampling, where nearly every charge
                # is a fresh anchor; the read side (snapshot /
                # split_points) coalesces to k on the way out, so the
                # published document is still k-bounded.
                if len(self._w) > 4 * self._k:
                    self._coalesce_locked(now)

    def _decay(self, dt):
        if self._hl <= 0.0 or dt <= 0.0:
            return 1.0
        return 2.0 ** (-dt / self._hl)

    def _settle_locked(self, now):
        """Bring every bucket's lazy (weight, stamp) pair to ``now`` so
        weights are directly comparable."""
        for k, t in self._t.items():
            if t != now:
                self._w[k] *= self._decay(now - t)
                self._t[k] = now

    def _coalesce_locked(self, now):
        """Adjacent-range merge: fold the least-heat neighbor pairs into
        their lower anchors until the bucket bound holds. Total weight
        is conserved; anchors stay a sorted subset of charged keys.

        Cost matters here — this runs from the charge hot path. The
        textbook loop (extract the global min pair, repeat) is O(k^2)
        per coalesce and measured ~17us/charge end to end; instead each
        pass picks the excess-th smallest pair sum as a threshold and
        folds qualifying pairs in ONE left-to-right sweep. Chained folds
        inflate the absorbing anchor past the threshold, so merges
        spread out like the exact algorithm's; the globally minimal pair
        always qualifies, so every pass merges at least once and the
        loop terminates in a handful of passes."""
        self._settle_locked(now)
        anchors = sorted(self._w)
        while len(anchors) > self._k:
            excess = len(anchors) - self._k
            sums = [self._w[anchors[i]] + self._w[anchors[i + 1]]
                    for i in range(len(anchors) - 1)]
            thresh = heapq.nsmallest(excess, sums)[-1]
            kept = [anchors[0]]
            merges = 0
            for hi in anchors[1:]:
                lo = kept[-1]
                if (merges < excess
                        and self._w[lo] + self._w[hi] <= thresh):
                    self._w[lo] += self._w.pop(hi)
                    del self._t[hi]
                    merges += 1
                else:
                    kept.append(hi)
            anchors = kept

    # ── read side ──
    @property
    def charges(self):
        return self._charges

    def total_heat(self):
        now = deterministic.now()
        with self._lock:
            return sum(
                w * self._decay(now - self._t[k])
                for k, w in self._w.items()
            )

    def snapshot(self, top=None):
        """JSON-ready sorted range list: ``[{begin, end, heat}, ...]``
        (begin/end are latin-1 decoded keys; the last range's end is
        None = the keyspace end). ``top`` keeps only the N hottest
        ranges, still ordered by key so they read as a map."""
        now = deterministic.now()
        with self._lock:
            self._coalesce_locked(now)  # publish at most max_buckets
            anchors = sorted(self._w)
            rows = []
            for i, a in enumerate(anchors):
                end = (self._decode(anchors[i + 1])
                       if i + 1 < len(anchors) else None)
                rows.append({
                    "begin": self._decode(a).decode("latin-1"),
                    "end": end.decode("latin-1") if end is not None
                    else None,
                    "heat": round(self._w[a], 4),
                })
        if top is not None and len(rows) > top:
            keep = sorted(rows, key=lambda r: (-r["heat"], r["begin"]))
            keep = {id(r) for r in keep[:top]}
            rows = [r for r in rows if id(r) in keep]
        return rows

    def split_points(self, n):
        """Suggested split keys at cumulative-heat quantiles: n-1 keys
        cutting the keyspace into n shards of roughly equal CURRENT
        heat — the exact input a lane-sharding pass needs."""
        if n <= 1:
            return []
        now = deterministic.now()
        with self._lock:
            self._coalesce_locked(now)  # quantiles over the k-bounded map
            anchors = sorted(self._w)
            weights = [self._w[a] for a in anchors]
        total = sum(weights)
        if total <= 0.0 or len(anchors) < 2:
            return []
        points = []
        acc = 0.0
        targets = [total * i / n for i in range(1, n)]
        ti = 0
        for a, w in zip(anchors, weights):
            while ti < len(targets) and acc >= targets[ti]:
                key = self._decode(a)
                if not points or points[-1] != key:
                    points.append(key)
                ti += 1
            acc += w
        return points

    def absorb(self, other):
        """Fold a retiring heatmap's state in (txn-system recovery,
        resolver respawn, configure() fleet shrink): weights add at a
        common stamp — heat never rewinds. Mirrors MetricsRegistry's
        adopt/absorb lifecycle, and deliberately bypasses the kill
        switch: carried history is not new overhead."""
        now = deterministic.now()
        with other._lock:
            other._settle_locked(now)
            o_rows = list(other._w.items())
            o_charges = other._charges
        with self._lock:
            self._settle_locked(now)
            for k, w in o_rows:
                self._w[k] = self._w.get(k, 0.0) + w
                self._t[k] = now
            self._charges += o_charges
            if len(self._w) > self._k:
                self._coalesce_locked(now)


def merged(heatmaps, name="merged", max_buckets=64, half_life_s=30.0,
           decode=None):
    """One heatmap over several producers (fleet rollup: the cluster's
    conflict heat across every commit proxy)."""
    acc = KeyRangeHeatmap(name, max_buckets=max_buckets,
                          half_life_s=half_life_s, decode=decode)
    for h in heatmaps:
        if h is not None:
            acc.absorb(h)
    return acc
