"""Two-region disaster recovery: an async satellite log + failover.

Ref parity: the reference's region configuration (region blocks in
fdbclient/DatabaseConfiguration.cpp, satellite tlog recruitment in
masterserver/ClusterRecovery) and the fdbdr async-replication shape: a
secondary region consumes the primary's committed stream ASYNCHRONOUSLY
— commits never wait on the WAN — so a regional disaster loses at most
the measured replication lag, and failover promotes the secondary to a
full read/write cluster.

Shape here:
- ``SecondaryRegion`` owns a satellite ``TLog`` (WAL-backed) and pulls
  the primary log's stream on ``pump()`` (the simulation's — or an
  operator loop's — heartbeat; deterministic under the sim scheduler).
  A pop-hold on the primary pins records until they replicate, exactly
  like a storage worker's cursor, so the satellite never gaps.
- ``partition()`` models the WAN failing: pumps become no-ops and the
  lag grows (the primary keeps committing — asynchronous replication's
  defining trade).
- ``failover()`` promotes: a fresh ``Cluster`` recovers from the
  satellite WAL through the ORDINARY recovery machinery (WAL replay +
  CAS generation) — the promoted region serves everything up to the
  replication frontier; commits past it (== the lag at disaster time)
  are the bounded loss the async mode accepts.
"""

import os

from foundationdb_tpu.core.mutations import Mutation, Op
from foundationdb_tpu.server.tlog import TLog, TLogDown
from foundationdb_tpu.utils.trace import TraceEvent

HOLD_NAME = "dr-secondary"


class SecondaryRegion:
    def __init__(self, primary_cluster, wal_path):
        self.primary = primary_cluster
        self.wal_path = wal_path
        os.makedirs(os.path.dirname(wal_path) or ".", exist_ok=True)
        self.tlog = TLog(wal_path=wal_path)
        self.position = 0  # replication frontier (last version applied)
        self.partitioned = False
        self.broken = False  # continuity gap detected (see pump)
        self._dropped = False
        # pin the primary log from the start: records must survive until
        # the satellite has them (ref: satellite tlogs holding the
        # primary's mutation stream)
        self.primary.tlog.hold_pop(HOLD_NAME, self.position)
        self._seed()

    def _seed(self):
        """Base snapshot into the satellite WAL: a log-only satellite
        attached to a primary with prior history (a recovered log's
        floor is its recovery version) cannot reconstruct that history
        from the log — DR starts with a full copy, then tails (ref:
        fdbdr's initial range copy before mutation streaming). The
        snapshot rides as ONE synthetic log record at its read version;
        promotion replays it like any other record. The scan runs through
        the SYSTEM keyspace (end b"\\xff\\xff", matching
        storage_owned_ranges' everywhere-replicated treatment of
        [\\xff, \\xff\\xff)): the tailed log replicates system mutations,
        so the seed must carry the pre-attach system state too — tenant
        map/modes/quotas, lock uid — or the promoted cluster would hold
        tenant data its tenant map has never heard of."""
        db = self.primary.database()
        tr = db.create_transaction()
        v = tr.get_read_version()
        muts = []
        begin = b""
        while True:
            rows = tr.get_range(begin, b"\xff\xff", limit=1000,
                                snapshot=True)
            muts.extend(Mutation(Op.SET, k, val) for k, val in rows)
            if len(rows) < 1000:
                break
            begin = rows[-1][0] + b"\x00"
        if v > 0:
            self.tlog.push(v, muts)
        self.position = v
        self.primary.tlog.hold_pop(HOLD_NAME, v)

    # ── replication (pumped) ──
    def pump(self):
        """Pull everything the primary has committed past our frontier.
        Returns the number of records replicated this round."""
        if self.partitioned or self._dropped or self.broken:
            return 0
        try:
            # GAP check first: a primary that crashed and recovered
            # comes back with a fresh log (floor = its recovery
            # version) and our pop-hold gone — versions in
            # (position, floor] are unobtainable, and silently tailing
            # past them would promote a TORN database at failover.
            # Mark broken loudly; the operator re-seeds DR.
            if self.primary.tlog._first_version > self.position:
                self.broken = True
                TraceEvent("RegionReplicationGap", severity=40).detail(
                    frontier=self.position,
                    primary_floor=self.primary.tlog._first_version,
                ).log()
                return 0
            records = self.primary.tlog.peek(self.position)
        except TLogDown:
            return 0  # primary log tier degraded: retry next round
        n = 0
        for version, muts in records:
            if version <= self.position:
                continue
            self.tlog.push(version, muts)
            self.position = version
            n += 1
        if n:
            self.primary.tlog.hold_pop(HOLD_NAME, self.position)
        return n

    def lag_versions(self):
        """How far behind the primary's committed frontier we are — the
        bounded data loss a failover right now would accept."""
        return max(
            0, self.primary.sequencer.committed_version - self.position
        )

    # ── WAN fault / lifecycle ──
    def partition(self):
        self.partitioned = True
        TraceEvent("RegionPartitioned", severity=30).detail(
            frontier=self.position).log()

    def heal(self):
        self.partitioned = False

    def reattach(self, new_primary):
        """Point at a new primary incarnation (crash/recovery swapped
        the cluster object). Gap detection on the next pump decides
        whether continuity survived — a satellite that was fully caught
        up resumes cleanly; one that was behind marks itself broken."""
        self.primary = new_primary
        if not self._dropped:
            self.primary.tlog.hold_pop(HOLD_NAME, self.position)

    def drop(self):
        """Primary abandons DR: release the log pin (otherwise the
        primary's log grows forever against a dead satellite)."""
        self._dropped = True
        try:
            self.primary.tlog.release_pop(HOLD_NAME)
        except TLogDown:
            pass

    # ── failover ──
    def failover(self, **cluster_kwargs):
        """Promote this region to a full cluster (ref: forced region
        failover). Recovery replays the satellite WAL — the promoted
        database is exactly the primary's state at the replication
        frontier; the lag at disaster time is the accepted loss.
        Returns the promoted Cluster."""
        from foundationdb_tpu.server.cluster import Cluster

        if self.broken:
            raise RuntimeError(
                "replication gap: this satellite lost continuity "
                "(RegionReplicationGap) — re-seed DR before failing over"
            )
        self.tlog.close()  # flush the WAL handle before recovery reads it
        lost = self.lag_versions() if not self.partitioned else None
        promoted = Cluster(wal_path=self.wal_path, **cluster_kwargs)
        TraceEvent("RegionFailover").detail(
            frontier=self.position,
            lag_at_failover=lost if lost is not None else "partitioned",
        ).log()
        return promoted
