r"""Multi-region replication: region config, satellite log, failover.

Ref parity: the reference's region configuration (region blocks in
fdbclient/DatabaseConfiguration.cpp, satellite tlog recruitment in
masterserver/ClusterRecovery) and the fdbdr replication shape. Two
layers live here:

* ``RegionConfig`` — the parsed/validated ``configure regions=<json>``
  block (primary/remote region ids, satellite replica count, sync vs
  async satellite mode). The canonical JSON persists beside the
  replication factor in the ``\xff/conf/regions`` system row, so WAL
  recovery restores the region configuration like any other config.
* ``RegionReplicator`` — the CLUSTER-OWNED replication subsystem
  ``configure regions=...`` attaches: it owns the satellite log (a
  region-tagged ``TLog``/``TLogSystem`` with its own WAL), seeds it
  with a base snapshot, and keeps it caught up CONTINUOUSLY — no
  operator pump. In **sync** satellite mode the commit path calls
  ``sync_push`` before acknowledging each commit, so a regional
  disaster loses zero acked transactions; in **async** mode commits
  never wait on the WAN and the streamer drains the backlog on its own
  cadence (the lag is measured in versions AND milliseconds). The
  streamer is driven by the thread scheduler in production
  (``start()``'s named daemon loop) and by the sim scheduler
  deterministically (``maybe_stream()`` off the injected clock plus the
  named "region-stream" RNG stream — the FL001 seam). A pop-hold on
  the primary log pins records until they replicate, so the satellite
  never gaps; a primary that recovered with a fresh log floor past our
  frontier marks the link ``broken`` loudly instead of tearing.
* **Automatic failover** rides ``Cluster.detect_and_recruit``: when
  every primary-region process is dead the cluster promotes the remote
  region IN PLACE through the ordinary recovery machinery
  (``Cluster._region_failover`` — generation CAS, satellite-log replay
  into fresh storages, fenced resolvers, new frontend) and the
  transition lands in the RecoveryTimeline under a ``region_failover``
  trigger. Note for full-process restarts: after a failover the
  cluster's durable log IS the satellite WAL.

``SecondaryRegion`` is the original operator-driven DR bolt-on, kept
as a thin manual wrapper over the same seed/drain helpers: ``pump()``
by hand, ``failover()`` into a brand-new cluster. The cluster-owned
subsystem above supersedes it for anything configured through
``configure regions=...``.
"""

import os
import threading

from foundationdb_tpu.core import deterministic
from foundationdb_tpu.core.errors import err
from foundationdb_tpu.core.mutations import Mutation, Op
from foundationdb_tpu.server.tlog import TLog, TLogDown, TLogSystem
from foundationdb_tpu.utils import lockdep
from foundationdb_tpu.utils.trace import TraceEvent

HOLD_NAME = "dr-secondary"


class RegionConfig:
    """Parsed ``configure regions=<json>`` block (ref: the region array
    of DatabaseConfiguration). Immutable; compares by value."""

    MODES = ("sync", "async")

    def __init__(self, primary, remote, satellites=1,
                 satellite_mode="async"):
        self.primary = str(primary)
        self.remote = str(remote)
        self.satellites = int(satellites)
        self.satellite_mode = str(satellite_mode)

    @classmethod
    def parse(cls, spec):
        """dict | JSON str/bytes → RegionConfig, validating every field
        (fdbcli hands the raw value through; a typo must fail the
        configure, not half-apply)."""
        import json

        if isinstance(spec, (bytes, bytearray)):
            spec = spec.decode()
        if isinstance(spec, str):
            try:
                spec = json.loads(spec)
            except ValueError:
                raise err("invalid_option_value")
        if isinstance(spec, cls):
            return spec
        if not isinstance(spec, dict):
            raise err("invalid_option_value")
        primary = spec.get("primary")
        remote = spec.get("remote")
        if not primary or not remote or primary == remote:
            raise err("invalid_option_value")
        try:
            satellites = int(spec.get("satellites", 1))
        except (TypeError, ValueError):
            raise err("invalid_option_value")
        if satellites < 1:
            raise err("invalid_option_value")
        mode = spec.get("satellite_mode", "async")
        if mode not in cls.MODES:
            raise err("invalid_option_value")
        unknown = set(spec) - {"primary", "remote", "satellites",
                               "satellite_mode"}
        if unknown:
            raise err("invalid_option_value")
        return cls(primary, remote, satellites, mode)

    def to_json(self):
        import json

        return json.dumps(
            {"primary": self.primary, "remote": self.remote,
             "satellites": self.satellites,
             "satellite_mode": self.satellite_mode},
            sort_keys=True,
        )

    def __eq__(self, other):
        return (isinstance(other, RegionConfig)
                and self.to_json() == other.to_json())

    def __ne__(self, other):
        return not self.__eq__(other)

    def __repr__(self):
        return f"RegionConfig({self.to_json()})"


# ── shared seed/drain machinery ──────────────────────────────────────
def seed_snapshot(primary_cluster, satellite_log, hold_name):
    """Base snapshot into the satellite log; returns the replication
    frontier (the snapshot's read version). A log-only satellite
    attached to a primary with prior history (a recovered log's floor
    is its recovery version) cannot reconstruct that history from the
    log — replication starts with a full copy, then tails (ref: fdbdr's
    initial range copy before mutation streaming). The snapshot rides
    as ONE synthetic log record at its read version; promotion replays
    it like any other record. The scan runs through the SYSTEM keyspace
    (end b"\\xff\\xff", matching storage_owned_ranges'
    everywhere-replicated treatment of [\\xff, \\xff\\xff)): the tailed
    log replicates system mutations, so the seed must carry the
    pre-attach system state too — tenant map/modes/quotas, lock uid,
    shard map — or the promoted cluster would hold data its own
    metadata has never heard of."""
    db = primary_cluster.database()
    tr = db.create_transaction()
    v = tr.get_read_version()
    muts = []
    begin = b""
    while True:
        rows = tr.get_range(begin, b"\xff\xff", limit=1000, snapshot=True)
        muts.extend(Mutation(Op.SET, k, val) for k, val in rows)
        if len(rows) < 1000:
            break
        begin = rows[-1][0] + b"\x00"
    if v > 0:
        satellite_log.push(v, muts)
    primary_cluster.tlog.hold_pop(hold_name, v)
    return v


def drain_log(primary_tlog, satellite_log, position, hold_name,
              up_to=None):
    """Copy primary records past ``position`` into the satellite, in
    version order, advancing the pop-hold as the frontier moves.
    Returns (records_copied, new_position, broken):

    * GAP check first: a primary that crashed and recovered comes back
      with a fresh log (floor = its recovery version) and our pop-hold
      gone — versions in (position, floor] are unobtainable, and
      silently tailing past them would promote a TORN database at
      failover. ``broken=True`` marks it loudly; the operator (or a
      restore-time re-seed) re-establishes replication.
    * ``up_to`` bounds the drain (sync mode copies through the commit
      being acknowledged and no further).
    * A dead primary log tier is retryable: (0, position, False).
    """
    try:
        if primary_tlog._first_version > position:
            TraceEvent("RegionReplicationGap", severity=40).detail(
                frontier=position,
                primary_floor=primary_tlog._first_version,
            ).log()
            return 0, position, True
        records = primary_tlog.peek(position)
    except TLogDown:
        return 0, position, False
    n = 0
    for version, muts in records:
        if version <= position:
            continue
        if up_to is not None and version > up_to:
            break
        satellite_log.push(version, muts)
        position = version
        n += 1
    if n:
        primary_tlog.hold_pop(hold_name, position)
    return n, position, False


class RegionReplicator:
    """The cluster-owned replication subsystem behind ``configure
    regions=...``: satellite log ownership, the continuous streamer,
    sync-mode commit gating, and failover bookkeeping. See the module
    docstring for the full shape."""

    HOLD = "region-satellite"

    def __init__(self, cluster, config, wal_path=None):
        self.cluster = cluster
        self.config = config
        self.active = config.primary  # flips to remote on failover
        self.wal_path = wal_path
        if wal_path:
            os.makedirs(os.path.dirname(wal_path) or ".", exist_ok=True)
            # fresh attach/restore truncates stale satellite WALs: the
            # seed below re-establishes the full base, and stale
            # records merging under a recovered log would resurrect a
            # previous attachment's history
            for p in ([wal_path] if config.satellites == 1 else
                      TLogSystem.replica_paths(wal_path, config.satellites)):
                open(p, "wb").close()
        if config.satellites > 1:
            self.satellite = TLogSystem(config.satellites,
                                        wal_path=wal_path)
        else:
            self.satellite = TLog(wal_path=wal_path)
        for log in self._satellite_logs():
            log.region = config.remote
        self.position = 0
        self.partitioned = False
        self.broken = False
        self.dropped = False
        self.sync_misses = 0  # sync-mode commits acked WITHOUT the satellite
        self.failovers = 0
        self.failed_attempts = 0  # failover rounds lost to coordination
        self.last_failover_ms = 0.0
        # streamer state is shared between the commit path (sync_push
        # under the proxy's commit mutex), the streamer (sim schedule
        # or the daemon loop below), and WAN fault injection — one lock
        # serializes the frontier
        self._mu = lockdep.lock("RegionReplicator._mu")
        # jittered cadence off the named deterministic stream (FL001):
        # same-seed sims stream at the same steps, real fleets de-align
        self._rng = deterministic.rng("region-stream")
        # flowlint: shared(single-driver protocol: thread mode streams ONLY from the region-streamer daemon, sims ONLY from their scheduler — never both, one writer at a time)
        self._next_due = None
        self._caught_up_at = deterministic.now()
        self._stop = threading.Event()
        self._thread = None
        # pin the primary log from the start: records must survive
        # until the satellite has them (ref: satellite tlogs holding
        # the primary's mutation stream)
        cluster.tlog.hold_pop(self.HOLD, 0)
        self.position = seed_snapshot(cluster, self.satellite, self.HOLD)
        TraceEvent("RegionConfigured").detail(
            primary=config.primary, remote=config.remote,
            satellites=config.satellites, mode=config.satellite_mode,
            seed_version=self.position).log()

    def _satellite_logs(self):
        if isinstance(self.satellite, TLogSystem):
            return self.satellite.logs
        return [self.satellite]

    @property
    def replicating(self):
        """True while this subsystem is shipping primary → satellite
        (failover or drop ends the stream; the promoted region then
        OWNS the satellite log)."""
        return self.active == self.config.primary and not self.dropped

    # ── commit-path gating (sync satellite mode) ─────────────────────
    def sync_push(self, version, mutations):
        """Called by the commit proxy AFTER the primary log accepted
        the batch and BEFORE the commit is acknowledged (sync satellite
        mode only): drain the primary log through this version into the
        satellite, so every acked commit is already in the remote
        region. Backfills any gap left by a healed partition using the
        pinned primary records. Returns True iff the satellite holds
        this commit; a False (WAN partitioned / satellite dead) still
        ACKS the commit — the cluster degrades to async rather than
        stalling commits on the WAN — counted in ``sync_misses`` and
        surfaced by the doctor as degraded."""
        if self.config.satellite_mode != "sync" or not self.replicating:
            return False
        with self._mu:
            if self.partitioned or self.broken:
                self.sync_misses += 1
                return False
            try:
                _, self.position, self.broken = drain_log(
                    self.cluster.tlog, self.satellite, self.position,
                    self.HOLD, up_to=version,
                )
            except (TLogDown, ValueError):
                self.sync_misses += 1
                return False
            if self.broken or self.position < version:
                self.sync_misses += 1
                return False
            self._caught_up_at = deterministic.now()
            return True

    # ── continuous streamer ──────────────────────────────────────────
    def maybe_stream(self):
        """Drain once if the knob interval elapsed (pull-based, exactly
        the LatencyProber cadence shape); returns records copied. Sims
        call this from their scheduler; thread-mode clusters from the
        daemon loop below."""
        if not self.replicating:
            return 0
        interval = self.cluster.knobs.region_stream_interval_s
        now = deterministic.now()
        if self._next_due is None:
            # first call arms the schedule with a jittered offset so a
            # fleet of streamers never thunders in step
            self._next_due = now + interval * self._rng.random()
            return 0
        if now < self._next_due:
            return 0
        self._next_due = now + interval * (0.5 + self._rng.random())
        return self.stream_now()

    def stream_now(self):
        """One unconditional drain round; returns records copied."""
        if not self.replicating:
            return 0
        with self._mu:
            if self.partitioned or self.broken:
                return 0
            n, self.position, self.broken = drain_log(
                self.cluster.tlog, self.satellite, self.position,
                self.HOLD,
            )
            if not self.broken and self.lag_versions() == 0:
                self._caught_up_at = deterministic.now()
            return n

    # ── lag measurement ──────────────────────────────────────────────
    def lag_versions(self):
        """How far behind the primary's committed frontier the
        satellite is — the bounded data loss a failover right now would
        accept (0 once promoted: the remote region IS the frontier)."""
        if not self.replicating:
            return 0
        return max(
            0, self.cluster.sequencer.committed_version - self.position
        )

    def lag_ms(self):
        """Replication lag in injected-clock milliseconds: how long the
        satellite has been behind (0 while caught up)."""
        if self.lag_versions() == 0:
            return 0.0
        return round(
            max(0.0, deterministic.now() - self._caught_up_at) * 1000, 3
        )

    # ── WAN fault / lifecycle ────────────────────────────────────────
    def partition(self):
        """The WAN fails: streaming (and sync-mode gating) become
        no-ops and the lag grows; the primary keeps committing."""
        self.partitioned = True
        TraceEvent("RegionPartitioned", severity=30).detail(
            frontier=self.position).log()

    def heal(self):
        self.partitioned = False

    def drop(self):
        """Detach: release the log pin (otherwise the primary's log
        grows forever against a dead satellite) and stop the streamer."""
        self.dropped = True
        self.stop()
        try:
            self.cluster.tlog.release_pop(self.HOLD)
        except TLogDown:
            pass

    def close(self):
        self.stop()
        self.satellite.close()

    # ── failover bookkeeping (Cluster._region_failover drives it) ────
    def should_failover(self, cluster):
        """Primary-region loss: every primary process dead at once —
        sequencer, commit proxy, and the whole storage tier (the
        machine-sim's regional disaster). Partial failures stay on the
        ordinary recovery/recruitment path."""
        return (
            self.replicating
            and not self.broken
            and not cluster.sequencer.alive
            and not cluster._commit_target().alive
            and not any(s.alive for s in cluster.storages)
        )

    def promote_log(self):
        """Hand the satellite log to the promoted cluster: it becomes
        THE log (full history retained for storage replay; future
        commits append to it, so the satellite WAL is now the durable
        log). Streaming ends — the remote region is active."""
        self.active = self.config.remote
        self.stop()
        return self.satellite

    def note_failover(self, duration_ms):
        self.failovers += 1
        self.last_failover_ms = round(duration_ms, 3)
        TraceEvent("RegionFailover").detail(
            promoted=self.active, frontier=self.position,
            failover_ms=self.last_failover_ms).log()

    def note_failed_attempt(self, error):
        self.failed_attempts += 1
        TraceEvent("RegionFailoverFailed", severity=30).detail(
            attempt=self.failed_attempts, error=repr(error)).log()

    # ── background driver (thread-mode clusters only) ────────────────
    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="region-streamer", daemon=True
        )
        self._thread.start()

    def _loop(self):
        from foundationdb_tpu.utils.backoff import Backoff
        from foundationdb_tpu.utils.trace import SEV_ERROR

        interval = self.cluster.knobs.region_stream_interval_s
        # heal-retry: a drain that keeps failing (WAN flapping, satellite
        # log mid-restart) widens the retry spacing instead of hammering
        # at the stream cadence; one clean round snaps it back
        retry = Backoff(initial_s=interval, max_s=max(interval * 8, 1.0))
        wait_s = interval
        while not self._stop.wait(wait_s):
            try:
                self.maybe_stream()
                retry.reset()
                wait_s = interval
            except Exception as e:
                # the streamer must never take the cluster down — but a
                # broken drain is forensics-worthy, not silence
                TraceEvent("RegionStreamError", severity=SEV_ERROR) \
                    .detail(error=repr(e))
                wait_s = retry.delay()

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)

    # ── reporting (cluster.regions status + cluster.health) ──────────
    def status(self):
        """The ``cluster.regions`` document (pure read)."""
        cfg = self.config
        return {
            "configured": True,
            "primary": cfg.primary,
            "remote": cfg.remote,
            "active": self.active,
            "satellite_mode": cfg.satellite_mode,
            "satellites": cfg.satellites,
            "connected": not self.partitioned and not self.broken,
            "broken": self.broken,
            "replication_lag_versions": self.lag_versions(),
            "replication_lag_ms": self.lag_ms(),
            "sync_misses": self.sync_misses,
            "failovers": self.failovers,
            "failed_failover_attempts": self.failed_attempts,
            "last_failover_ms": self.last_failover_ms,
        }


class SecondaryRegion:
    """The original operator-pumped DR shape, kept for manual
    deployments: ``pump()`` by hand (or an operator loop), explicit
    ``partition()``/``heal()``, and ``failover()`` promoting into a
    brand-NEW cluster recovered from the satellite WAL. The cluster-
    owned ``RegionReplicator`` above supersedes this for anything
    attached through ``configure regions=...`` — continuous streaming,
    sync-mode commit gating, in-place automatic failover."""

    def __init__(self, primary_cluster, wal_path):
        self.primary = primary_cluster
        self.wal_path = wal_path
        os.makedirs(os.path.dirname(wal_path) or ".", exist_ok=True)
        self.tlog = TLog(wal_path=wal_path)
        self.position = 0  # replication frontier (last version applied)
        self.partitioned = False
        self.broken = False  # continuity gap detected (see drain_log)
        self._dropped = False
        # pin the primary log from the start: records must survive until
        # the satellite has them (ref: satellite tlogs holding the
        # primary's mutation stream)
        self.primary.tlog.hold_pop(HOLD_NAME, self.position)
        self.position = seed_snapshot(self.primary, self.tlog, HOLD_NAME)

    # ── replication (pumped) ──
    def pump(self):
        """Pull everything the primary has committed past our frontier.
        Returns the number of records replicated this round."""
        if self.partitioned or self._dropped or self.broken:
            return 0
        n, self.position, broken = drain_log(
            self.primary.tlog, self.tlog, self.position, HOLD_NAME
        )
        if broken:
            self.broken = True
        return n

    def lag_versions(self):
        """How far behind the primary's committed frontier we are — the
        bounded data loss a failover right now would accept."""
        return max(
            0, self.primary.sequencer.committed_version - self.position
        )

    # ── WAN fault / lifecycle ──
    def partition(self):
        self.partitioned = True
        TraceEvent("RegionPartitioned", severity=30).detail(
            frontier=self.position).log()

    def heal(self):
        self.partitioned = False

    def reattach(self, new_primary):
        """Point at a new primary incarnation (crash/recovery swapped
        the cluster object). Gap detection on the next pump decides
        whether continuity survived — a satellite that was fully caught
        up resumes cleanly; one that was behind marks itself broken."""
        self.primary = new_primary
        if not self._dropped:
            self.primary.tlog.hold_pop(HOLD_NAME, self.position)

    def drop(self):
        """Primary abandons DR: release the log pin (otherwise the
        primary's log grows forever against a dead satellite)."""
        self._dropped = True
        try:
            self.primary.tlog.release_pop(HOLD_NAME)
        except TLogDown:
            pass

    # ── failover ──
    def failover(self, **cluster_kwargs):
        """Promote this region to a full cluster (ref: forced region
        failover). Recovery replays the satellite WAL — the promoted
        database is exactly the primary's state at the replication
        frontier; the lag at disaster time is the accepted loss.
        Returns the promoted Cluster."""
        from foundationdb_tpu.server.cluster import Cluster

        if self.broken:
            raise RuntimeError(
                "replication gap: this satellite lost continuity "
                "(RegionReplicationGap) — re-seed DR before failing over"
            )
        self.tlog.close()  # flush the WAL handle before recovery reads it
        lost = self.lag_versions() if not self.partitioned else None
        promoted = Cluster(wal_path=self.wal_path, **cluster_kwargs)
        TraceEvent("RegionFailover").detail(
            frontier=self.position,
            lag_at_failover=lost if lost is not None else "partitioned",
        ).log()
        return promoted
