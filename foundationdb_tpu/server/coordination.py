"""Coordinators: the quorum-replicated cluster-state store.

Ref parity: fdbserver/Coordination.actor.cpp + LeaderElection — a small
set of coordinator processes store the cluster's bootstrap state (who
the current cluster controller / transaction system generation is)
behind a disk-Paxos-like protocol: a value is *the* cluster state iff a
majority of coordinators hold it at the highest ballot.

Ours implements single-decree Paxos per generation slot over
file-backed coordinator states (the reference's OnDemandStore), exposed
as the two operations recovery actually needs:

* ``read_quorum()`` — the highest-generation state any majority holds.
* ``write_quorum(state)`` — commit a new cluster state; fails without a
  live majority (coordinators can be marked down, e.g. by simulation
  fault injection).

Recovery (server/cluster.py) uses this the way the reference's master
recovery does: read the old transaction-system generation from the
coordinated state, lock it by writing generation+1, and only then
recruit the new transaction system.
"""

import json
import os
import threading

from foundationdb_tpu.utils import lockdep
from foundationdb_tpu.utils.backoff import Backoff


class CoordinatorDown(Exception):
    pass


class GenerationConflict(Exception):
    """A CAS write found a different generation already committed — a
    competing recovery won the slot (ref: the coordinated-state lock
    making concurrent master recoveries mutually exclusive)."""

    def __init__(self, prior):
        super().__init__(f"coordinated state moved: {prior!r}")
        self.prior = prior


class _BallotOutdated(Exception):
    """A majority is reachable but promised a higher ballot (another
    proposer, or our own pre-restart incarnation). Retryable."""


class Coordinator:
    """One coordinator replica: a ballot-versioned register on disk.

    Ref: Coordination.actor.cpp's LocalConfigStore / OnDemandStore.
    """

    def __init__(self, path=None):
        self._lock = lockdep.lock("Coordinator._lock")
        self.path = path
        self.alive = True
        self.promised = 0  # highest ballot promised (Paxos phase 1)
        self.accepted_ballot = 0  # ballot of the accepted value
        self.accepted = None  # the accepted cluster state (JSON-able)
        if path and os.path.exists(path):
            with open(path) as f:
                saved = json.load(f)
            self.promised = saved["promised"]
            self.accepted_ballot = saved["accepted_ballot"]
            self.accepted = saved["accepted"]

    def _persist(self):
        if not self.path:
            return
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "promised": self.promised,
                    "accepted_ballot": self.accepted_ballot,
                    "accepted": self.accepted,
                },
                f,
            )
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    # ── Paxos phase 1: prepare(ballot) → promise + prior accepted ──
    def prepare(self, ballot):
        with self._lock:
            if not self.alive:
                raise CoordinatorDown()
            if ballot <= self.promised:
                return (False, self.promised, None, 0)
            self.promised = ballot
            self._persist()
            return (True, ballot, self.accepted, self.accepted_ballot)

    # ── Paxos phase 2: accept(ballot, value) ──
    def accept(self, ballot, value):
        with self._lock:
            if not self.alive:
                raise CoordinatorDown()
            if ballot < self.promised:
                return False
            self.promised = ballot
            self.accepted_ballot = ballot
            self.accepted = value
            self._persist()
            return True

    def read(self):
        with self._lock:
            if not self.alive:
                raise CoordinatorDown()
            return (self.accepted_ballot, self.accepted)


class CoordinationQuorum:
    """Client view of the coordinator set (ref: ClientCoordinators).

    All proposals route through here; ballot numbers are made unique per
    proposer by striding (proposer_id + k * n_proposers), the standard
    Paxos ballot partitioning.
    """

    def __init__(self, coordinators, proposer_id=0, n_proposers=1):
        if not coordinators:
            raise ValueError("need at least one coordinator")
        self.coordinators = list(coordinators)
        self.proposer_id = proposer_id
        self.n_proposers = max(1, n_proposers)
        self._ballot = proposer_id

    @classmethod
    def local(cls, n=3, dir_path=None):
        """An in-process quorum of n coordinators (simulation deployment)."""
        if dir_path:
            os.makedirs(dir_path, exist_ok=True)
        coords = [
            Coordinator(
                os.path.join(dir_path, f"coordinator-{i}.json")
                if dir_path
                else None
            )
            for i in range(n)
        ]
        return cls(coords)

    @property
    def quorum_size(self):
        return len(self.coordinators) // 2 + 1

    def _next_ballot(self):
        self._ballot += self.n_proposers
        return self._ballot

    def read_quorum(self):
        """Highest accepted state visible to a majority, or None.

        A read must go through phase 1 to be linearizable (a bare read
        of accepted values could see a stale majority mid-write); this
        is the reference's openDatabase-from-coordinators path.
        """
        value, _ = self._prepare_retrying()
        return value

    def write_quorum(self, state, expect_generation=None):
        """Commit ``state`` as the new cluster state via full Paxos.

        With ``expect_generation``, the write is a compare-and-swap: each
        round's phase 1 re-reads the highest accepted state, and if its
        generation no longer matches, GenerationConflict is raised — so
        two concurrent recoveries that both read generation g cannot both
        commit g+1 (whichever loses the ballot race observes the winner's
        value when it retries). Without it, the slot is overwritten
        unconditionally.

        Raises CoordinatorDown if no majority is reachable. Returns the
        ballot at which the state was committed.
        """
        # ballot races with other proposers: retry with a tiny jittered
        # backoff — two proposers in lockstep re-race every round
        # forever; jittered sleeps break the symmetry (flow Backoff)
        cas_backoff = Backoff(initial_s=0.001, max_s=0.05)
        for attempt in range(10):
            if attempt:
                cas_backoff.sleep()
            prior, ballot = self._prepare_retrying()
            if expect_generation is not None:
                prior_gen = (prior or {}).get("generation", 0)
                if prior_gen != expect_generation:
                    raise GenerationConflict(prior)
            acks = 0
            for c in self.coordinators:
                try:
                    if c.accept(ballot, state):
                        acks += 1
                except CoordinatorDown:
                    pass
            if acks >= self.quorum_size:
                return ballot
        raise CoordinatorDown("could not commit cluster state (ballot races)")

    def _prepare_retrying(self, attempts=10):
        backoff = Backoff(initial_s=0.001, max_s=0.05)
        for attempt in range(attempts):
            if attempt:
                backoff.sleep()  # desynchronize competing proposers
            try:
                return self._prepare_round()
            except _BallotOutdated:
                continue  # _prepare_round already jumped our ballot
        raise CoordinatorDown("ballot races exhausted retries")

    def _prepare_round(self):
        ballot = self._next_ballot()
        promises = 0
        reachable = 0
        best = (0, None)
        max_promised = 0
        for c in self.coordinators:
            try:
                ok, promised, accepted, accepted_ballot = c.prepare(ballot)
            except CoordinatorDown:
                continue
            reachable += 1
            max_promised = max(max_promised, promised)
            if ok:
                promises += 1
                if accepted is not None and accepted_ballot > best[0]:
                    best = (accepted_ballot, accepted)
        if promises < self.quorum_size:
            if max_promised > self._ballot:
                # jump past the competing (or pre-restart) ballot
                k = (max_promised - self.proposer_id) // self.n_proposers + 1
                self._ballot = self.proposer_id + k * self.n_proposers
            if reachable >= self.quorum_size:
                raise _BallotOutdated()
            raise CoordinatorDown(
                f"only {reachable}/{len(self.coordinators)} coordinators "
                f"reachable (need {self.quorum_size})"
            )
        return best[1], ballot
