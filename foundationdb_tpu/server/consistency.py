"""Consistency checker: every replica of every shard must agree.

Ref parity: fdbserver/workloads/ConsistencyCheck.actor.cpp — walk the
shard map, read each shard's contents from every storage server in its
team at one consistent version, and compare exactly; also audit the
shard-map metadata itself (sorted unique boundaries, team sizes, teams
pointing at live-or-known storages). The reference runs this as a
simulation workload after every fault scenario and as an operator tool
(consistencycheck in fdbcli); ours is both (sim tests call it after
kill/recruit rounds, tools/cli.py exposes it).

The per-shard replica comparison is ``consistencyscan.
compare_shard_batch`` — the SAME code path the continuous background
scanner (server/consistencyscan.py) walks in bounded batches, so the
one-shot check and the always-on scan can never disagree about what
"consistent" means.

Returns a list of human-readable error strings — empty means consistent.
"""

from foundationdb_tpu.server.consistencyscan import (
    SYSTEM_END, compare_shard_batch,
)

__all__ = ["SYSTEM_END", "consistency_check"]


def consistency_check(cluster, max_keys_per_shard=None):
    errors = []
    version = cluster.sequencer.committed_version
    smap = cluster.dd.map

    # ── shard-map metadata audit ──
    bounds = smap.boundaries
    if bounds[0] != b"":
        errors.append(f"shard map does not start at b'': {bounds[0]!r}")
    for i in range(1, len(bounds)):
        if bounds[i - 1] >= bounds[i]:
            errors.append(
                f"shard boundaries not strictly increasing at {i}: "
                f"{bounds[i-1]!r} >= {bounds[i]!r}"
            )
    n_storages = len(cluster.storages)
    for i, team in enumerate(smap.teams):
        if not team:
            errors.append(f"shard {i} has an empty team")
        if len(set(team)) != len(team):
            errors.append(f"shard {i} team has duplicates: {team}")
        for sid in team:
            if not 0 <= sid < n_storages:
                errors.append(f"shard {i} references unknown storage {sid}")

    # ── replica data comparison, shard by shard (the shared core) ──
    for i in range(len(smap)):
        begin, end = smap.shard_range(i)
        end = SYSTEM_END if end is None else end
        res = compare_shard_batch(
            cluster, i, begin, end, smap.teams[i], version,
            limit=max_keys_per_shard,
        )
        errors.extend(res.errors)
    return errors
