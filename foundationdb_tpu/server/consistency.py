"""Consistency checker: every replica of every shard must agree.

Ref parity: fdbserver/workloads/ConsistencyCheck.actor.cpp — walk the
shard map, read each shard's contents from every storage server in its
team at one consistent version, and compare exactly; also audit the
shard-map metadata itself (sorted unique boundaries, team sizes, teams
pointing at live-or-known storages). The reference runs this as a
simulation workload after every fault scenario and as an operator tool
(consistencycheck in fdbcli); ours is both (sim tests call it after
kill/recruit rounds, tools/cli.py exposes it).

Returns a list of human-readable error strings — empty means consistent.
"""

from foundationdb_tpu.utils.trace import SEV_ERROR, TraceEvent

SYSTEM_END = b"\xff\xff"  # past user + system keys (engine meta excluded)


def consistency_check(cluster, max_keys_per_shard=None):
    errors = []
    version = cluster.sequencer.committed_version
    smap = cluster.dd.map

    # ── shard-map metadata audit ──
    bounds = smap.boundaries
    if bounds[0] != b"":
        errors.append(f"shard map does not start at b'': {bounds[0]!r}")
    for i in range(1, len(bounds)):
        if bounds[i - 1] >= bounds[i]:
            errors.append(
                f"shard boundaries not strictly increasing at {i}: "
                f"{bounds[i-1]!r} >= {bounds[i]!r}"
            )
    n_storages = len(cluster.storages)
    for i, team in enumerate(smap.teams):
        if not team:
            errors.append(f"shard {i} has an empty team")
        if len(set(team)) != len(team):
            errors.append(f"shard {i} team has duplicates: {team}")
        for sid in team:
            if not 0 <= sid < n_storages:
                errors.append(f"shard {i} references unknown storage {sid}")

    # ── replica data comparison, shard by shard ──
    for i in range(len(smap)):
        begin, end = smap.shard_range(i)
        end = SYSTEM_END if end is None else end
        team = smap.teams[i]
        live = [
            sid for sid in team
            if 0 <= sid < n_storages and cluster.storages[sid].alive
        ]
        if not live:
            errors.append(f"shard {i} [{begin!r}, {end!r}) has no live replica")
            continue
        datasets = []
        for sid in live:
            s = cluster.storages[sid]
            try:
                rows = s.read_range(
                    begin, end, version, limit=max_keys_per_shard,
                )
            except Exception as e:
                # the error lands in the report AND the trace stream: a
                # sim run greps traces for forensics, and an operator's
                # consistencycheck may summarize away the detail (FL005)
                TraceEvent("ConsistencyCheckReadError",
                           severity=SEV_ERROR).detail(
                    shard=i, storage=sid, version=version,
                    etype=type(e).__name__, error=str(e)[:200]).log()
                errors.append(
                    f"shard {i} replica {sid} unreadable at v{version}: {e}"
                )
                continue
            datasets.append((sid, rows))
        if len(datasets) < 2:
            continue
        ref_sid, ref_rows = datasets[0]
        for sid, rows in datasets[1:]:
            if rows == ref_rows:
                continue
            ref_map, got_map = dict(ref_rows), dict(rows)
            missing = sorted(set(ref_map) - set(got_map))[:3]
            extra = sorted(set(got_map) - set(ref_map))[:3]
            diff = sorted(
                k for k in set(ref_map) & set(got_map)
                if ref_map[k] != got_map[k]
            )[:3]
            errors.append(
                f"shard {i} [{begin!r}, {end!r}) replicas {ref_sid} vs "
                f"{sid} diverge at v{version}: missing={missing} "
                f"extra={extra} differing={diff}"
            )
    return errors
