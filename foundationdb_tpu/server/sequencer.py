"""Sequencer (Master): the cluster's version authority.

Ref parity: fdbserver/masterserver.actor.cpp getVersion — hands out
strictly increasing commit versions, advancing with wall time at
VERSIONS_PER_SECOND so versions double as a coarse clock (which is what
makes the 5s MVCC window a *time* window in the reference).
"""

import time

from foundationdb_tpu.core.versions import VERSIONS_PER_SECOND


class SequencerDown(Exception):
    """The version authority is dead; GRVs and commits fail retryably
    until the failure monitor recruits a new transaction system."""


class Sequencer:
    def __init__(self, version_clock="counter", start_version=0):
        assert version_clock in ("counter", "wall")
        self.version_clock = version_clock
        self.alive = True
        self._committed = start_version
        self._last_granted = start_version
        self._epoch = time.monotonic()
        self._start = start_version

    def kill(self):
        """Master death (ref: master failure forcing a full recovery —
        a new sequencer generation must fence this one's versions)."""
        self.alive = False

    def next_commit_version(self, min_advance=1000):
        """Grant the next batch's commit version (ref: the proxy's
        getVersion request; one version per commit batch)."""
        if not self.alive:
            raise SequencerDown()
        if self.version_clock == "wall":
            wall = self._start + int((time.monotonic() - self._epoch) * VERSIONS_PER_SECOND)
            v = max(self._last_granted + min_advance, wall)
        else:
            v = self._last_granted + min_advance
        self._last_granted = v
        return v

    def report_committed(self, version):
        """Proxy reports a batch fully committed (tlog-durable)."""
        if version > self._committed:
            self._committed = version

    @property
    def committed_version(self):
        return self._committed
