"""Sequencer (Master): the cluster's version authority.

Ref parity: fdbserver/masterserver.actor.cpp getVersion — hands out
strictly increasing commit versions, advancing with wall time at
VERSIONS_PER_SECOND so versions double as a coarse clock (which is what
makes the 5s MVCC window a *time* window in the reference).
"""

import time

from foundationdb_tpu.core.versions import VERSIONS_PER_SECOND


class Sequencer:
    def __init__(self, version_clock="counter", start_version=0):
        assert version_clock in ("counter", "wall")
        self.version_clock = version_clock
        self._committed = start_version
        self._last_granted = start_version
        self._epoch = time.monotonic()
        self._start = start_version

    def next_commit_version(self, min_advance=1000):
        """Grant the next batch's commit version (ref: the proxy's
        getVersion request; one version per commit batch)."""
        if self.version_clock == "wall":
            wall = self._start + int((time.monotonic() - self._epoch) * VERSIONS_PER_SECOND)
            v = max(self._last_granted + min_advance, wall)
        else:
            v = self._last_granted + min_advance
        self._last_granted = v
        return v

    def report_committed(self, version):
        """Proxy reports a batch fully committed (tlog-durable)."""
        if version > self._committed:
            self._committed = version

    @property
    def committed_version(self):
        return self._committed
