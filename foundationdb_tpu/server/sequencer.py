"""Sequencer (Master): the cluster's version authority.

Ref parity: fdbserver/masterserver.actor.cpp getVersion — hands out
strictly increasing commit versions, advancing with wall time at
VERSIONS_PER_SECOND so versions double as a coarse clock (which is what
makes the 5s MVCC window a *time* window in the reference). Grants are
CHAINED: every grant also names the version granted just before it
(the reference's GetCommitVersionReply.prevVersion), which is what lets
a FLEET of commit proxies interleave — each proxy knows exactly which
version its batch must wait on before resolving/logging, so batches
from different proxies form one global serial order with no gaps.
"""

import threading

import time

from foundationdb_tpu.core.versions import VERSIONS_PER_SECOND
from foundationdb_tpu.utils import lockdep


class SequencerDown(Exception):
    """The version authority is dead; GRVs and commits fail retryably
    until the failure monitor recruits a new transaction system."""


class Sequencer:
    def __init__(self, version_clock="counter", start_version=0):
        assert version_clock in ("counter", "wall")
        self.version_clock = version_clock
        self.alive = True
        self._committed = start_version
        self._last_granted = start_version
        self._epoch = time.monotonic()
        self._start = start_version
        # concurrent commit proxies request versions from their own
        # threads; grants must be atomic or two batches could share one
        self._mu = lockdep.lock("Sequencer._mu")

    def kill(self):
        """Master death (ref: master failure forcing a full recovery —
        a new sequencer generation must fence this one's versions)."""
        self.alive = False

    def next_commit_version(self, min_advance=1000):
        """Grant the next batch's commit version (ref: the proxy's
        getVersion request; one version per commit batch)."""
        return self.next_commit_versions(1, min_advance)[0][1]

    def next_commit_versions(self, k, min_advance=1000):
        """Grant ``k`` consecutive chained versions atomically: returns
        [(prev, v), ...] where each ``prev`` is the version granted
        immediately before ``v`` cluster-wide (ref: getVersion's
        prevVersion chaining across the proxy fleet). A backlog grabs
        its whole run in one call so no other proxy's batch lands
        between its members."""
        if not self.alive:
            raise SequencerDown()
        with self._mu:
            if not self.alive:  # kill raced the lock
                raise SequencerDown()
            out = []
            for _ in range(k):
                prev = self._last_granted
                if self.version_clock == "wall":
                    wall = self._start + int(
                        (time.monotonic() - self._epoch) * VERSIONS_PER_SECOND
                    )
                    v = max(prev + min_advance, wall)
                else:
                    v = prev + min_advance
                self._last_granted = v
                out.append((prev, v))
            return out

    def report_committed(self, version):
        """Proxy reports a batch fully committed (tlog-durable)."""
        if version > self._committed:
            self._committed = version

    @property
    def committed_version(self):
        return self._committed
