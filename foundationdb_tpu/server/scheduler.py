"""Abort-aware intra-batch commit scheduling.

Ref: "The Transactional Conflict Problem" (arxiv 1804.00947) — the order
txns occupy within a shared-version batch decides how many of them OCC
aborts. All members of a batch share one commit version and resolve in
batch order; an accepted txn's writes enter the conflict history at that
version, so a LATER member whose read set overlaps them is rejected
(read_version < commit_version). Those aborts are self-inflicted: the
same set of transactions in a different order often all commit. The
canonical win is reader-before-writer — T reads x, W blind-writes x;
arrival order [W, T] aborts T, scheduled order [T, W] commits both.

This pass runs HOST-SIDE in the commit proxy, before packing, over the
conflict sets the clients already encoded (the flat limb blobs of
core/flatpack.py — a point entry's key bytes slice straight out of the
blob, no numpy, no decode of the length-padded tail beyond one struct
read). It builds reader→writer precedence edges per key with a cheap
hash pass (points) plus an interval pass (the rare true ranges), then
orders the batch by Kahn's algorithm with arrival index as the
tie-break, so untouched batches keep arrival order exactly. Cycles —
RMW cliques on a hot key, where every order dooms all but one member —
are broken by force-placing the arrival-first member; the members
placed after a writer of their read set are counted as ``deferred``
(they will abort this window and retry — with the repair engine, at the
very next commit version).

Scheduling never changes which outcomes are LEGAL, only which of the
legal serial orders the batch commits in: any order of a shared-version
batch is a valid serialization, and the resolver re-validates every
member regardless, so a mis-scheduled batch costs throughput, never
correctness. The pass is fully deterministic (no entropy, no clock —
FL001 clean by construction); a seeded simulation schedules
byte-identically.

Gated behind ``knobs.commit_batch_scheduling`` (default off — arrival
order is the measured baseline); decisions ride the proxy's metrics
registry (``sched_reordered`` / ``sched_deferred``), the batcher's
stage summary, and the batch span.
"""

import struct

_LEN_WORD = struct.Struct(">I")

# bail-out bounds: past these the pass would cost more than the aborts
# it saves (a 1024-txn batch with a few keys each stays far inside)
MAX_EDGES = 65_536
MAX_RANGES = 512
# per-key clique bound: a key with readers*writers past this is a hot
# clique whose members mostly abort regardless of order — skip its
# edges instead of materializing the quadratic fan-out
MAX_KEY_FANOUT = 4_096


class SchedulePlan:
    """The scheduler's verdict for one batch: ``order[pos]`` is the
    original index committed at position ``pos``. ``restore`` maps the
    pipeline's position-ordered results back to request order, so
    callers (and their futures) never observe the permutation."""

    __slots__ = ("order", "reordered", "deferred")

    def __init__(self, order, reordered, deferred):
        self.order = order
        self.reordered = reordered
        self.deferred = deferred

    @property
    def identity(self):
        return self.reordered == 0

    def restore(self, results):
        out = [None] * len(results)
        for pos, i in enumerate(self.order):
            out[i] = results[pos]
        return out


def _entries_keys(blob, num_limbs):
    """Raw point keys sliced out of a flat entry blob (entry = padded
    key ‖ length word): one struct read per key, zero numpy."""
    w = 4 * num_limbs + 4
    out = []
    for off in range(0, len(blob), w):
        (n,) = _LEN_WORD.unpack_from(blob, off + w - 4)
        out.append(blob[off:off + n])
    return out


def _entries_ranges(blob, num_limbs):
    """[(begin, end)] sliced out of a flat range blob (lower ‖ upper
    entry pairs)."""
    ks = _entries_keys(blob, num_limbs)
    return list(zip(ks[0::2], ks[1::2]))


def _entries_raw(blob, w):
    """Fixed-width entry slices, NOT decoded to keys. An entry (padded
    key ‖ length word) is order-isomorphic to its key — ``entry(a) <
    entry(b) ⟺ a < b`` — so when every request in the batch carries
    same-width flat blobs, the entries themselves serve as canonical
    keys for the hash and interval passes with zero per-key decode.
    0/1-entry blobs — the bulk of point traffic — skip the loop."""
    nb = len(blob)
    if nb == 0:
        return ()
    if nb == w:
        return (blob,)
    return [blob[o:o + w] for o in range(0, nb, w)]


def _conflict_sets(req, entry_w):
    """((read_points, read_ranges), (write_points, write_ranges)) for
    one request. ``entry_w`` non-None = the whole batch is flat at that
    entry width: points and range bounds stay as raw entry slices (one
    shared key-space — see ``_entries_raw``). Otherwise decode flat
    blobs to real keys, or split the legacy byte-pair lists (the point
    test mirrors proxy._split_ranges without building successors)."""
    f = getattr(req, "flat_conflicts", None)
    if f is not None and entry_w is not None:
        if f.read_ranges:
            rr = _entries_raw(f.read_range_blob, entry_w)
            rr = list(zip(rr[0::2], rr[1::2]))
        else:
            rr = ()
        if f.write_ranges:
            wr = _entries_raw(f.write_range_blob, entry_w)
            wr = list(zip(wr[0::2], wr[1::2]))
        else:
            wr = ()
        return (
            (_entries_raw(f.read_point_blob, entry_w), rr),
            (_entries_raw(f.write_point_blob, entry_w), wr),
        )
    if f is not None:
        return (
            (_entries_keys(f.read_point_blob, f.num_limbs),
             _entries_ranges(f.read_range_blob, f.num_limbs)),
            (_entries_keys(f.write_point_blob, f.num_limbs),
             _entries_ranges(f.write_range_blob, f.num_limbs)),
        )
    sides = []
    for ranges in (req.read_conflict_ranges, req.write_conflict_ranges):
        pts, rgs = [], []
        for b, e in ranges:
            if len(e) == len(b) + 1 and e[-1] == 0 and e.startswith(b):
                pts.append(b)
            else:
                rgs.append((b, e))
        sides.append((pts, rgs))
    return sides[0], sides[1]


def _overlaps(point_set, ranges, keys, key_ranges):
    """Does {keys ∪ key_ranges} intersect {point_set ∪ ranges}?"""
    for k in keys:
        if k in point_set:
            return True
        for b, e in ranges:
            if b <= k < e:
                return True
    for rb, re_ in key_ranges:
        for k in point_set:
            if rb <= k < re_:
                return True
        for b, e in ranges:
            if rb < e and b < re_:
                return True
    return False


def schedule(requests):
    """Order a commit batch to minimize self-inflicted aborts.

    Returns a :class:`SchedulePlan`, or None when the batch is too
    small, carries no read/write overlap at all, or exceeds the pass's
    work bounds (the caller keeps arrival order — always sound).
    """
    n = len(requests)
    if n < 2:
        return None
    # one shared key-space for the whole batch: raw entry slices when
    # every request is flat at the same width (zero per-key decode),
    # raw key bytes otherwise
    entry_w = None
    limbs = {getattr(r.flat_conflicts, "num_limbs", None)
             if getattr(r, "flat_conflicts", None) is not None else None
             for r in requests}
    if len(limbs) == 1 and None not in limbs:
        entry_w = 4 * limbs.pop() + 4
    reads = []
    writes = []
    n_ranges = 0
    for r in requests:
        rd, wr = _conflict_sets(r, entry_w)
        n_ranges += len(rd[1]) + len(wr[1])
        if n_ranges > MAX_RANGES:
            return None
        reads.append(rd)
        writes.append(wr)
    # per-key reader/writer indices (the hash pass), built once; edges
    # then come key-centric so keys read or written by only one side
    # cost nothing past the index insert
    readers_by_key = {}
    writers_by_key = {}
    range_writers = []  # [(begin, end, writer id)] — the interval pass
    for j in range(n):
        for k in reads[j][0]:
            lst = readers_by_key.get(k)
            if lst is None:
                readers_by_key[k] = [j]
            elif lst[-1] != j:
                lst.append(j)
        for k in writes[j][0]:
            lst = writers_by_key.get(k)
            if lst is None:
                writers_by_key[k] = [j]
            elif lst[-1] != j:
                lst.append(j)
        for b, e in writes[j][1]:
            range_writers.append((b, e, j))
    if not writers_by_key and not range_writers:
        return None
    # reader→writer precedence edges: reader i must resolve before any
    # j that writes a key i reads (i committing after j's write at the
    # shared commit version would be a guaranteed abort). MUTUAL pairs
    # — i and j both read-and-write the same key, the RMW clique — get
    # NO edge: exactly one member commits in every order, so an edge
    # buys nothing and a clique of them would otherwise force a cycle
    # break that scrambles arrival order for free.
    succ = [None] * n  # i -> set of writers that must come after i
    indeg = [0] * n
    n_edges = 0

    def add_edge(i, j):
        nonlocal n_edges
        ws = succ[i]
        if ws is None:
            ws = succ[i] = set()
        if j not in ws:
            ws.add(j)
            indeg[j] += 1
            n_edges += 1

    for k, writers in writers_by_key.items():
        readers = readers_by_key.get(k)
        if not readers:
            continue
        if len(readers) * len(writers) > MAX_KEY_FANOUT:
            continue  # hot clique: order cannot save its members
        wset = set(writers)
        rset = set(readers)
        for i in readers:
            i_rmw = i in wset
            for j in writers:
                if j != i and not (i_rmw and j in rset):
                    add_edge(i, j)
        if n_edges > MAX_EDGES:
            return None
    if range_writers or n_ranges:
        for i in range(n):
            rp, rrg = reads[i]
            for b, e, j in range_writers:
                if j != i and any(b <= k < e for k in rp):
                    add_edge(i, j)
            for rb, re_ in rrg:
                for b, e, j in range_writers:
                    if j != i and rb < e and b < re_:
                        add_edge(i, j)
                for k, writers in writers_by_key.items():
                    if rb <= k < re_:
                        for j in writers:
                            if j != i:
                                add_edge(i, j)
        if n_edges > MAX_EDGES:
            return None
    if n_edges == 0:
        return None
    # Kahn with arrival-index priority: the unique minimal reordering —
    # conflict-free batches come out in arrival order exactly
    import heapq

    ready = [i for i in range(n) if indeg[i] == 0]
    heapq.heapify(ready)
    order = []
    placed = [False] * n
    placed_writes = set()
    placed_range_writes = []
    deferred = 0
    cursor = 0  # arrival scan position for cycle breaking
    while len(order) < n:
        if ready:
            i = heapq.heappop(ready)
            if placed[i]:
                continue
        else:
            # cycle (an RMW clique): force the arrival-first unplaced
            # member — it commits; the rest of the cycle is doomed in
            # every order and counts below as deferred
            while placed[cursor]:
                cursor += 1
            i = cursor
        placed[i] = True
        order.append(i)
        rp, rrg = reads[i]
        if _overlaps(placed_writes, placed_range_writes, rp, rrg):
            # placed after a writer of its read set: this member will
            # abort this window and retry at the next commit version —
            # the "defer to the next window" outcome
            deferred += 1
        else:
            wp, wrg = writes[i]
            placed_writes.update(wp)
            placed_range_writes.extend(wrg)
        if succ[i]:
            for j in succ[i]:
                indeg[j] -= 1
                if indeg[j] == 0 and not placed[j]:
                    heapq.heappush(ready, j)
    reordered = sum(1 for pos, i in enumerate(order) if pos != i)
    return SchedulePlan(tuple(order), reordered, deferred)
