"""Change feeds: versioned streams of mutations over a key range.

Ref parity: FoundationDB's change feeds (fdbclient/DatabaseContext.h
getChangeFeedStream / fdbserver/storageserver.actor.cpp changeFeed
machinery): a feed is registered over a key range; every committed
mutation intersecting the range is appended to the feed's version-
ordered stream; consumers read (begin_version, end_version] windows and
pop what they have durably consumed. The reference persists feeds on
storage servers; here the registry lives beside the commit pipeline
(every committed batch flows through exactly once, in version order) —
in-memory with bounded retention, the same place our tlog sits on the
durability spectrum.

Reading below a feed's popped/trimmed frontier raises
``transaction_too_old`` (1007): the data is gone for the same reason an
old read version is — it left the retained window.
"""

import threading

from collections import deque

from foundationdb_tpu.core.errors import err
from foundationdb_tpu.core.mutations import Op
from foundationdb_tpu.utils import lockdep


class _Feed:
    __slots__ = ("begin", "end", "entries", "pop_version", "dropped")

    def __init__(self, begin, end, retention):
        self.begin = begin
        self.end = end
        self.entries = deque(maxlen=retention)  # [(version, [Mutation])]
        self.pop_version = 0  # everything <= this is consumed/trimmed
        self.dropped = 0


class ChangeFeedRegistry:
    """All feeds of one cluster. note_commit is on the commit path —
    it takes the lock only when feeds exist."""

    def __init__(self, retention=10_000):
        self.retention = retention
        self._feeds = {}
        self._mu = lockdep.lock("ChangeFeedRegistry._mu")

    def __len__(self):
        return len(self._feeds)

    def register(self, feed_id, begin, end):
        if begin >= end:
            raise err("inverted_range")
        with self._mu:
            if feed_id in self._feeds:
                raise err("client_invalid_operation")
            self._feeds[feed_id] = _Feed(begin, end, self.retention)

    def deregister(self, feed_id):
        with self._mu:
            self._feeds.pop(feed_id, None)

    def list(self):
        with self._mu:
            return {
                fid: {"begin": f.begin, "end": f.end,
                      "pop_version": f.pop_version,
                      "entries": len(f.entries)}
                for fid, f in self._feeds.items()
            }

    def note_commit(self, version, mutations):
        """Append this commit's in-range mutations to every feed.
        Called once per committed batch, in version order."""
        if not self._feeds or not mutations:
            return
        with self._mu:
            for f in self._feeds.values():
                hits = []
                for m in mutations:
                    if m.op is Op.CLEAR_RANGE:
                        if m.key < f.end and f.begin < m.param:
                            hits.append(m)
                    elif f.begin <= m.key < f.end:
                        hits.append(m)
                if hits:
                    if len(f.entries) == f.entries.maxlen:
                        # retention cap: the oldest window trims away and
                        # readers below it get 1007, never silent gaps
                        oldest = f.entries[0][0]
                        f.pop_version = max(f.pop_version, oldest)
                        f.dropped += 1
                    f.entries.append((version, hits))

    def read(self, feed_id, begin_version, end_version=None, limit=0):
        """Entries with begin_version < version <= end_version, in
        order. Reading from below the popped/trimmed frontier raises
        1007 — the stream there no longer exists."""
        with self._mu:
            f = self._feeds.get(feed_id)
            if f is None:
                raise err("client_invalid_operation")
            if begin_version < f.pop_version:
                raise err("transaction_too_old")
            out = []
            for v, muts in f.entries:
                if v <= begin_version:
                    continue
                if end_version is not None and v > end_version:
                    break
                out.append((v, list(muts)))
                if limit and len(out) >= limit:
                    break
            return out

    def pop(self, feed_id, version):
        """Consumer checkpoint: entries <= version can be discarded."""
        with self._mu:
            f = self._feeds.get(feed_id)
            if f is None:
                raise err("client_invalid_operation")
            f.pop_version = max(f.pop_version, version)
            while f.entries and f.entries[0][0] <= f.pop_version:
                f.entries.popleft()
