"""Transaction log: ordered durable record of committed mutations.

Ref parity: fdbserver/TLogServer.actor.cpp — commit proxies push
version-ordered mutation batches; storage servers peek from their durable
version and pop when applied. Durability here is an optional append-only
file WAL with length-framed records (the reference fsyncs a DiskQueue).
"""

import os
import pickle
import struct
import zlib


class TLog:
    def __init__(self, wal_path=None, fsync=False):
        self._log = []  # list[(version, mutations)]
        self._first_version = 0
        self.wal_path = wal_path
        self.fsync = fsync
        self._wal = open(wal_path, "ab") if wal_path else None
        self._pop_holds = {}  # name -> version: keep records > version

    def push(self, version, mutations):
        if self._log and version <= self._log[-1][0]:
            raise ValueError("tlog push out of order")
        self._log.append((version, mutations))
        if self._wal is not None:
            payload = pickle.dumps((version, mutations), protocol=4)
            rec = struct.pack(">II", len(payload), zlib.crc32(payload)) + payload
            self._wal.write(rec)
            self._wal.flush()
            if self.fsync:
                os.fsync(self._wal.fileno())

    def peek(self, from_version):
        """All records with version > from_version, in order."""
        return [(v, m) for v, m in self._log if v > from_version]

    def hold_pop(self, name, version):
        """Register a peek cursor: records newer than ``version`` survive
        pop until the holder advances or releases (ref: backup workers'
        pop locks on the tlog)."""
        self._pop_holds[name] = version

    def release_pop(self, name):
        self._pop_holds.pop(name, None)

    def pop(self, up_to_version):
        """Discard records <= up_to_version (applied durably downstream),
        clamped so no registered peek cursor loses unread records."""
        if self._pop_holds:
            up_to_version = min(up_to_version, *self._pop_holds.values())
        self._log = [(v, m) for v, m in self._log if v > up_to_version]
        self._first_version = max(self._first_version, up_to_version)

    @property
    def last_version(self):
        return self._log[-1][0] if self._log else self._first_version

    def close(self):
        if self._wal is not None:
            self._wal.close()
            self._wal = None

    @staticmethod
    def recover(wal_path):
        """Replay a WAL file → list[(version, mutations)], tolerating a
        torn tail (ref: DiskQueue recovery)."""
        out = []
        try:
            with open(wal_path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return out
        off = 0
        while off + 8 <= len(data):
            ln, crc = struct.unpack_from(">II", data, off)
            if off + 8 + ln > len(data):
                break  # torn tail
            payload = data[off + 8 : off + 8 + ln]
            if zlib.crc32(payload) != crc:
                break
            out.append(pickle.loads(payload))
            off += 8 + ln
        return out
