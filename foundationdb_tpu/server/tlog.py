"""Transaction log: ordered durable record of committed mutations.

Ref parity: fdbserver/TLogServer.actor.cpp — commit proxies push
version-ordered mutation batches; storage servers peek from their durable
version and pop when applied. Durability here is an optional append-only
file WAL with length-framed records (the reference fsyncs a DiskQueue).

TAG PARTITIONING (ref: tag streams in TLogServer.actor.cpp +
TagPartitionedLogSystem.actor.cpp): the commit proxy routes each
mutation to its owning storages (tags) before the push and hands the
log the per-tag split; ``peek(from_version, tag=...)`` then serves ONE
storage's stream — a worker that owns 1/k of the keyspace pulls ~1/k of
the bytes instead of the whole firehose. Tags live in memory alongside
the records (the WAL keeps the untagged batch: recovery re-routes by
the restored shard map, and a tag-less recovered record legally serves
the full batch to every cursor — conservative, never lossy).

``TLogSystem`` is the replicated tier (ref: TagPartitionedLogSystem):
k TLog replicas, a push is acked once a quorum made it durable, peeks
merge across live replicas, and recovery unions the surviving WALs —
losing a minority of logs loses no acked commit.
"""

import bisect
import os
import pickle
import struct
import threading

import zlib

from foundationdb_tpu.utils import lockdep
from foundationdb_tpu.utils import metrics as metrics_mod
from foundationdb_tpu.utils import span as span_mod


class TLogDown(Exception):
    """This log replica is dead (simulation kill or process loss)."""


class TLog:
    def __init__(self, wal_path=None, fsync=False):
        self._log = []  # list[(version, mutations)]
        self._tags = {}  # version -> {tag: [mutations]} (memory only)
        self._first_version = 0
        self.index = 0  # replica id (TLogSystem numbers its members)
        # placement tag (ref: the region/locality of a TLog recruit in
        # DatabaseConfiguration region blocks): the cluster stamps its
        # primary-region id here, the RegionReplicator stamps its
        # satellite replicas with the remote region id. None = regions
        # not configured.
        self.region = None
        self.wal_path = wal_path
        self.fsync = fsync
        self.alive = True
        self._wal = open(wal_path, "ab") if wal_path else None
        self._pop_holds = {}  # name -> version: keep records > version
        # holds mutate on RPC handler threads (remote storage workers)
        # while the commit pipeline's pop iterates them — lock the dict
        self._holds_mu = lockdep.lock("TLog._holds_mu")
        # long-polling peekers (rpc/storageworker.py LogFeed) park here
        # instead of sleep-polling last_version
        self._data_cond = lockdep.condition("TLog._data_cond")
        # push-latency bands + volume counters for the status document
        # (ref: TLogMetrics in TLogServer.actor.cpp). Durations come off
        # the injected clock, so sim snapshots replay deterministically.
        self.metrics = metrics_mod.MetricsRegistry("tlog")
        self._m_push = self.metrics.latency("tlog_push")
        self._m_pushes = self.metrics.counter("pushes")
        self._m_mutations = self.metrics.counter("mutations")

    def _wal_append(self, record):
        """Length+CRC-framed durable append (one framing for push and
        rollback markers — recovery depends on them agreeing)."""
        if self._wal is None:
            return
        payload = pickle.dumps(record, protocol=4)
        self._wal.write(
            struct.pack(">II", len(payload), zlib.crc32(payload)) + payload
        )
        self._wal.flush()
        if self.fsync:
            os.fsync(self._wal.fileno())

    def push(self, version, mutations, tags=None):
        """``tags``: optional {tag: [mutations]} split of this batch by
        destination storage (the proxy's routing); enables per-tag
        peeks. The WAL stores the untagged batch only."""
        if not self.alive:
            raise TLogDown()
        if self._log and version <= self._log[-1][0]:
            raise ValueError("tlog push out of order")
        # a traced batch (the proxy's ambient batch-span context) gets
        # a per-REPLICA push span — the hop the critical-path tool
        # attributes WAL/fsync time to
        psp = span_mod.from_context("tlog.push", span_mod.current(),
                                    replica=self.index, version=version)
        t0 = metrics_mod.now()
        self._log.append((version, mutations))
        if tags is not None:
            self._tags[version] = tags
        self._wal_append((version, mutations))
        self._m_push.record(max(0.0, metrics_mod.now() - t0))
        self._m_pushes.inc()
        self._m_mutations.inc(len(mutations))
        psp.finish(mutations=len(mutations))
        with self._data_cond:
            self._data_cond.notify_all()

    def wait_for_version(self, version, timeout):
        """Park until a record at/after ``version`` exists (or timeout).
        The long-poll half of peek: a tailing storage worker blocks here
        at zero CPU instead of the lead burning a thread at 1 kHz
        wakeups per idle worker. Death/close wakes waiters immediately
        (kill()/close() notify) so shutdown never stalls on the timeout."""
        with self._data_cond:
            return self._data_cond.wait_for(
                lambda: self.last_version >= version or not self.alive,
                timeout=timeout,
            )

    def kill(self):
        """Process death (simulation / failure injection): wake parked
        long-pollers so they observe the dead log now, not at timeout."""
        self.alive = False
        with self._data_cond:
            self._data_cond.notify_all()

    def rollback(self, version):
        """Undo a just-pushed tail record that failed to reach its
        replication quorum: drop it from the live log and append an
        abort marker so WAL recovery drops it too. Without this, a
        record on a minority of replicas materializes at recovery AFTER
        later commits were applied without it — a consistency anomaly,
        not just the legal 1021 ambiguity."""
        if not self.alive:
            raise TLogDown()
        if self._log and self._log[-1][0] == version:
            self._log.pop()
            self._tags.pop(version, None)
            self._wal_append(("abort", version))

    def peek(self, from_version, tag=None):
        """All records with version > from_version, in order. The log
        is version-sorted, so this bisects to the start instead of
        filtering the whole retained window (storage workers poll).

        With ``tag``: each record carries only that tag's mutations (the
        per-storage stream — ref: TLog tag cursors). Every version still
        appears (possibly empty) so cursors advance; records pushed
        without tags (recovered WALs) serve the full batch —
        conservative, never lossy."""
        if not self.alive:
            raise TLogDown()
        # snapshot once: pop() swaps the list on the commit thread, and a
        # bisect index computed against the OLD list applied to the NEW
        # one would silently skip still-retained records
        log = self._log
        i = bisect.bisect_right(log, from_version, key=lambda r: r[0])
        recs = log[i:]
        if tag is None:
            return recs
        tags = self._tags
        return [
            (v, tags[v].get(tag, []) if v in tags else m)
            for v, m in recs
        ]

    def hold_pop(self, name, version):
        """Register a peek cursor: records newer than ``version`` survive
        pop until the holder advances or releases (ref: backup workers'
        pop locks on the tlog)."""
        with self._holds_mu:
            self._pop_holds[name] = version

    def release_pop(self, name):
        with self._holds_mu:
            self._pop_holds.pop(name, None)

    def pop(self, up_to_version):
        """Discard records <= up_to_version (applied durably downstream),
        clamped so no registered peek cursor loses unread records."""
        with self._holds_mu:
            holds = list(self._pop_holds.values())
        if holds:
            up_to_version = min(up_to_version, *holds)
        self._log = [(v, m) for v, m in self._log if v > up_to_version]
        if self._tags:
            self._tags = {
                v: t for v, t in self._tags.items() if v > up_to_version
            }
        self._first_version = max(self._first_version, up_to_version)

    @property
    def last_version(self):
        return self._log[-1][0] if self._log else self._first_version

    def status(self):
        """This replica's status RPC payload (leaf of the status doc)."""
        self.metrics.gauge("retained_records").set(len(self._log))
        self.metrics.gauge("last_version").set(self.last_version)
        return {
            "alive": self.alive,
            "region": self.region,
            "metrics": self.metrics.snapshot(),
        }

    def close(self):
        self.alive = False
        with self._data_cond:
            self._data_cond.notify_all()
        if self._wal is not None:
            self._wal.close()
            self._wal = None

    @staticmethod
    def recover(wal_path):
        """Replay a WAL file → list[(version, mutations)], tolerating a
        torn tail (ref: DiskQueue recovery)."""
        out = []
        try:
            with open(wal_path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return out
        off = 0
        while off + 8 <= len(data):
            ln, crc = struct.unpack_from(">II", data, off)
            if off + 8 + ln > len(data):
                break  # torn tail
            payload = data[off + 8 : off + 8 + ln]
            if zlib.crc32(payload) != crc:
                break
            rec = pickle.loads(payload)
            if rec[0] == "abort":
                # rollback marker undoes the PRECEDING record with that
                # version only (positional: a later re-grant of the same
                # version number is a distinct, valid record)
                for i in range(len(out) - 1, -1, -1):
                    if out[i][0] == rec[1]:
                        del out[i]
                        break
            else:
                out.append(rec)
            off += 8 + ln
        return out


class TLogSystem:
    """k replicated TLogs with quorum-acked pushes.

    Ref parity: TagPartitionedLogSystem — the proxy's push is durable
    once enough replicas logged it; the chosen quorum (majority by
    default) means any surviving majority holds every acked commit, so
    recovery (union of surviving WALs) loses nothing when a minority of
    logs dies. Exposes the single-TLog interface, so the proxy, backup
    agent, and storage recovery are replication-agnostic.
    """

    def __init__(self, n=3, wal_path=None, fsync=False, quorum=None):
        self.n = n
        self.quorum = quorum if quorum is not None else n // 2 + 1
        self.wal_path = wal_path  # base path; replica i appends .i
        self.logs = [
            TLog(wal_path=f"{wal_path}.{i}" if wal_path else None, fsync=fsync)
            for i in range(n)
        ]
        for i, log in enumerate(self.logs):
            log.index = i  # replica id on each push span
        self._pop_holds = {}
        self._data_cond = lockdep.condition("TLogSystem._data_cond")

    @staticmethod
    def replica_paths(wal_path, n):
        return [f"{wal_path}.{i}" for i in range(n)]

    # ── replica lifecycle (simulation / failure detection hooks) ──
    def kill(self, i):
        self.logs[i].kill()
        with self._data_cond:
            self._data_cond.notify_all()

    def revive(self, i):
        """A rebooted replica rejoins caught-up from a live peer (ref: a
        new tlog generation starting from the recovery version). Without
        a live donor it STAYS dead and returns None — rejoining with a
        gap would make merged peeks silently lose acked records that
        other (now-dead) replicas hold."""
        log = self.logs[i]
        donor = next(
            (l for l in self.logs if l.alive and l is not log), None
        )
        if donor is None:
            return None
        log.alive = True
        log._log = []
        log._tags = {}
        log._first_version = donor._first_version
        for v, m in donor.peek(0):
            log.push(v, m, tags=donor._tags.get(v))
        return log

    @property
    def live_count(self):
        return sum(1 for l in self.logs if l.alive)

    # ── single-TLog facade ──
    @property
    def _first_version(self):
        if self.live_count == 0:
            raise TLogDown("no live tlog replicas")
        return min(l._first_version for l in self.logs if l.alive)

    @_first_version.setter
    def _first_version(self, v):
        for l in self.logs:
            l._first_version = v

    def push(self, version, mutations, tags=None):
        """Replicate to every live log; durable at ``quorum`` acks.
        Raises TLogDown when a quorum is unreachable — the partial
        replicas roll the record back (abort-marked in their WALs) so it
        cannot resurface at recovery after later commits landed without
        it; the proxy turns the failure into commit_unknown_result."""
        accepted = []
        for log in self.logs:
            try:
                log.push(version, mutations, tags=tags)
                accepted.append(log)
            except TLogDown:
                continue
        if len(accepted) < self.quorum:
            for log in accepted:  # best-effort undo of the partial push
                try:
                    log.rollback(version)
                except TLogDown:
                    pass
            raise TLogDown(
                f"{len(accepted)}/{self.n} tlogs acked (need {self.quorum})"
            )
        with self._data_cond:
            self._data_cond.notify_all()

    def wait_for_version(self, version, timeout):
        """Park until a quorum-acked record at/after ``version`` exists
        (long-poll support; see TLog.wait_for_version)."""
        with self._data_cond:
            return self._data_cond.wait_for(
                lambda: self.live_count == 0
                or self.last_version >= version,
                timeout=timeout,
            )

    def peek(self, from_version, tag=None):
        """Merged view across live replicas: the union of their records
        (any acked record is on ≥ quorum of them; a dead replica's gaps
        are covered by the others)."""
        merged = {}
        for log in self.logs:
            if not log.alive:
                continue
            for v, m in log.peek(from_version, tag=tag):
                merged.setdefault(v, m)
        return sorted(merged.items())

    def hold_pop(self, name, version):
        self._pop_holds[name] = version
        for log in self.logs:
            log.hold_pop(name, version)

    def release_pop(self, name):
        self._pop_holds.pop(name, None)
        for log in self.logs:
            log.release_pop(name)

    def pop(self, up_to_version):
        for log in self.logs:
            if log.alive:
                log.pop(up_to_version)

    @property
    def last_version(self):
        if self.live_count == 0:
            raise TLogDown("no live tlog replicas")
        return max(l.last_version for l in self.logs if l.alive)

    def status(self):
        """Per-replica status payloads (the status doc's logs section)."""
        return [log.status() for log in self.logs]

    def close(self):
        for log in self.logs:
            log.close()
        with self._data_cond:
            self._data_cond.notify_all()

    @classmethod
    def recover(cls, wal_path, n):
        """Union the surviving replica WALs → list[(version, mutations)].
        Any record acked at quorum survives the loss of a minority; a
        record present on only a minority was never acked (its client saw
        commit_unknown_result) — including it is the legal 1021 outcome."""
        merged = {}
        for path in cls.replica_paths(wal_path, n):
            for v, m in TLog.recover(path):
                merged.setdefault(v, m)
        return sorted(merged.items())
