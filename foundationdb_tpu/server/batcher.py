"""Cross-client commit batching — the commit proxy's real job.

Ref parity: fdbserver/CommitProxyServer.actor.cpp commitBatcher (~L300):
client commits accumulate into a batch bounded by an interval and a size
cap; the whole batch shares one commit version and one resolver dispatch.
The TPU resolver inverts the reference's cost model — big batches are
*cheaper* per txn — so keeping batches full is the whole performance
story: a 1-txn batch pads the kernel's T-lane to 0.1% occupancy.

Two drive modes:

- **thread** (live deployments, the e2e bench): a daemon batcher thread
  collects submissions for up to ``interval_s`` (or until ``max_batch``),
  then drives the inner proxy. Clients block on a CommitFuture. With
  ``knobs.commit_pipeline_depth > 1`` the drain loop is a bounded
  TWO-STAGE pipeline: the batcher thread runs stage A+B of each backlog
  group (version grant + host packing + gate-ordered lazy resolve
  dispatch, proxy.commit_batches_begin) and a second apply worker runs
  stage C (status sync + tlog push + storage apply,
  proxy.commit_batches_finish) strictly in grant order — so group N+1
  packs on the host and resolves on the device while group N applies.
  Depth 1 reproduces the old serial loop exactly. Client threads read
  storage under each StorageServer's mutation lock (storage.py
  ``_mu``), which the apply/flush path also takes — point and range
  reads are consistent even while the pipeline mutates the overlay.

- **manual** (deterministic simulation): no thread, no wall clock.
  Actors submit and yield on the future; the sim scheduler calls
  ``pump(step)`` which flushes when the batch is full or ``flush_after``
  scheduling steps have passed since the first pending submission.
  A synchronous ``commit()`` flushes immediately — riding every pending
  async submission along in the same batch.
"""

import threading
import time
from collections import deque

from foundationdb_tpu.core.errors import FDBError
from foundationdb_tpu.utils import lockdep
from foundationdb_tpu.utils import metrics as metrics_mod
from foundationdb_tpu.utils import span as span_mod
from foundationdb_tpu.utils.trace import SEV_ERROR, StageStats, TraceEvent


_UNSET = object()


class CommitFuture:
    """Resolves to a commit version (int) or an FDBError.

    Futures from one BatchingCommitProxy share its completion condition
    instead of carrying a private threading.Event each: a whole batch
    resolves together, so one notify_all per batch wakes every waiter —
    the per-commit Event (allocation + lock dance on both set and wait)
    was measurable e2e overhead at tens of thousands of commits/sec.
    A standalone future (no proxy) must be ``set`` before ``result`` is
    awaited — the pattern of every standalone construction site
    (read-only fast paths, fault wrappers resolve immediately)."""

    __slots__ = ("_result", "_proxy", "born")

    def __init__(self, proxy=None):
        self._result = _UNSET
        self._proxy = proxy
        self.born = None  # injected-clock stamp set at submit (spans)

    def done(self):
        return self._result is not _UNSET

    def set(self, result):
        # first settlement wins: once a waiter may have observed a
        # verdict (e.g. the stranded-batch watchdog's 1021, already
        # acted on by a retry), a late real result must not replace it
        # — an acked-then-changed verdict is how double-applies happen
        if self._result is _UNSET:
            self._result = result

    def result(self, timeout=None):
        """Block until resolved (thread mode); returns version or FDBError.

        Waits in bounded chunks, invoking the proxy's stranded-batch
        watchdog between them: a batch wedged inside the inner proxy
        past the commit deadline settles as commit_unknown_result on
        the WAITING thread — a hung pipeline costs a deadline, never a
        hung client (FL002 settle-and-retry)."""
        if self._result is not _UNSET:
            return self._result
        if self._proxy is None:
            raise TimeoutError("standalone commit future never resolved")
        cond = self._proxy._done_cond
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            chunk = 0.25
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0 and not self.done():
                    raise TimeoutError("commit future not resolved")
                chunk = min(chunk, max(0.0, remaining))
            with cond:
                cond.wait_for(self.done, chunk)
            if self.done():
                return self._result
            self._proxy._check_stranded()


class BatchingCommitProxy:
    """Accumulates CommitRequests into shared-version batches."""

    WATCHDOG_GRACE_S = 1.0

    def __init__(self, inner, max_batch=None, interval_s=None,
                 flush_after=4, mode="thread"):
        self.inner = inner
        knobs = inner.knobs
        self.max_batch = max_batch or min(
            knobs.batch_txn_capacity, 1024
        )
        self.interval_s = (
            interval_s if interval_s is not None
            else knobs.commit_batch_interval_s
        )
        self.flush_after = flush_after  # manual mode: sim steps before flush
        self.mode = mode
        self._lock = lockdep.lock("BatchingCommitProxy._lock")
        self._pending = []  # [(request, future)]
        self._first_pending_step = None
        self._wake = lockdep.condition("BatchingCommitProxy._lock", self._lock)
        self._done_cond = lockdep.condition("BatchingCommitProxy._done_cond")  # batch-completion waiters
        self._closed = False
        # stranded-batch watchdog bound: a batch inside the inner proxy
        # longer than this settles 1021 from the waiting client thread.
        # Two commit deadlines of slack — the inner proxy may itself be
        # a deadline-bounded RPC that retries once — plus grace.
        self.watchdog_s = (
            2 * getattr(knobs, "rpc_deadline_commit_s", 15.0)
            + self.WATCHDOG_GRACE_S
        )
        self._running = None  # batch currently driving the inner proxy
        self._running_since = 0.0
        self.stranded_settled = 0
        self.batches_committed = 0
        self.txns_batched = 0
        self.max_batch_seen = 0
        # flowlint: shared(last-writer-wins debug breadcrumb; readers only poll it)
        self.last_batch_error = None
        # flowlint: shared(AIMD heuristic target; GIL-atomic int, staleness is benign)
        self._backlog_target = self.MAX_BACKLOG
        self._thread = None
        # ── bounded commit pipeline (thread mode only) ──
        # Up to ``commit_pipeline_depth`` backlog groups in flight:
        # this thread runs stage A+B (version grant + host packing +
        # lazy resolve dispatch) for group N+1 while the apply worker
        # runs stage C (status sync + tlog push + storage apply) for
        # group N. Depth 1 — and manual/sim mode always — is the
        # strictly serial drain loop, byte-for-byte today's behavior.
        depth = getattr(knobs, "commit_pipeline_depth", 1)
        self.pipeline_depth = max(1, int(depth)) if mode == "thread" else 1
        # share the inner proxy's registry (one "commit_proxy" document
        # carrying both the proxy's error-class counters and the
        # batcher's spans); remote/inner-less wrappers get their own
        self.metrics = getattr(inner, "metrics", None) \
            or metrics_mod.MetricsRegistry("commit_proxy")
        if hasattr(inner, "spans_owned_externally"):
            # claim the commit_e2e span: this wrapper sees the full
            # submit→settle window (queue wait included), so the inner
            # proxy must not double-record the narrower batch span
            inner.spans_owned_externally = True
        # the per-batch end-to-end commit span (submit → settle): the
        # latency-band number the <2ms-added-p99 target is gated on
        self._m_e2e = self.metrics.latency("commit_e2e")
        self._m_settled_batches = self.metrics.counter("batches_settled")
        self.stages = StageStats(registry=self.metrics)
        self._inflight = deque()  # [(chunks, _PipelinedGroup)] FIFO
        self._inflight_cv = lockdep.condition("BatchingCommitProxy._inflight_cv")
        self._occ_level = 0
        self._occ_t = time.perf_counter()
        self._occ_busy = 0.0  # seconds with >=1 group in flight
        self._occ_area = 0.0  # integral of in-flight count over busy time
        self._apply_thread = None
        if mode == "thread" and self.pipeline_depth > 1 \
                and hasattr(inner, "commit_batches_begin"):
            self._apply_thread = threading.Thread(
                target=self._apply_loop, name="commit-apply", daemon=True
            )
            self._apply_thread.start()
        if mode == "thread":
            self._thread = threading.Thread(
                target=self._batcher_loop, name="commit-batcher", daemon=True
            )
            self._thread.start()

    # ────────────────────────── client surface ──────────────────────────
    def submit(self, request):
        """Enqueue a commit; returns a CommitFuture."""
        fut = CommitFuture(self)
        with self._lock:
            if self._closed:
                raise RuntimeError("batching proxy is closed")
            if not self._pending:
                # stamp the FIRST submit of each batch window only: it
                # is the oldest — the span _record_span publishes — and
                # one clock call per window keeps per-txn metric cost
                # out of the commit hot path (metrics_smoke's 2% budget)
                fut.born = metrics_mod.now()
            self._pending.append((request, fut))
            self._wake.notify()
        return fut

    def commit(self, request):
        """Synchronous commit (the Transaction.commit path).

        Thread mode: submit and block — the batcher thread forms the
        batch, so concurrent committers share a version. Manual mode:
        submit and flush now, batching up every pending async commit.
        """
        fut = self.submit(request)
        if self.mode == "thread":
            return fut.result()
        self.flush()
        return fut.result(timeout=0)

    # ─────────────────────────── batch driving ──────────────────────────
    def flush(self):
        """Drain everything pending into one inner commit_batch, then
        wait for any in-flight pipelined groups to settle — a returned
        flush means every submitted commit has resolved."""
        with self._lock:
            pending, self._pending = self._pending, []
            self._first_pending_step = None
        if pending:
            self._run_batch(pending)
        self.drain_pipeline()

    def pump(self, step):
        """Manual-mode heartbeat from the sim scheduler: flush when full
        or when ``flush_after`` steps have passed since the first pending
        submission (the deterministic analog of the batch interval)."""
        with self._lock:
            n = len(self._pending)
            if n and self._first_pending_step is None:
                self._first_pending_step = step
            due = n >= self.max_batch or (
                n and step - self._first_pending_step >= self.flush_after
            )
        if due:
            self.flush()

    # cap on batches per commit_batches call. The resolver chunks the
    # backlog into BACKLOG_B-wide scans internally, so this only bounds
    # how much queue drains per settle round (keeping client latency and
    # host-side packing memory bounded), not the dispatch width.
    MAX_BACKLOG = 64

    # Conflict-adaptive backlog depth: every txn in one settle round
    # resolves against read versions from before the round, so OCC
    # conflict probability grows with depth × contention. On contended
    # workloads (TPC-C hot rows) a 64-deep backlog turns throughput into
    # retries; on YCSB-shaped traffic depth is pure win. AIMD on the
    # observed conflict rate — the same signal the reference's
    # ratekeeper damps overload with (ref: Ratekeeper.actor.cpp).
    BACKLOG_SHRINK_AT = 0.35  # conflict rate that halves the depth
    BACKLOG_GROW_AT = 0.15  # conflict rate that lets depth double

    def _adapt_backlog(self, txns, conflicts):
        if txns == 0:
            return
        rate = conflicts / txns
        if rate > self.BACKLOG_SHRINK_AT:
            self._backlog_target = max(1, self._backlog_target // 2)
        elif rate < self.BACKLOG_GROW_AT:
            self._backlog_target = min(
                self.MAX_BACKLOG, self._backlog_target * 2
            )

    def _check_stranded(self):
        """Stranded-batch watchdog (invoked by waiting clients between
        wait chunks): a batch that has been driving the inner proxy
        past ``watchdog_s`` settles every future in it with 1021 — the
        commits MAY have happened; the retry loop's idempotency ids own
        the disambiguation. The wedged drive keeps running; its eventual
        ``set`` calls lose to the watchdog's (first settlement wins)."""
        with self._lock:
            run = self._running
            if run is None \
                    or time.monotonic() - self._running_since \
                    < self.watchdog_s:
                return
            self._running = None  # claimed: exactly one waiter settles
            self.stranded_settled += len(run)
        TraceEvent("CommitBatchStranded", severity=30).detail(
            txns=len(run), bound_s=self.watchdog_s).log()
        unknown = FDBError.from_name("commit_unknown_result")
        for _, fut in run:
            fut.set(unknown)
        with self._done_cond:
            self._done_cond.notify_all()

    def _run_batch(self, pending):
        with self._lock:
            self._running = pending
            self._running_since = time.monotonic()
        try:
            self._run_batch_inner(pending)
        finally:
            with self._lock:
                if self._running is pending:
                    self._running = None

    def _run_batch_inner(self, pending):
        chunks = [
            pending[i : i + self.max_batch]
            for i in range(0, len(pending), self.max_batch)
        ]
        while chunks:
            depth = self._backlog_target
            group, chunks = chunks[:depth], chunks[depth:]
            if len(group) > 1 and hasattr(self.inner, "commit_batches"):
                # a backlog: one resolver dispatch covers every chunk
                # (ref: the proxy pipelining resolution across batches)
                reqs = [[r for r, _ in c] for c in group]
                if self._apply_thread is not None:
                    try:
                        eligible = self.inner.pipeline_eligible(reqs)
                    except Exception as e:
                        TraceEvent("CommitBatchError",
                                   severity=SEV_ERROR).detail(
                            phase="eligibility",
                            etype=type(e).__name__,
                            error=str(e)[:200]).log()
                        self._fail_chunks(group, e)
                        continue
                    if eligible:
                        # the pipelined route: stages A+B now, stage C
                        # on the apply worker while the NEXT group
                        # packs here
                        try:
                            self._pipeline_submit(group, reqs)
                        except Exception as e:
                            # begin died outside its own guards (e.g. a
                            # dedupe/storage TOCTOU): same contract as a
                            # failed commit_batches — futures resolve
                            TraceEvent("CommitBatchError",
                                       severity=SEV_ERROR).detail(
                                phase="pipeline_begin",
                                etype=type(e).__name__,
                                error=str(e)[:200]).log()
                            self._fail_chunks(group, e)
                        continue
                # serial fallback (lock/dedupe-hit/overload/fleet of
                # resolvers): in-flight pipelined groups must settle
                # first or this group's versions would overtake theirs
                # at the log
                self.drain_pipeline()
                try:
                    results_list = self.inner.commit_batches(reqs)
                except Exception as e:
                    TraceEvent("CommitBatchError",
                               severity=SEV_ERROR).detail(
                        phase="backlog",
                        etype=type(e).__name__,
                        error=str(e)[:200]).log()
                    self._fail_chunks(group, e)
                    continue
                txns = conflicts = 0
                for chunk, results in zip(group, results_list):
                    self._settle(chunk, results)
                    txns += len(results)
                    conflicts += sum(
                        1 for r in results
                        if isinstance(r, FDBError) and r.code == 1020
                    )
                self._adapt_backlog(txns, conflicts)
                continue
            self.drain_pipeline()
            for chunk in group:
                try:
                    results = self.inner.commit_batch([r for r, _ in chunk])
                except Exception as e:  # resolve/apply blew up: fail it
                    # Never propagate: every future must resolve (an
                    # escaped exception would kill the batcher thread and
                    # leave later chunks' clients blocked forever) and
                    # the remaining chunks still deserve their shot. The
                    # pipeline may or may not have made the chunk durable
                    # — exactly what commit_unknown_result (1021) means.
                    TraceEvent("CommitBatchError",
                               severity=SEV_ERROR).detail(
                        phase="batch",
                        etype=type(e).__name__,
                        error=str(e)[:200]).log()
                    self._fail_chunks([chunk], e)
                    continue
                self._settle(chunk, results)
                self._adapt_backlog(
                    len(results),
                    sum(1 for r in results
                        if isinstance(r, FDBError) and r.code == 1020),
                )

    # ─────────────────────── pipeline executor ──────────────────────
    def _occ_transition(self, new_level):
        """Time-weighted in-flight accounting (under _inflight_cv):
        ``pipeline_depth_effective`` is the average number of groups in
        flight while the pipeline was busy — 1.0 means the stages never
        actually overlapped, ~depth means the pipe stayed full."""
        now = time.perf_counter()
        if self._occ_level > 0:
            dt = now - self._occ_t
            self._occ_busy += dt
            self._occ_area += self._occ_level * dt
        self._occ_t = now
        self._occ_level = new_level

    @property
    def pipeline_depth_effective(self):
        with self._inflight_cv:
            if self._occ_busy <= 0:
                return 1.0
            return round(self._occ_area / self._occ_busy, 2)

    def stage_summary(self):
        """Per-stage mean wall time (ms) + occupancy for the bench
        artifact: pack (stage A host work: grant + batch build + limb
        staging), dispatch (stage B's device scan call), resolve (the
        host sync stall in stage C), apply (tlog push + storage apply +
        settlement) — plus the pack-path split (flat columnar vs legacy
        request batches), the mean flat bytes per packed batch, and the
        packer's staging-buffer reuse hit rate."""
        out = {
            "stage_pack_ms": round(self.stages.mean_ms("pack"), 3),
            "stage_dispatch_ms": round(self.stages.mean_ms("dispatch"),
                                       3),
            "stage_resolve_ms": round(self.stages.mean_ms("resolve"), 3),
            "stage_apply_ms": round(self.stages.mean_ms("apply"), 3),
            "pipeline_depth": self.pipeline_depth,
            "pipeline_depth_effective": self.pipeline_depth_effective,
        }
        inner = self.inner
        flat = getattr(inner, "pack_flat_batches", 0)
        legacy = getattr(inner, "pack_legacy_batches", 0)
        out["pack_path"] = (
            "flat" if flat and not legacy else
            "legacy" if legacy and not flat else
            "mixed" if flat else "legacy"
        )
        out["pack_flat_batches"] = flat
        out["pack_legacy_batches"] = legacy
        # abort-aware batch scheduling decisions (server/scheduler.py):
        # zero across the board when the knob is off — the fields ride
        # anyway so a bench line always states whether scheduling ran
        out["sched_batches"] = getattr(inner, "sched_batches", 0)
        out["sched_reordered"] = getattr(inner, "sched_reordered_total", 0)
        out["sched_deferred"] = getattr(inner, "sched_deferred_total", 0)
        # which resolve path served this run: "range" (single-dispatch
        # presharded mesh), "hash" (replicated-batch mesh), or "local"
        # (single-lane / host fan-out) — so a bench line always states
        # the path behind its lane_skew_pct numbers
        resolvers = getattr(inner, "resolvers", ())
        out["resolver_sharding"] = next(
            (r.sharding for r in resolvers if hasattr(r, "sharding")),
            "local")
        out["resolver_lanes"] = sum(
            getattr(r, "n_lanes", 1) for r in resolvers)
        out["pack_bytes"] = round(
            getattr(inner, "pack_bytes_total", 0) / max(flat, 1)
        )
        hits = misses = 0
        for r in getattr(inner, "resolvers", ()):
            fast = getattr(r, "_fast", None)
            for pk in (getattr(r, "packer", None),
                       fast[0] if fast else None):
                if pk is not None:
                    hits += pk.flat_reuse_hits
                    misses += pk.flat_reuse_misses
        out["pack_reuse_rate"] = (
            round(hits / (hits + misses), 3) if hits + misses else 0.0
        )
        return out

    def _dispatch_wall(self):
        """The resolvers' cumulative device-dispatch wall time (the
        scan call inside resolve_many) — subtracted from the stage-A+B
        timer so pack and dispatch report as separate stages."""
        return sum(
            getattr(r, "dispatch_wall_s", 0.0)
            for r in getattr(self.inner, "resolvers", ())
        )

    def _pipeline_submit(self, group_chunks, reqs):
        """Run stages A+B for one backlog group and hand it to the
        apply worker; blocks while ``pipeline_depth`` groups are already
        in flight (bounding version-grant runahead and host memory)."""
        with self._inflight_cv:
            while len(self._inflight) >= self.pipeline_depth \
                    and self._apply_thread.is_alive():
                self._inflight_cv.wait(timeout=1.0)
        t0s = span_mod.now()  # stage-span stamp (cheap; ctx known after)
        d0 = self._dispatch_wall()
        t0 = time.perf_counter()
        pgroup = self.inner.commit_batches_begin(reqs)
        pack_s = time.perf_counter() - t0
        # the group's trace context was scanned ONCE inside begin
        gctx = getattr(pgroup, "trace_ctx", None)
        # hand the group to the apply worker BEFORE any other fallible
        # call (FL002): once queued, stage C settles its futures even if
        # this thread dies; the stage timers record after the handoff
        with self._inflight_cv:
            self._inflight.append((group_chunks, pgroup))
            self._occ_transition(len(self._inflight))
            self._inflight_cv.notify_all()
        # dispatch (stage B's scan call) accumulated on this same
        # thread inside begin: report it as its own stage so
        # stage_pack_ms measures HOST PACKING (grant + batch build +
        # staging scatter), the stage the flat path exists to cut
        dispatch_s = max(0.0, self._dispatch_wall() - d0)
        self.stages.add("pack", max(0.0, pack_s - dispatch_s))
        self.stages.add("dispatch", dispatch_s)
        if gctx is not None:
            # per-stage spans mirroring the StageStats split: the pack
            # span is the host-packing share of begin(), the dispatch
            # span the device scan call carved off its tail
            t1s = span_mod.now()
            cut = max(t0s, t1s - dispatch_s)
            span_mod.emit_span("stage.pack", gctx, begin=t0s, end=cut)
            span_mod.emit_span("stage.dispatch", gctx, begin=cut,
                               end=t1s)

    def drain_pipeline(self):
        """Block until every in-flight group has settled (ordering
        barrier before serial fallbacks, flush, and close)."""
        if self._apply_thread is None:
            return
        with self._inflight_cv:
            while self._inflight and self._apply_thread.is_alive():
                self._inflight_cv.wait(timeout=1.0)

    def _apply_loop(self):
        while True:
            with self._inflight_cv:
                while not self._inflight and not self._closed:
                    self._inflight_cv.wait()
                if not self._inflight and self._closed:
                    return
                group_chunks, pgroup = self._inflight[0]
            try:
                self._finish_group(group_chunks, pgroup)
            except BaseException as e:  # pragma: no cover — last resort
                # _finish_group resolves futures itself; this guard only
                # keeps the worker alive (a dead worker would hang both
                # drain_pipeline and every waiting client). Futures are
                # re-set defensively — set() on a settled future is a
                # no-op-safe overwrite the waiters never observe twice.
                TraceEvent("CommitApplyWorkerError",
                           severity=SEV_ERROR).detail(
                    etype=type(e).__name__, error=str(e)[:200]).log()
                self.last_batch_error = e
                try:
                    self._fail_chunks(group_chunks, e)
                except Exception as e2:
                    TraceEvent("CommitSettleError",
                               severity=SEV_ERROR).detail(
                        etype=type(e2).__name__,
                        error=str(e2)[:200]).log()
            finally:
                with self._inflight_cv:
                    self._inflight.popleft()
                    self._occ_transition(len(self._inflight))
                    self._inflight_cv.notify_all()

    def _finish_group(self, group_chunks, pgroup):
        """Stage C for one group: finish at the proxy, settle futures
        in order, feed the AIMD backlog and the stage timers."""
        gctx = getattr(pgroup, "trace_ctx", None)
        t0s = span_mod.now() if gctx is not None else 0.0
        try:
            results_list = self.inner.commit_batches_finish(pgroup)
        except Exception as e:
            self._fail_chunks(group_chunks, e)
            return
        if pgroup.error is not None:
            # the group failed inside the proxy (results are honest
            # 1020/1021s); record the root cause like the serial path
            self.last_batch_error = pgroup.error
        self.stages.add("resolve", pgroup.resolve_s)
        self.stages.add("apply", pgroup.apply_s)
        if gctx is not None:
            # stage-C spans mirroring the timers finish() recorded:
            # resolve (the host sync stall) from the front of the call,
            # apply (log push + storage apply) carved off its tail
            t1s = span_mod.now()
            span_mod.emit_span(
                "stage.resolve", gctx, begin=t0s,
                end=min(t1s, t0s + pgroup.resolve_s))
            span_mod.emit_span(
                "stage.apply", gctx,
                begin=max(t0s, t1s - pgroup.apply_s), end=t1s)
        txns = conflicts = 0
        for chunk, results in zip(group_chunks, results_list):
            self._settle(chunk, results)
            txns += len(results)
            conflicts += sum(
                1 for r in results
                if isinstance(r, FDBError) and r.code == 1020
            )
        self._adapt_backlog(txns, conflicts)

    def _settle(self, chunk, results):
        self._record_span(chunk)
        for (_, fut), res in zip(chunk, results):
            fut.set(res)
        with self._done_cond:  # ONE wakeup for the whole batch
            # stat counters live under _done_cond: _settle runs on the
            # batcher thread, the apply worker, AND caller threads
            # (manual/sim pipelines), so the bare += was a lost-update
            self.batches_committed += 1
            self.txns_batched += len(chunk)
            self.max_batch_seen = max(self.max_batch_seen, len(chunk))
            self._done_cond.notify_all()

    def _record_span(self, chunk):
        """One commit_e2e band record per settled batch window: the
        span from the window's OLDEST submit (the stamped head future —
        submit order is preserved into the chunks) to now. Every txn in
        the window replies together, so this is the honest worst case;
        per batch, not per txn, because tens of thousands of record()
        calls per second would themselves be commit-path overhead.

        The SAME stamps drive slow-commit promotion (utils/span.py): a
        window outliving ``tracing_slow_commit_ms`` while tracing is
        enabled emits a ``commit.window`` span — per-window, like the
        band itself, so unsampled transactions pay nothing extra."""
        if not metrics_mod.enabled():
            return
        born = chunk[0][1].born if chunk else None
        if born is not None:
            end = metrics_mod.now()
            dur = max(0.0, end - born)
            self._m_e2e.record(dur)
            knobs = getattr(self.inner, "knobs", None)
            if (knobs is not None
                    and getattr(knobs, "tracing_sample_rate", 0.0) > 0.0
                    and dur * 1e3 >= knobs.tracing_slow_commit_ms):
                span_mod.slow_window_span(born, end, txns=len(chunk))
        self._m_settled_batches.inc()

    def _fail_chunks(self, chunks, e):
        self.last_batch_error = e
        for chunk in chunks:
            self._record_span(chunk)  # a failure reply is still a reply
            for _, fut in chunk:
                fut.set(e if isinstance(e, FDBError) else
                        FDBError.from_name("commit_unknown_result"))
        with self._done_cond:
            self._done_cond.notify_all()

    def _batcher_loop(self):
        while True:
            # acquire via the Condition (it wraps self._lock — the same
            # mutex): waiting on the object we hold keeps the
            # release-while-parked relationship explicit (FL003)
            with self._wake:
                while not self._pending and not self._closed:
                    self._wake.wait()
                if self._closed and not self._pending:
                    return
            # batch window: let concurrent committers pile in
            if self.interval_s:
                time.sleep(self.interval_s)
            with self._lock:
                pending, self._pending = self._pending, []
                self._first_pending_step = None
            if pending:
                try:
                    self._run_batch(pending)
                except BaseException as e:  # pragma: no cover — last resort
                    # _run_batch resolves futures itself; this guard only
                    # keeps the batcher alive if future.set's internals fail
                    TraceEvent("CommitBatcherError",
                               severity=SEV_ERROR).detail(
                        etype=type(e).__name__, error=str(e)[:200]).log()
                    self.last_batch_error = e

    def fail_pending(self, error):
        """Resolve every queued commit with ``error`` — a cluster crash
        took the proxy down before the batch formed; clients see
        commit_unknown_result and retry against the new incarnation."""
        with self._lock:
            pending, self._pending = self._pending, []
            self._first_pending_step = None
        for _, fut in pending:
            fut.set(error)
        with self._done_cond:
            self._done_cond.notify_all()

    def close(self):
        with self._lock:
            self._closed = True
            self._wake.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
            if self._thread.is_alive():
                # still mid-batch (e.g. first-dispatch JIT compile): the
                # batcher owns the pipeline; flushing from this thread
                # would interleave two commit_batch runs on shared state
                return
        self.flush()
        if self._apply_thread is not None:
            # flush drained the pipe; the closed flag lets the worker
            # exit its wait loop
            with self._inflight_cv:
                self._inflight_cv.notify_all()
            self._apply_thread.join(timeout=30)
        if hasattr(self.inner, "close"):
            self.inner.close()  # release the sub-resolve pool

    # pass everything else (commit_count, pump_durability, …) through
    def __getattr__(self, name):
        return getattr(self.inner, name)
