r"""Cluster doctor: latency probes, recovery timeline, health verdict.

Ref parity: the health layer of fdbserver/Status.actor.cpp —
``latencyProbe`` (status runs REAL transactions against the cluster and
reports how long GRV/read/commit took), ``recovery_state`` (the named
phase the master recovery is in), and ``cluster.messages`` (the
machine-checkable alert list operators and watchdogs key off).

Three pieces, all cluster-owned so they survive txn-system recoveries:

* ``LatencyProber`` — periodically runs a tagged probe transaction
  (GRV → point read → commit on ``\xff/probe/latency``) against the
  live cluster and records per-hop latency bands into the cluster's
  ("prober", 0) registry. The probe key lives in the plain system
  keyspace (NOT the virtual \xff\xff space), so the probe exercises the
  full commit pipeline — sequencer, resolver, tlog, storage — while the
  storage read sampler's ``key < \xff`` guard keeps it out of workload
  heatmaps. Cadence rides the injected deterministic clock with jitter
  from the named "latency-probe" stream (the FL001 seam): same-seed
  sims fire the same probes at the same steps.
* ``RecoveryTimeline`` — a bounded ring of per-recovery phase
  breakdowns (fence → coordinator CAS → recruit → tlog replay →
  accept-commits), stamped off the deterministic clock. Simulations
  install ``cluster.clock_advance`` so each phase consumes simulated
  time and same-seed runs agree byte-for-byte.
* ``build_health`` — folds lag/saturation rollups (storage durability
  lag, tlog queue depth, GRV queue depth, per-reason ratekeeper denial
  counters) with the prober and timeline into one ``cluster.health``
  doc carrying a doctor verdict (``healthy | degraded | unavailable``),
  sorted reasons, and FDB-style ``messages``.

``set_enabled(False)`` is the module kill switch (the health_smoke
bench measures enabled-vs-disabled cost): the prober stops firing and
``maybe_probe`` becomes a cheap no-op; the health DOC stays readable —
turning off probes must not blind the doctor.
"""

import threading

from foundationdb_tpu.core import deterministic
from foundationdb_tpu.core.errors import FDBError

# the probe row: plain system keyspace (replicated everywhere, excluded
# from heatmaps by the storage sampler's key < \xff guard), never the
# virtual \xff\xff space — a probe must pay the REAL commit pipeline
PROBE_KEY = b"\xff/probe/latency"
PROBE_TAG = "probe"

_enabled = True
_enabled_mu = threading.Lock()


def set_enabled(on):
    """Process-wide prober kill switch (health_smoke measures the
    delta). The health document stays readable either way."""
    global _enabled
    with _enabled_mu:
        _enabled = bool(on)


def enabled():
    return _enabled


class LatencyProber:
    """Live GRV/read/commit probe transactions (ref: Status.actor.cpp
    latencyProbe). Pull-based: ``maybe_probe()`` fires at most once per
    knob interval off the injected clock; thread-mode clusters drive it
    from a daemon loop, sims/tests call it from their own schedule."""

    def __init__(self, cluster):
        self.cluster = cluster
        reg = cluster._role_registry("prober")
        self._m_grv = reg.latency("probe_grv")
        self._m_read = reg.latency("probe_read")
        self._m_commit = reg.latency("probe_commit")
        self._m_probes = reg.counter("probes")
        self._m_failures = reg.counter("probe_failures")
        # jittered cadence off the named deterministic stream (FL001):
        # same-seed sims draw the same offsets, real fleets de-align
        self._rng = deterministic.rng("latency-probe")
        # flowlint: shared(single-driver protocol: thread mode probes ONLY from the daemon loop, sims ONLY from their scheduler — never both, one writer at a time)
        self._next_due = None
        # flowlint: shared(last-writer-wins breadcrumb; the doctor only polls it)
        self.last_error = None  # last failed probe's error code
        self._stop = threading.Event()
        self._thread = None

    # ── cadence ──────────────────────────────────────────────────────
    def maybe_probe(self):
        """Fire one probe if the interval elapsed; returns True iff a
        probe ran (successfully or not)."""
        if not enabled() or not self.cluster.knobs.health_probe_enabled:
            return False
        interval = self.cluster.knobs.health_probe_interval_s
        now = deterministic.now()
        if self._next_due is None:
            # first call arms the schedule with a jittered offset so a
            # fleet of probers never thunders in step
            self._next_due = now + interval * self._rng.random()
            return False
        if now < self._next_due:
            return False
        self._next_due = now + interval * (0.5 + self._rng.random())
        self.probe_now()
        return True

    def probe_now(self):
        """One probe transaction: GRV, point read, commit — each hop
        timed off the injected clock. Lock-aware (a locked database is
        not an unhealthy one) and tagged so workload attribution can
        separate probe traffic; returns True on success."""
        tr = self.cluster.database().create_transaction()
        tr.options.set_tag(PROBE_TAG)
        tr.options.set_lock_aware()
        t0 = deterministic.now()
        try:
            tr.get_read_version()
            t1 = deterministic.now()
            tr.get(PROBE_KEY)
            t2 = deterministic.now()
            # deterministic payload: the probe sequence number
            tr.set(PROBE_KEY, b"%d" % self._m_probes.value)
            tr.commit()
            t3 = deterministic.now()
        except FDBError as e:
            # a failing probe IS the signal: count it and move on (the
            # doctor reads probe_failures; retrying here would hide the
            # outage the probe exists to witness)
            self._m_probes.inc()
            self._m_failures.inc()
            self.last_error = e.code
            return False
        self._m_probes.inc()
        self.last_error = None
        self._m_grv.record(t1 - t0)
        self._m_read.record(t2 - t1)
        self._m_commit.record(t3 - t2)
        return True

    # ── background driver (thread-mode clusters only) ────────────────
    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="latency-prober", daemon=True
        )
        self._thread.start()

    def _loop(self):
        from foundationdb_tpu.utils.trace import SEV_ERROR, TraceEvent

        interval = self.cluster.knobs.health_probe_interval_s
        while not self._stop.wait(interval):
            try:
                self.maybe_probe()
            except Exception as e:
                # the prober must never take the cluster down — but a
                # broken probe is forensics-worthy, not silence
                TraceEvent("LatencyProbeError", severity=SEV_ERROR) \
                    .detail(error=repr(e))
                self._m_failures.inc()

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)

    # ── reporting ────────────────────────────────────────────────────
    def status(self):
        return {
            "enabled": enabled()
            and bool(self.cluster.knobs.health_probe_enabled),
            "probes": self._m_probes.value,
            "failures": self._m_failures.value,
            "last_error": self.last_error,
            "grv": self._m_grv.bands_ms(),
            "read": self._m_read.bands_ms(),
            "commit": self._m_commit.bands_ms(),
        }


# ── recovery-state timeline ──────────────────────────────────────────
RECOVERY_PHASES = ("fence", "cas", "recruit", "replay", "accept")


class RecoveryTimeline:
    """Bounded ring of txn-system recovery phase breakdowns (ref: the
    recovery_state section of status json + the master recovery trace
    events operators graph). Cluster-owned: survives every recovery it
    records; byte-identical across same-seed sims because every stamp
    comes off the injected clock."""

    MAX_RECORDS = 16

    def __init__(self):
        self.records = []
        self.count = 0  # total recoveries ever (the ring forgets, this doesn't)

    def begin(self, trigger, clock_advance=None):
        return _RecoveryRecorder(self, trigger, clock_advance)

    def last_recovery_ms(self):
        return self.records[-1]["total_ms"] if self.records else 0.0

    def snapshot(self):
        return {
            "count": self.count,
            "last_recovery_ms": self.last_recovery_ms(),
            "records": [dict(r) for r in self.records],
        }


class _RecoveryRecorder:
    """One in-flight recovery's phase stopwatch. ``clock_advance`` is
    the simulation's hook (each phase mark consumes a simulated tick so
    same-seed phase durations are nonzero AND identical); production
    leaves it None and measures real elapsed time."""

    def __init__(self, timeline, trigger, clock_advance):
        self._timeline = timeline
        self._advance = clock_advance
        started = deterministic.now()
        self._last = started
        self.record = {
            "generation": None,
            "trigger": trigger,
            "started_at": round(started, 6),
            "phases": {},
            "total_ms": 0.0,
        }

    def phase(self, name):
        """Close the phase that just ran (marks are placed AFTER each
        phase's work in cluster._recover_txn_system)."""
        if self._advance is not None:
            self._advance()
        now = deterministic.now()
        self.record["phases"][name] = round((now - self._last) * 1000, 3)
        self._last = now

    def finish(self, generation, recovered_version):
        self.record["generation"] = generation
        self.record["recovered_version"] = recovered_version
        self.record["total_ms"] = round(
            sum(self.record["phases"].values()), 3
        )
        tl = self._timeline
        tl.count += 1
        tl.records.append(self.record)
        del tl.records[: -tl.MAX_RECORDS]


# ── health doc + verdict ─────────────────────────────────────────────
# FDB-style cluster.messages (ref: the messages array Status.actor.cpp
# emits): name → operator-facing description, keyed by reason
_MESSAGES = {
    "sequencer_down": "The sequencer is unreachable; commits and read "
                      "versions cannot be served until recovery.",
    "commit_proxy_down": "The commit proxy is unreachable; commits fail "
                         "until recovery.",
    "storage_servers_down": "No storage server is reachable; the "
                            "database is unavailable.",
    "log_quorum_lost": "The log system has lost its ack quorum; commits "
                       "cannot become durable.",
    "storage_server_down": "One or more storage servers are down; "
                           "recruitment is pending.",
    "log_replica_down": "One or more log replicas are down; the log "
                        "tier is degraded.",
    "resolver_down": "One or more resolvers are down; respawn is "
                     "pending.",
    "storage_lag": "A storage server's durability lag exceeds the "
                   "doctor threshold.",
    "workload_saturated": "The ratekeeper is shedding load "
                          "(target TPS squeezed below capacity).",
    "probe_failures": "The most recent latency probe failed; the "
                      "transaction path may be impaired.",
    "probe_trend": "A latency probe p99 is rising monotonically across "
                   "consecutive history windows; latency is trending "
                   "toward the SLO threshold before breaching it.",
    "region_lag": "Remote-region replication lag exceeds the doctor "
                  "threshold; a failover now would lose that much.",
    "region_replication_broken": "Region replication lost log "
                                 "continuity; the satellite must be "
                                 "re-seeded before it can fail over.",
    "satellite_down": "The satellite region is unreachable (WAN "
                      "partition); replication lag is growing.",
    "rpc_endpoints_failed": "The failure monitor holds one or more RPC "
                            "endpoints marked failed; calls to them "
                            "are being skipped until a recovery probe "
                            "succeeds.",
    "data_inconsistent": "The consistency scan confirmed replica "
                         "divergence (re-read against the live shard "
                         "map); the data is corrupt on at least one "
                         "replica.",
}


def build_health(cluster):
    """The ``cluster.health`` document: verdict + sorted reasons +
    messages + probe bands + recovery timeline + lag/saturation
    rollups. A pure read — no probes fire, no state mutates — so
    status() stays side-effect free."""
    from foundationdb_tpu.server.tlog import TLogSystem
    from foundationdb_tpu.utils import metrics as metrics_mod

    knobs = cluster.knobs
    storages = cluster.storages
    live_storages = sum(1 for s in storages if s.alive)
    sequencer_up = cluster.sequencer.alive
    proxy_up = cluster._commit_target().alive

    # ── lag rollups ──
    committed = cluster.sequencer.committed_version
    per_storage = []
    for i, s in enumerate(storages):
        lag = max(0, committed - s.durable_version) if s.alive else None
        per_storage.append({"id": i, "alive": s.alive,
                            "durability_lag_versions": lag})
    lags = [r["durability_lag_versions"] for r in per_storage
            if r["durability_lag_versions"] is not None]
    lag_max = max(lags, default=0)
    if isinstance(cluster.tlog, TLogSystem):
        logs = cluster.tlog.logs
        quorum_ok = cluster.tlog.live_count >= cluster.tlog.quorum
        logs_live, logs_total = cluster.tlog.live_count, cluster.tlog.n
    else:
        logs = [cluster.tlog]
        quorum_ok = True
        logs_live = logs_total = 1
    tlog_depth = max(
        (len(l._log) for l in logs if l.alive), default=0
    )
    tlog_pushes = sum(l.metrics.counter("pushes").value for l in logs)
    grv_depth = max(
        (reg.gauge("grv_queue_depth").value
         for reg in cluster._role_registries("grv_proxy")), default=0
    )

    # ── saturation (ratekeeper) ──
    rk = cluster.ratekeeper
    saturation = round(1.0 - rk.target_tps / max(rk.max_tps, 1e-9), 4)
    rk_doc = {
        "target_tps": rk.target_tps,
        "max_tps": rk.max_tps,
        "saturation": saturation,
        # per-reason denial counters (registry-backed: survive recovery
        # and show in benchdiff trajectories)
        "admit_denied_tag": rk.metrics.counter("admit_denied_tag").value,
        "admit_denied_budget": rk.metrics.counter(
            "admit_denied_budget").value,
        "throttled_tags": len(rk.throttled_tags()),
    }

    # ── verdict ──
    unavailable, degraded = set(), set()
    if not sequencer_up:
        unavailable.add("sequencer_down")
    if not proxy_up:
        unavailable.add("commit_proxy_down")
    if live_storages == 0:
        unavailable.add("storage_servers_down")
    if not quorum_ok:
        unavailable.add("log_quorum_lost")
    if live_storages < len(storages):
        degraded.add("storage_server_down")
    if logs_live < logs_total:
        degraded.add("log_replica_down")
    if any(not r.alive for r in cluster.resolvers):
        degraded.add("resolver_down")
    if lag_max > knobs.doctor_lag_versions:
        degraded.add("storage_lag")
    if saturation >= 0.5:
        degraded.add("workload_saturated")
    # ── multi-region replication (server/region.py) ──
    # always-present section: tools never branch on a missing key. The
    # broken/partition split matters to an operator — broken needs a
    # re-seed, a partition just needs the WAN back (elif: broken
    # subsumes the connectivity complaint).
    reg = getattr(cluster, "regions", None)
    regions_doc = reg.status() if reg is not None else {
        "configured": False}
    if reg is not None and reg.replicating:
        if reg.broken:
            degraded.add("region_replication_broken")
        elif reg.partitioned:
            degraded.add("satellite_down")
        if (regions_doc["replication_lag_versions"]
                > knobs.doctor_region_lag_versions):
            degraded.add("region_lag")
    # ── RPC endpoint health (rpc/failuremon.py) ──
    # this process's failure-monitor view: which peers it is currently
    # routing around, plus the timeout/failure tallies. snapshot() is
    # wall-time free, so same-seed sim health docs stay byte-identical.
    from foundationdb_tpu.rpc import failuremon

    rpc_doc = failuremon.monitor().snapshot()
    if rpc_doc["failed"]:
        degraded.add("rpc_endpoints_failed")
    prober = getattr(cluster, "prober", None)
    probe_doc = prober.status() if prober is not None else {
        "enabled": False, "probes": 0, "failures": 0, "last_error": None,
        "grv": metrics_mod.merged_bands_ms([]),
        "read": metrics_mod.merged_bands_ms([]),
        "commit": metrics_mod.merged_bands_ms([]),
    }
    if probe_doc["last_error"] is not None:
        degraded.add("probe_failures")
    # ── continuous consistency scan (server/consistencyscan.py) ──
    # a CONFIRMED inconsistency (survived the live-map re-read) is a
    # degraded verdict: the database still serves, but at least one
    # replica holds corrupt data. The verdict transition makes the
    # flight recorder dump the black box automatically.
    scanner = getattr(cluster, "scanner", None)
    scan_doc = scanner.status() if scanner is not None else {
        "enabled": False, "round": 0, "progress_pct": 0.0, "cursor": "",
        "batches": 0, "keys_scanned": 0, "bytes_scanned": 0,
        "last_round_ms": 0.0, "round_age_s": 0.0,
        "inconsistencies": 0, "reread_saves": 0,
        "last_error": None, "errors": [],
    }
    if scan_doc["inconsistencies"]:
        degraded.add("data_inconsistent")
    # ── trend-aware early warning (utils/timeseries.py) ──
    # a probe p99 rising monotonically across doctor_trend_windows
    # history windows degrades the verdict BEFORE the instant
    # doctor_probe_p99_ms threshold breaches — the trend-consuming
    # doctor alert ROADMAP item 4's admission control will act on
    hist = getattr(cluster, "history", None)
    trend_alerts = hist.trend_alerts() if hist is not None else []
    if trend_alerts:
        degraded.add("probe_trend")
    if unavailable:
        verdict, reasons = "unavailable", unavailable | degraded
    elif degraded:
        verdict, reasons = "degraded", degraded
    else:
        verdict, reasons = "healthy", set()
    reasons = sorted(reasons)

    timeline = getattr(cluster, "recovery_timeline", None)
    rec = timeline.snapshot() if timeline is not None else {
        "count": 0, "last_recovery_ms": 0.0, "records": []}
    rec["generation"] = cluster.generation

    return {
        "verdict": verdict,
        "reasons": reasons,
        "messages": [
            {"name": r,
             "description": _MESSAGES.get(r, r)} for r in reasons
        ],
        "probe": probe_doc,
        "consistency_scan": scan_doc,
        "trend_alerts": trend_alerts,
        "recovery": rec,
        "lag": {
            "durability_lag_versions_max": lag_max,
            "storages": per_storage,
            "tlog_queue_depth": tlog_depth,
            "tlog_pushes": tlog_pushes,
            "logs_live": logs_live,
            "logs_total": logs_total,
            "grv_queue_depth": grv_depth,
        },
        "ratekeeper": rk_doc,
        "regions": regions_doc,
        "rpc": rpc_doc,
    }
