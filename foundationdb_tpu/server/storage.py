"""Storage server: MVCC versioned reads over an ordered key space.

Ref parity: fdbserver/storageserver.actor.cpp — serves reads at a
client's read version within the 5s MVCC window, applies committed
mutations in version order, resolves key selectors, supports watches.
Mirrors the reference's two-tier design: a versioned in-memory overlay
(PTree in the reference) holding the MVCC window, above a pluggable
single-version persistent engine (server/kvstore.py) that stores the
state as of the *durable version*. ``flush()`` advances the durable
version by folding overlay versions into the engine, exactly like the
reference's updateStorage loop making versions durable then popping the
tlog.
"""

import threading

from collections import deque

try:
    from sortedcontainers import SortedDict
except ImportError:  # container without the dep: the in-repo shim
    from foundationdb_tpu.utils.sorteddict import SortedDict

from foundationdb_tpu.core.errors import FDBError, err
from foundationdb_tpu.core.keys import KeySelector, key_successor
from foundationdb_tpu.core.mutations import ATOMIC_OPS, Op, apply_atomic
from foundationdb_tpu.server.kvstore import KeyValueStoreMemory
from foundationdb_tpu.utils import heatmap as heatmap_mod
from foundationdb_tpu.utils import lockdep
from foundationdb_tpu.utils import metrics as metrics_mod
from foundationdb_tpu.utils import span as span_mod

_MISS = object()  # overlay has no entry at-or-below the read version


class Watch:
    """Fires when the watched key's value diverges from the seen value.

    Ref: watchValue in storageserver.actor.cpp."""

    def __init__(self, key, seen_value):
        self.key = key
        self.seen_value = seen_value
        self.fired = False
        self._callbacks = []

    def on_fire(self, cb):
        if self.fired:
            cb()
        else:
            self._callbacks.append(cb)

    def _fire(self):
        if not self.fired:
            self.fired = True
            for cb in self._callbacks:
                cb()



class RangeReadInterface:
    """Key-selector resolution and range reads over any provider of
    ``_iter_live(begin, end, version, reverse)`` + ``_check_version``.

    Shared by StorageServer (one storage's merged overlay/engine view)
    and StorageRouter (the partitioned tier stitched across shards) so
    selector semantics cannot diverge between them.
    """

    _WALK_END = b"\xff\xff"  # past every user + system key

    def _live_keys(self, begin, end, version, reverse=False):
        for k, _ in self._iter_live(begin, end, version, reverse=reverse):
            yield k

    def read_range(self, begin, end, version, limit=None):
        """Plain (key, value) list over [begin, end) at ``version`` —
        the shard-copy read used by data distribution (ref: fetchKeys'
        getRange stream), bypassing key-selector resolution."""
        self._check_version(version)
        out = []
        for kv in self._iter_live(begin, end, version):
            out.append(kv)
            if limit is not None and len(out) >= limit:
                break
        return out

    def resolve_selector(self, sel: KeySelector, version):
        """Resolve a key selector to a concrete key (ref: storageserver
        findKey): start at the last live key < (or <=) sel.key, then move
        ``offset`` live keys right. Clamps to b'' / \\xff sentinel."""
        import itertools

        self._check_version(version)
        offset = sel.offset
        upper = sel.key + b"\x00" if sel.or_equal else sel.key
        # lazily walk left from the reference key, taking only what the
        # offset needs (the reference does the same bounded walk in findKey)
        need = 1 if offset > 0 else (-offset + 1)
        prev = list(
            itertools.islice(self._live_keys(b"", upper, version, reverse=True), need)
        )
        if offset > 0:
            start = prev[0] + b"\x00" if prev else b""
            following = self._live_keys(start, self._WALK_END, version)
            k = next(itertools.islice(following, offset - 1, None), None)
            return k if k is not None else b"\xff"
        else:
            # offset 0 => last-less-than(-or-equal); negative walks left
            idx = -offset
            if idx < len(prev):
                return prev[idx]
            return b""

    def get_range(self, begin_sel, end_sel, version, limit=0, reverse=False):
        """Half-open range read by key selectors. Returns list[(k, v)]."""
        self._check_version(version)
        begin = begin_sel if isinstance(begin_sel, bytes) else self.resolve_selector(begin_sel, version)
        end = end_sel if isinstance(end_sel, bytes) else self.resolve_selector(end_sel, version)
        if begin > end:
            return []
        out = []
        for kv in self._iter_live(begin, end, version, reverse=reverse):
            out.append(kv)
            if limit and len(out) >= limit:
                break
        return out


class StorageServer(RangeReadInterface):
    def __init__(self, window_versions=5_000_000, engine=None):
        # overlay: key -> list[(version, value_or_None)] ascending, all
        # versions > durable_version; None = tombstone
        self._overlay = SortedDict()
        self._dirty = deque()  # (version, key) in apply order, for flush
        # Guards overlay/engine mutation vs reads: in thread-mode batching
        # the batcher thread applies/flushes while client threads read.
        # SortedDict iteration is not safe under concurrent mutation, so
        # readers hold the same lock (RLock: flush iterates internally).
        # Single-threaded deployments pay one uncontended acquire per op.
        self._mu = lockdep.rlock("StorageServer._mu")
        self.alive = True  # failure detection flips this (sim kill)
        # placement tag (ref: storage locality in DatabaseConfiguration
        # region blocks): the cluster stamps its primary-region id when
        # regions are configured, and recruitment carries it to
        # replacements. None = regions not configured.
        self.region = None
        self.engine = engine if engine is not None else KeyValueStoreMemory()
        # Versioned engines (the Redwood role, kvstore.KeyValueStoreVersioned)
        # store per-key version chains, so the MVCC window extends into the
        # durable tier: flush() writes every overlay version down instead of
        # folding, and reads below durable_version stay serveable.
        self.versioned_engine = bool(getattr(self.engine, "versioned", False))
        self.durable_version = self.engine.stored_version()
        if self.versioned_engine:
            self.oldest_version = self.engine.oldest_retained
        else:
            self.oldest_version = self.durable_version
        self.version = self.durable_version  # latest applied
        self.window_versions = window_versions
        self._watches = {}  # key -> list[Watch]
        # apply/flush-latency bands + volume counters (ref: the storage
        # server's StorageMetrics fed into status json). Recruitment
        # hands the replacement this registry so counters never rewind.
        self.metrics = metrics_mod.MetricsRegistry("storage")
        self._m_apply = self.metrics.latency("storage_apply")
        self._m_mutations = self.metrics.counter("mutations_applied")
        self._m_reads = self.metrics.counter("point_reads")
        self._m_range_reads = self.metrics.counter("range_reads")
        # multiplexed read batches (txn/futures.py ReadBatcher →
        # rpc read_batch endpoint): serve latency band, reads-per-RPC
        # histogram, and the coalesce-rate counters bench lines report
        self._m_read_batch = self.metrics.latency("read_batch")
        self._m_read_batch_keys = self.metrics.latency("read_batch_keys")
        self._m_read_batches = self.metrics.counter("read_batches")
        self._m_batched_reads = self.metrics.counter("batched_reads")
        # read/write key sampling (ref: StorageMetrics byte-sampling):
        # cluster-owned heatmaps attached via attach_heatmaps; None =
        # sampling off. Countdown sampling — one integer decrement per
        # access, a "key-sample"-stream draw only when a sample fires —
        # keeps the hot-path cost inside the heatmap_smoke 2% budget.
        self._read_heat = None
        self._write_heat = None
        self._sample_every = 8
        self._sample_w = 8.0
        self._srng = None
        self._read_cd = 1  # first access sampled: heat appears promptly
        self._write_cd = 1

    @classmethod
    def recover(cls, engine, log_records, window_versions=5_000_000):
        """Rebuild from a persistent engine + tlog records past its
        durable version (ref: storage server recovery peeking the tlog)."""
        ss = cls(window_versions=window_versions, engine=engine)
        for version, mutations in log_records:
            if version > ss.durable_version:
                ss.apply(version, mutations)
        return ss

    # ───────────────────────────── writes ──────────────────────────────
    def apply(self, version, mutations):
        """Apply one commit's mutations at ``version`` (monotone order).

        The SET case is inlined (no _append call): it is the bulk of
        every write-heavy batch and this loop runs on the batcher
        thread for the WHOLE cluster — its per-mutation cost is a
        direct throughput tax on the commit pipeline."""
        if version <= self.version:
            raise ValueError(f"apply out of order: {version} <= {self.version}")
        # a traced batch (the proxy's ambient batch-span context) gets
        # a storage.apply hop span alongside the latency band
        asp = span_mod.from_context("storage.apply", span_mod.current(),
                                    version=version)
        t0 = metrics_mod.now()
        with self._mu:
            overlay_get = self._overlay.get
            overlay = self._overlay
            dirty_append = self._dirty.append
            watches = self._watches
            for m in mutations:
                op = m.op
                if op is Op.SET:
                    key = m.key
                    chain = overlay_get(key)
                    if chain is None:
                        overlay[key] = chain = []
                    chain.append((version, m.param))
                    dirty_append((version, key))
                    if watches:
                        self._fire_watches(key, m.param)
                elif op is Op.CLEAR_RANGE:
                    self._apply_clear_range(m.key, m.param, version)
                elif op is Op.CLEAR:
                    self._append(m.key, version, None)
                elif op in ATOMIC_OPS:
                    old = self._lookup(m.key, version)
                    self._append(m.key, version, apply_atomic(m.op, old, m.param))
                else:
                    raise ValueError(f"unresolved mutation {m.op} reached storage")
            self.version = version
        self._m_apply.record(max(0.0, metrics_mod.now() - t0))
        self._m_mutations.inc(len(mutations))
        if self._write_heat is not None and mutations:
            # write sampling stays OUT of the inlined SET loop: one
            # countdown decrement per apply call, a sampled key drawn
            # from the batch only when the countdown fires (and the kill
            # switch checked only then — per fire, not per apply)
            self._write_cd -= len(mutations)
            if self._write_cd <= 0:
                self._write_cd = self._srng.randrange(
                    1, 2 * self._sample_every + 1)
                if heatmap_mod.enabled():
                    m = mutations[self._srng.randrange(len(mutations))]
                    if m.key < b"\xff":  # user keyspace only (see reads)
                        self._write_heat.charge(m.key, self._sample_w)
        asp.finish(mutations=len(mutations))

    def _apply_clear_range(self, begin, end, version):
        # tombstone every key the clear shadows: overlay keys in range plus
        # engine (durable) keys in range not yet overlaid
        keys = set(self._overlay.irange(begin, end, inclusive=(True, False)))
        keys.update(k for k, _ in self.engine.get_range(begin, end))
        for k in keys:
            self._append(k, version, None)

    def _append(self, key, version, value):
        chain = self._overlay.get(key)
        if chain is None:
            chain = []
            self._overlay[key] = chain
        chain.append((version, value))
        self._dirty.append((version, key))
        if self._watches:
            self._fire_watches(key, value)

    def _fire_watches(self, key, value):
        watchers = self._watches.get(key)
        if watchers:
            for w in watchers:
                if value != w.seen_value:
                    w._fire()
            self._watches[key] = [w for w in watchers if not w.fired]

    def flush(self, up_to_version=None):
        """Make versions ≤ ``up_to_version`` durable: fold the newest
        overlay entry at-or-below it into the engine, prune the overlay,
        advance durable_version. Returns the new durable version."""
        if up_to_version is None:
            up_to_version = self.version
        up_to_version = min(up_to_version, self.version)
        if up_to_version <= self.durable_version:
            return self.durable_version
        with self._mu:
            return self._flush_locked(up_to_version)

    def _flush_locked(self, up_to_version):
        # the dirty queue is version-ordered, so flushing touches only keys
        # actually written at-or-below the target (ref: the version-ordered
        # update queue in the storage server's updateStorage loop)
        touched = set()
        while self._dirty and self._dirty[0][0] <= up_to_version:
            touched.add(self._dirty.popleft()[1])
        for key in touched:
            chain = self._overlay.get(key)
            if chain is None:
                continue
            folded = _MISS
            keep = []
            for v, val in chain:
                if v <= up_to_version:
                    if self.versioned_engine:
                        # Redwood-style: every version goes down intact
                        self.engine.set_versioned(key, v, val)
                    folded = val
                else:
                    keep.append((v, val))
            if folded is not _MISS and not self.versioned_engine:
                if folded is None:
                    self.engine.clear_range(key, key_successor(key))
                else:
                    self.engine.set(key, folded)
            if keep:
                self._overlay[key] = keep
            else:
                del self._overlay[key]
        self.engine.commit(up_to_version)
        self.durable_version = up_to_version
        if not self.versioned_engine:
            # reads below the durable version can no longer be served (the
            # engine is single-version); keep the window invariant tight.
            # A versioned engine keeps serving them from its chains, so its
            # read floor moves only with advance_window (+ prune).
            self.oldest_version = max(self.oldest_version, up_to_version)
        return self.durable_version

    def kill(self):
        """Process death: volatile state is gone for callers (reads and
        watches error until the cluster controller recruits a
        replacement). Ref: sim2 killing one storage process."""
        self.alive = False

    # ───────────────────────────── reads ───────────────────────────────
    def _check_version(self, version):
        if not self.alive:
            # retryable: the client re-routes / waits out recruitment
            # (ref: the client's wrong_shard_server / future_version retry
            # loop against a dead storage interface)
            raise err("process_behind")
        if version < self.oldest_version:
            raise err("transaction_too_old")
        if version > self.version:
            raise err("future_version")

    def _lookup(self, key, version):
        """Value of key at version (overlay first, engine beneath)."""
        chain = self._overlay.get(key)
        if chain:
            val = _MISS
            for v, x in chain:
                if v <= version:
                    val = x
                else:
                    break
            if val is not _MISS:
                return val
        if self.versioned_engine:
            return self.engine.get_at(key, version)
        return self.engine.get(key)

    def get(self, key, version):
        self._check_version(version)
        self._m_reads.inc()
        if self._read_heat is not None:
            # countdown inlined: the per-read sampling cost is ONE
            # integer decrement — no function call until a sample fires
            self._read_cd -= 1
            if self._read_cd <= 0:
                self._sample_read(key)
        with self._mu:
            return self._lookup(key, version)

    def read_batch(self, ops):
        """Vectorized multi-key serve: one LOCK ACQUISITION for the
        whole batch instead of one per key (the Jiffy lesson — batch
        the per-item crossing). ``ops`` is a list of tuples:

        - ``("g", key, rv)`` → value or None
        - ``("r", begin, end, rv, limit, reverse)`` → list[(k, v)]
        - ``("s", selector, rv)`` → resolved key

        Returns one slot per op, FDBError slots included (per-key
        errors are NOT batch-fatal — a too-old key fails alone).
        Delegates to the public per-op methods under the held RLock
        (reentrant), so version checks, read counters, and countdown
        heat sampling charge EXACTLY as the unbatched path does: one
        decrement per key served, never one per RPC."""
        t0 = metrics_mod.now()
        out = []
        with self._mu:
            for op in ops:
                try:
                    kind = op[0]
                    if kind == "g":
                        out.append(self.get(op[1], op[2]))
                    elif kind == "r":
                        out.append([
                            (k, v) for k, v in self.get_range(
                                op[1], op[2], op[3],
                                limit=op[4], reverse=op[5],
                            )
                        ])
                    elif kind == "s":
                        out.append(self.resolve_selector(op[1], op[2]))
                    else:
                        raise err("client_invalid_operation")
                except FDBError as e:
                    out.append(e)
        self._m_read_batch.record(max(0.0, metrics_mod.now() - t0))
        # reads-per-RPC histogram: recorded /1e3 so bands_ms()'s ×1e3
        # yields the RAW batch size (p50_ms field == p50 batch size)
        self._m_read_batch_keys.record(len(ops) / 1e3)
        self._m_read_batches.inc()
        self._m_batched_reads.inc(len(ops))
        return out

    def _overlay_at(self, key, version):
        """Newest overlay value at-or-below ``version`` (or _MISS)."""
        val = _MISS
        for v, x in self._overlay.get(key, ()):
            if v <= version:
                val = x
            else:
                break
        return val

    def _iter_live(self, begin, end, version, reverse=False):
        """Lazy merged (key, value) iteration of engine + overlay at
        ``version`` — overlay wins ties; pulls the engine cursor only as
        far as the caller consumes (limit pushdown).

        Holds the mutation lock for the duration of the iteration: every
        in-package consumer drains (or drops) the generator within one
        call, so the lock's critical section ends when that call returns
        (CPython closes the abandoned generator at function exit)."""
        self._m_range_reads.inc()
        if self._read_heat is not None:
            # a range read charges its begin key: the scan's heat lands
            # on the range's bucket without touching the merge loop
            self._read_cd -= 1
            if self._read_cd <= 0:
                self._sample_read(begin)
        with self._mu:
            yield from self._iter_live_locked(begin, end, version, reverse)

    def _iter_live_locked(self, begin, end, version, reverse=False):
        sentinel = object()
        ov = iter(self._overlay.irange(begin, end, inclusive=(True, False), reverse=reverse))
        if self.versioned_engine:
            base = self.engine.iter_range_at(begin, end, version, reverse=reverse)
        else:
            base = self.engine.iter_range(begin, end, reverse=reverse)
        ko = next(ov, sentinel)
        kb = next(base, sentinel)
        while ko is not sentinel or kb is not sentinel:
            if kb is sentinel:
                take_overlay = True
            elif ko is sentinel:
                take_overlay = False
            elif ko == kb[0]:
                # same key in both: overlay decides if it has an entry
                val = self._overlay_at(ko, version)
                if val is _MISS:
                    val = kb[1]
                if val is not None:
                    yield ko, val
                ko = next(ov, sentinel)
                kb = next(base, sentinel)
                continue
            else:
                take_overlay = (ko < kb[0]) != reverse
            if take_overlay:
                val = self._overlay_at(ko, version)
                if val is not _MISS and val is not None:
                    yield ko, val
                ko = next(ov, sentinel)
            else:
                yield kb
                kb = next(base, sentinel)

    def export_shard(self, begin, end):
        """Snapshot a shard WITH its MVCC history: engine base rows at
        the durable version plus every overlay version chain. Data
        distribution hands this to joiners so reads at pre-move read
        versions stay correct (ref: fetchKeys streaming + the mutation
        buffer that brings a joining storage up to date)."""
        with self._mu:
            if self.versioned_engine:
                # the engine holds real history below durable_version —
                # export it intact so the joiner can honor the same floor
                base = {k: c for k, c in self.engine.iter_chains(begin, end)}
            else:
                base = {
                    k: [(self.durable_version, v)]
                    for k, v in self.engine.iter_range(begin, end)
                }
            keys = set(base)
            keys.update(self._overlay.irange(begin, end, inclusive=(True, False)))
            rows = []
            for k in sorted(keys):
                chain = list(base.get(k, ()))
                chain.extend(self._overlay.get(k, ()))
                rows.append((k, chain))
            return (self.oldest_version, self.version, rows)

    def ingest_shard(self, begin, end, export):
        """Install an ``export_shard`` snapshot (ref: fetchKeys applying
        fetched blocks). Physically clears [begin, end) first so stale
        non-owned data and deletes on the source do not survive. The
        read floor rises to the source's: versions below it were not
        exported, and serving them here would silently miss history —
        TOO_OLD (retryable) is the correct answer, exactly as a version
        older than the window gets everywhere else."""
        oldest, version, rows = export
        with self._mu:
            self.version = max(self.version, version)
            self.oldest_version = max(self.oldest_version, oldest)
            if self.versioned_engine:
                # physically evict any stale pre-move history: a clear
                # would tombstone at the durable version, and the later
                # flush of the ingested (lower-version) chain entries
                # would land AFTER it, corrupting the ascending-order
                # invariant chains rely on
                self.engine.erase_range(begin, end)
            else:
                self.engine.clear_range(begin, end)
            for k in list(self._overlay.irange(begin, end, inclusive=(True, False))):
                del self._overlay[k]
            for k, chain in rows:
                self._overlay[k] = list(chain)
                for v, _ in chain:
                    self._dirty.append((v, k))

    # ───────────────────────────── watches ─────────────────────────────
    def fire_watches_in_range(self, begin, end):
        """Spuriously fire every watch on a key in [begin, end) — called
        when a shard relocates away so watchers re-read from the new
        owner instead of hanging on a storage that stopped receiving the
        key's mutations (ref: watches erroring with wrong_shard_server
        on shard moves; ours wakes instead of erroring)."""
        with self._mu:  # vs concurrent watch() registration / _append firing
            for key in list(self._watches):
                if begin <= key and (end is None or key < end):
                    for w in self._watches.pop(key):
                        w._fire()

    def watch(self, key, seen_value):
        if not self.alive:
            raise err("process_behind")
        with self._mu:
            w = Watch(key, seen_value)
            current = self._lookup(key, self.version)
            if current != seen_value:
                w._fire()
            else:
                self._watches.setdefault(key, []).append(w)
            return w

    def advance_window(self, oldest):
        """Advance the MVCC read floor. Folding old overlay versions into
        the engine is NOT done here — the commit proxy's periodic
        durability pump owns flushing (ref: the storage server's
        updateStorage loop being a separate actor from version updates),
        so the pump can observe real durability lag and feed it to the
        ratekeeper instead of hiding it behind a per-batch flush.

        With a versioned engine the floor also garbage-collects: history
        below it is unreachable, so the engine prunes its chains (ref:
        Redwood trimming page versions that left the MVCC window)."""
        if oldest > self.oldest_version:
            self.oldest_version = oldest
            if self.versioned_engine:
                with self._mu:
                    self.engine.prune(min(oldest, self.durable_version))

    def attach_heatmaps(self, read_heat, write_heat, sample_every=8):
        """Wire the cluster-owned read/write heatmaps into this storage
        (and a recruited replacement: the cluster re-attaches the SAME
        objects, so per-shard heat survives recruitment like the
        registry). The sampling stream is the shared deterministic
        "key-sample" stream — same-seed sims replay the exact draws."""
        from foundationdb_tpu.core import deterministic

        self._read_heat = read_heat
        self._write_heat = write_heat
        self._sample_every = max(1, int(sample_every))
        self._sample_w = float(self._sample_every)
        self._srng = deterministic.rng("key-sample")

    def _sample_read(self, key):
        """Fire path — the countdown hit zero (the decrement lives
        inline at the read sites). Randomized stride (mean ≈
        sample_every) instead of a fixed one: periodic access patterns
        cannot alias with the sampler; weight scales by the rate so heat
        estimates TOTAL accesses, matching the ref's byte-sample
        scaling. The kill switch is checked HERE, once per fire, not
        once per access."""
        self._read_cd = self._srng.randrange(
            1, 2 * self._sample_every + 1)
        # system keys (\xff...) stay out of the workload heatmaps: the
        # status/metacluster machinery reads them on every poll, and an
        # observer that heats what it observes would drown user ranges
        if key < b"\xff" and heatmap_mod.enabled():
            self._read_heat.charge(key, self._sample_w)

    def adopt_metrics(self, registry):
        """Recruitment carryover: the replacement continues the dead
        instance's registry, so storage counters never rewind."""
        if registry is self.metrics:
            return
        registry.absorb(self.metrics)
        self.metrics = registry
        self._m_apply = registry.latency("storage_apply")
        self._m_mutations = registry.counter("mutations_applied")
        self._m_reads = registry.counter("point_reads")
        self._m_range_reads = registry.counter("range_reads")
        self._m_read_batch = registry.latency("read_batch")
        self._m_read_batch_keys = registry.latency("read_batch_keys")
        self._m_read_batches = registry.counter("read_batches")
        self._m_batched_reads = registry.counter("batched_reads")

    def status(self):
        """This role's status RPC payload (leaf of the status doc)."""
        self.metrics.gauge("version").set(self.version)
        self.metrics.gauge("durable_version").set(self.durable_version)
        self.metrics.gauge("durability_lag_versions").set(
            max(0, self.version - self.durable_version)
        )
        return {
            "alive": self.alive,
            "region": self.region,
            "metrics": self.metrics.snapshot(),
        }

