"""Storage server: MVCC versioned reads over an ordered key space.

Ref parity: fdbserver/storageserver.actor.cpp — serves reads at a client's
read version within the 5s MVCC window, applies committed mutations in
version order, resolves key selectors, supports watches. The reference
layers a versioned in-memory tree over a persistent engine; here the
versioned view is a SortedDict of per-key version chains over a pluggable
KeyValueStore (server/kvstore.py) snapshot.
"""

from sortedcontainers import SortedDict

from foundationdb_tpu.core.errors import err
from foundationdb_tpu.core.keys import KeySelector
from foundationdb_tpu.core.mutations import ATOMIC_OPS, Op, apply_atomic


class Watch:
    """Fires when the watched key's value diverges from the seen value.

    Ref: watchValue in storageserver.actor.cpp."""

    def __init__(self, key, seen_value):
        self.key = key
        self.seen_value = seen_value
        self.fired = False
        self._callbacks = []

    def on_fire(self, cb):
        if self.fired:
            cb()
        else:
            self._callbacks.append(cb)

    def _fire(self):
        if not self.fired:
            self.fired = True
            for cb in self._callbacks:
                cb()


class StorageServer:
    def __init__(self, window_versions=5_000_000):
        # key -> list[(version, value_or_None)] ascending; None = tombstone
        self._data = SortedDict()
        self.oldest_version = 0
        self.version = 0  # latest applied
        self.window_versions = window_versions
        self._watches = {}  # key -> list[Watch]

    # ───────────────────────────── writes ──────────────────────────────
    def apply(self, version, mutations):
        """Apply one commit's mutations at ``version`` (monotone order)."""
        if version <= self.version:
            raise ValueError(f"apply out of order: {version} <= {self.version}")
        for m in mutations:
            if m.op is Op.CLEAR_RANGE:
                for k in list(self._data.irange(m.key, m.param, inclusive=(True, False))):
                    self._append(k, version, None)
            elif m.op in (Op.SET, Op.CLEAR):
                self._append(m.key, version, m.param if m.op is Op.SET else None)
            elif m.op in ATOMIC_OPS:
                old = self._read_chain(m.key, version)
                self._append(m.key, version, apply_atomic(m.op, old, m.param))
            else:
                raise ValueError(f"unresolved mutation {m.op} reached storage")
        self.version = version
        self.oldest_version = max(self.oldest_version, version - self.window_versions)

    def _append(self, key, version, value):
        chain = self._data.get(key)
        if chain is None:
            chain = []
            self._data[key] = chain
        chain.append((version, value))
        # prune chain entries older than the window (keep the newest <= oldest)
        if len(chain) > 4:
            cut = 0
            for i, (v, _) in enumerate(chain):
                if v <= self.oldest_version:
                    cut = i
            if cut:
                del chain[:cut]
        for w in self._watches.get(key, []):
            if value != w.seen_value:
                w._fire()
        if self._watches.get(key):
            self._watches[key] = [w for w in self._watches[key] if not w.fired]

    # ───────────────────────────── reads ───────────────────────────────
    def _check_version(self, version):
        if version < self.oldest_version:
            raise err("transaction_too_old")
        if version > self.version:
            raise err("future_version")

    def _read_chain(self, key, version):
        chain = self._data.get(key)
        if not chain:
            return None
        val = None
        for v, x in chain:
            if v <= version:
                val = x
            else:
                break
        return val

    def get(self, key, version):
        self._check_version(version)
        return self._read_chain(key, version)

    def _live_keys(self, begin, end, version, reverse=False):
        it = self._data.irange(begin, end, inclusive=(True, False), reverse=reverse)
        for k in it:
            if self._read_chain(k, version) is not None:
                yield k

    def resolve_selector(self, sel: KeySelector, version):
        """Resolve a key selector to a concrete key (ref: storageserver
        findKey): start at the last live key < (or <=) sel.key, then move
        ``offset`` live keys right. Clamps to b'' / \\xff sentinel."""
        self._check_version(version)
        base_idx = None  # index among live keys, conceptually
        # walk from the reference key
        if sel.or_equal:
            prev = list(self._live_keys(b"", sel.key + b"\x00", version, reverse=True))
        else:
            prev = list(self._live_keys(b"", sel.key, version, reverse=True))
        offset = sel.offset
        if offset > 0:
            start = prev[0] + b"\x00" if prev else b""
            following = self._live_keys(start, b"\xff\xff", version)
            k = None
            for i, kk in enumerate(following, start=1):
                if i == offset:
                    k = kk
                    break
            return k if k is not None else b"\xff"
        else:
            # offset 0 => last-less-than(-or-equal); negative walks left
            idx = -offset
            if idx < len(prev):
                return prev[idx]
            return b""

    def get_range(self, begin_sel, end_sel, version, limit=0, reverse=False):
        """Half-open range read by key selectors. Returns list[(k, v)]."""
        self._check_version(version)
        begin = begin_sel if isinstance(begin_sel, bytes) else self.resolve_selector(begin_sel, version)
        end = end_sel if isinstance(end_sel, bytes) else self.resolve_selector(end_sel, version)
        if begin > end:
            return []
        out = []
        for k in self._live_keys(begin, end, version, reverse=reverse):
            out.append((k, self._read_chain(k, version)))
            if limit and len(out) >= limit:
                break
        return out

    # ───────────────────────────── watches ─────────────────────────────
    def watch(self, key, seen_value):
        w = Watch(key, seen_value)
        current = self._read_chain(key, self.version)
        if current != seen_value:
            w._fire()
        else:
            self._watches.setdefault(key, []).append(w)
        return w

    def advance_window(self, oldest):
        self.oldest_version = max(self.oldest_version, oldest)
