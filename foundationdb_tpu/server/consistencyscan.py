r"""Continuous consistency scan: the cluster audits its own data.

Ref parity: fdbserver/ConsistencyScan.actor.cpp — the reference runs a
dedicated, rate-limited ConsistencyScan role that walks the shard map
forever in bounded batches, reading every replica of every shard at a
pinned version and comparing exactly, persisting its cursor in the
system keyspace so rounds resume across recoveries. (The one-shot
ConsistencyCheck workload — ``server/consistency.py`` here — shares the
same comparison core; this module owns that core so there is exactly
one code path that decides "do these replicas agree".)

Jiffy's snapshot-batched traversal (PAPERS.md) is the scan shape: each
batch reads at ONE pinned read version via the storage shard-copy
surface (``read_range`` — the same heatmap-exempt path data
distribution's fetchKeys uses; the storage sampler never fires on it,
so scanning cannot pollute workload heat), writers are never blocked,
and the pin only lives for a single bounded batch so the MVCC window
stays small.

Four properties the scanner guarantees:

* **No false positives from movement.** A batch that observes replica
  divergence re-reads ONCE against the LIVE shard map at a fresh
  pinned version before declaring corruption — a concurrent
  split/move/recruitment leaves a replica legitimately mid-copy at the
  first pinned version, and the re-read sees the settled truth.
  Availability problems (dead/unreadable replicas mid-recovery) are
  never counted as inconsistencies at all — they retry on a later
  batch.
* **Recovery-proof progress.** The cursor + round count persist in
  ``\xff/consistencyScan/`` through the normal commit pipeline (the
  ``persist_shard_map`` idiom: tlog-durable, recovered like user
  data), and the stats live in the cluster-owned
  ("consistency_scan", 0) registry — a txn-system recovery or a full
  restart resumes the round instead of rewinding it.
* **Deterministic cadence.** ``maybe_scan()`` rides the injected clock
  with jitter from the named "consistency-scan" stream (the FL001
  seam) under the PR 13/19 single-driver protocol: thread-mode
  clusters drive it from a daemon loop, sims pump it from their
  scheduler — never both.
* **Bounded cost.** ``consistency_scan_batch_keys`` bounds one batch,
  ``scan_rate_bytes_per_s`` defers the next batch until the last one's
  bytes have drained, and ``set_enabled(False)`` is the module kill
  switch (BENCH_MODE=scan_smoke measures the enabled-vs-disabled
  delta); the status doc stays readable when disabled.
"""

import collections
import threading

from foundationdb_tpu.core import deterministic
from foundationdb_tpu.utils.trace import SEV_ERROR, SEV_WARN, TraceEvent

SYSTEM_END = b"\xff\xff"  # past user + system keys (engine meta excluded)

# scan position rows: plain system keyspace (replicated everywhere,
# tlog-durable, WAL-recovered) — NOT the virtual \xff\xff space
CURSOR_KEY = b"\xff/consistencyScan/cursor"
ROUND_KEY = b"\xff/consistencyScan/round"

_enabled = True
_enabled_mu = threading.Lock()


def set_enabled(on):
    """Process-wide scanner kill switch (scan_smoke measures the
    delta; fdbcli ``scan on|off`` flips it). The scan document stays
    readable either way."""
    global _enabled
    with _enabled_mu:
        _enabled = bool(on)


def enabled():
    return _enabled


# ── the one batch-compare code path ──────────────────────────────────
# (errors ⊇ divergence: availability problems — dead or unreadable
# replicas — appear only in errors; divergence holds the strings where
# two readable replicas actually disagreed about the data)
BatchResult = collections.namedtuple(
    "BatchResult", "errors divergence keys bytes next_key"
)


def _read_replica(cluster, shard_idx, sid, begin, end, version, limit,
                  errors):
    s = cluster.storages[sid]
    try:
        return s.read_range(begin, end, version, limit=limit)
    except Exception as e:
        # the error lands in the report AND the trace stream: a sim run
        # greps traces for forensics, and an operator's consistencycheck
        # may summarize away the detail (FL005)
        TraceEvent("ConsistencyCheckReadError",
                   severity=SEV_ERROR).detail(
            shard=shard_idx, storage=sid, version=version,
            etype=type(e).__name__, error=str(e)[:200]).log()
        errors.append(
            f"shard {shard_idx} replica {sid} unreadable at "
            f"v{version}: {e}"
        )
        return None


def compare_shard_batch(cluster, shard_idx, begin, end, team, version,
                        limit=None):
    """Read [begin, end) at the pinned ``version`` from every live
    replica in ``team`` and compare exactly — the single comparison
    core shared by the continuous scanner and the one-shot
    ``consistency_check``.

    The first cleanly-readable replica is the reference: its rows pin
    the batch's key window, and when ``limit`` truncates the read,
    every OTHER replica is compared over exactly [begin, last_ref_key)
    — never a limit-truncated tail of its own — so batch boundaries
    can't fabricate missing/extra keys. ``next_key`` is where the next
    batch resumes (None when the reference covered the whole range).
    """
    errors, divergence = [], []
    n_storages = len(cluster.storages)
    live = [sid for sid in team
            if 0 <= sid < n_storages and cluster.storages[sid].alive]
    if not live:
        errors.append(
            f"shard {shard_idx} [{begin!r}, {end!r}) has no live replica"
        )
        return BatchResult(errors, divergence, 0, 0, None)
    ref_sid = ref_rows = None
    rest = []
    for sid in live:
        if ref_sid is not None:
            rest.append(sid)
            continue
        rows = _read_replica(cluster, shard_idx, sid, begin, end,
                             version, limit, errors)
        if rows is not None:
            ref_sid, ref_rows = sid, rows
    if ref_sid is None:
        return BatchResult(errors, divergence, 0, 0, None)
    if limit is not None and len(ref_rows) >= limit:
        batch_end = ref_rows[-1][0] + b"\x00"
        next_key = batch_end
    else:
        batch_end, next_key = end, None
    keys = len(ref_rows)
    nbytes = sum(len(k) + len(v) for k, v in ref_rows)
    for sid in rest:
        rows = _read_replica(cluster, shard_idx, sid, begin, batch_end,
                             version, None, errors)
        if rows is None:
            continue
        nbytes += sum(len(k) + len(v) for k, v in rows)
        if rows == ref_rows:
            continue
        ref_map, got_map = dict(ref_rows), dict(rows)
        missing = sorted(set(ref_map) - set(got_map))[:3]
        extra = sorted(set(got_map) - set(ref_map))[:3]
        diff = sorted(
            k for k in set(ref_map) & set(got_map)
            if ref_map[k] != got_map[k]
        )[:3]
        msg = (
            f"shard {shard_idx} [{begin!r}, {batch_end!r}) replicas "
            f"{ref_sid} vs {sid} diverge at v{version}: "
            f"missing={missing} extra={extra} differing={diff}"
        )
        errors.append(msg)
        divergence.append(msg)
    return BatchResult(errors, divergence, keys, nbytes, next_key)


class ConsistencyScanner:
    """Cluster-owned background replica auditor. Pull-based like the
    LatencyProber: ``maybe_scan()`` fires at most one bounded batch per
    knob interval off the injected clock; thread-mode clusters drive it
    from a daemon loop, sims/tests call it from their own schedule."""

    MAX_ERROR_SAMPLE = 8  # confirmed-inconsistency strings retained

    def __init__(self, cluster):
        self.cluster = cluster
        reg = cluster._role_registry("consistency_scan")
        self._m_rounds = reg.counter("scan_rounds")
        self._m_batches = reg.counter("scan_batches")
        self._m_keys = reg.counter("scan_keys")
        self._m_bytes = reg.counter("scan_bytes")
        self._m_inconsistencies = reg.counter("scan_inconsistencies")
        # divergences the live-map re-read dismissed: each one is a
        # concurrent split/move that would have been a false positive
        self._m_reread_saves = reg.counter("scan_reread_saves")
        self._m_round_ms = reg.gauge("scan_last_round_ms")
        # jittered cadence off the named deterministic stream (FL001):
        # same-seed sims draw the same batches at the same steps
        self._rng = deterministic.rng("consistency-scan")
        # flowlint: shared(single-driver protocol: thread mode scans ONLY from the daemon loop, sims ONLY from their scheduler — never both, one writer at a time)
        self._next_due = None
        # flowlint: shared(advanced only by the single scan driver; status() and the persist path only read it)
        self._cursor = b""
        # flowlint: shared(round-start stamp: written only by the single scan driver, like _cursor)
        self._round_started = None
        self._started_at = deterministic.now()
        self._last_round_at = None
        # flowlint: shared(last-writer-wins breadcrumb; the doctor only polls it)
        self.last_error = None
        # flowlint: shared(bounded sample list, rebound whole by the single scan driver; readers copy)
        self.errors = []  # bounded confirmed-inconsistency sample
        self._stop = threading.Event()
        self._thread = None

    # ── persistence (recovery-proof cursor) ──────────────────────────
    def restore_cursor(self):
        """Re-load the persisted scan position after recovery/restart
        (the registry counters survive recovery by themselves; a full
        restart rebuilds them, so the round count persists too)."""
        s0 = self.cluster.storages[0]
        row = s0.get(CURSOR_KEY, s0.version)
        if row is not None:
            self._cursor = row
        row = s0.get(ROUND_KEY, s0.version)
        if row is not None:
            try:
                behind = int(row) - self._m_rounds.value
            except ValueError:
                behind = 0
            if behind > 0:
                self._m_rounds.inc(behind)

    def _persist_cursor(self):
        """Write cursor + round count to \\xff/consistencyScan/ through
        the normal commit pipeline (the persist_shard_map idiom).
        Best-effort: a failed system commit leaves the previous
        position; the next batch retries."""
        from foundationdb_tpu.core.mutations import Mutation, Op
        from foundationdb_tpu.server.proxy import CommitRequest

        req = CommitRequest(
            read_version=self.cluster.sequencer.committed_version,
            mutations=[
                Mutation(Op.SET, CURSOR_KEY, self._cursor),
                Mutation(Op.SET, ROUND_KEY,
                         b"%d" % self._m_rounds.value),
            ],
            read_conflict_ranges=[], write_conflict_ranges=[],
        )
        try:
            result = self.cluster.commit_proxy.commit(req)
        except Exception:
            return False
        return not isinstance(result, Exception)

    # ── cadence ──────────────────────────────────────────────────────
    def maybe_scan(self):
        """Run one bounded batch if the interval elapsed; returns True
        iff a batch ran. The rate budget stretches the next due time so
        sustained read throughput stays under scan_rate_bytes_per_s."""
        if not enabled() or not self.cluster.knobs.consistency_scan_enabled:
            return False
        interval = self.cluster.knobs.consistency_scan_interval_s
        now = deterministic.now()
        if self._next_due is None:
            # first call arms the schedule with a jittered offset so a
            # fleet of scanners never thunders in step
            self._next_due = now + interval * self._rng.random()
            return False
        if now < self._next_due:
            return False
        self._next_due = now + interval * (0.5 + self._rng.random())
        batch_bytes = self.scan_step()
        rate = self.cluster.knobs.scan_rate_bytes_per_s
        if rate > 0 and batch_bytes:
            self._next_due = max(self._next_due,
                                 now + batch_bytes / rate)
        return True

    # ── one batch ────────────────────────────────────────────────────
    def scan_step(self):
        """One bounded batch at one pinned version: compare the owning
        team's replicas over the cursor's shard, re-read divergence
        against the live map, advance + persist the cursor. Returns the
        bytes read (rate accounting); never raises — a scan must never
        take the cluster down, and failures mid-recovery simply retry
        on a later fire."""
        cluster = self.cluster
        try:
            if self._round_started is None:
                self._round_started = deterministic.now()
            version = cluster.sequencer.committed_version
            smap = cluster.dd.map
            cursor = self._cursor
            i = smap.shard_index(cursor)
            shard_begin, shard_end = smap.shard_range(i)
            end = SYSTEM_END if shard_end is None else shard_end
            begin = max(cursor, shard_begin)
            res = compare_shard_batch(
                cluster, i, begin, end, smap.teams[i], version,
                limit=cluster.knobs.consistency_scan_batch_keys,
            )
            self._m_batches.inc()
            self._m_keys.inc(res.keys)
            self._m_bytes.inc(res.bytes)
            confirmed = []
            if res.divergence:
                confirmed = self._recheck(begin, res.next_key or end)
            if confirmed:
                self._m_inconsistencies.inc(len(confirmed))
                self.errors = (self.errors
                               + confirmed)[-self.MAX_ERROR_SAMPLE:]
                for msg in confirmed:
                    TraceEvent("ConsistencyScanCorruption",
                               severity=SEV_ERROR).detail(
                        error=msg[:300]).log()
            if res.next_key is not None:
                new_cursor = res.next_key
            elif shard_end is None:
                new_cursor = None  # past the last shard
            else:
                new_cursor = shard_end
            if new_cursor is None or new_cursor >= SYSTEM_END:
                self._finish_round()
            else:
                self._cursor = new_cursor
            self._persist_cursor()
            self.last_error = None
            return res.bytes
        except Exception as e:
            self.last_error = f"{type(e).__name__}: {str(e)[:200]}"
            TraceEvent("ConsistencyScanStepError",
                       severity=SEV_WARN).detail(
                etype=type(e).__name__, error=str(e)[:200]).log()
            return 0

    def _recheck(self, begin, end):
        """The false-positive guard: re-read [begin, end) ONCE against
        the LIVE shard map at a fresh pinned version before declaring
        corruption. A concurrent split/move leaves a replica
        legitimately mid-copy at the first pinned version; real
        corruption survives the re-read. Unconfirmable (unreadable
        mid-recovery) divergence is dismissed too — the range rescans
        on a later round."""
        cluster = self.cluster
        try:
            version = cluster.sequencer.committed_version
            smap = cluster.dd.map
            confirmed = []
            for j in smap.shards_overlapping(begin, end):
                b, e = smap.shard_range(j)
                e = SYSTEM_END if e is None else e
                res = compare_shard_batch(
                    cluster, j, max(b, begin), min(e, end),
                    smap.teams[j], version,
                )
                confirmed.extend(res.divergence)
            if not confirmed:
                self._m_reread_saves.inc()
            return confirmed
        except Exception:
            self._m_reread_saves.inc()
            return []

    def _finish_round(self):
        now = deterministic.now()
        started = (self._round_started
                   if self._round_started is not None else now)
        self._m_round_ms.set(round((now - started) * 1000, 3))
        self._m_rounds.inc()
        self._round_started = None
        self._last_round_at = now
        self._cursor = b""

    # ── background driver (thread-mode clusters only) ────────────────
    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="consistency-scan", daemon=True
        )
        self._thread.start()

    def _loop(self):
        interval = self.cluster.knobs.consistency_scan_interval_s
        while not self._stop.wait(interval):
            try:
                self.maybe_scan()
            except Exception as e:
                # the scanner must never take the cluster down — but a
                # broken scan is forensics-worthy, not silence
                TraceEvent("ConsistencyScanLoopError",
                           severity=SEV_ERROR).detail(error=repr(e))
                self.last_error = repr(e)

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)

    # ── reporting ────────────────────────────────────────────────────
    def status(self):
        """The ``cluster.consistency_scan`` document — JSON-safe and
        byte-identical across same-seed sims (cursor as hex, every
        stamp off the injected clock)."""
        smap = self.cluster.dd.map
        cursor = self._cursor
        progress = (
            round(smap.shard_index(cursor) * 100.0 / max(1, len(smap)), 2)
            if cursor else 0.0
        )
        # age of the last COMPLETED round (seconds, injected clock);
        # before any round completes, age since the scanner was built —
        # either way a stalled scanner's age grows and the doctor's
        # --scan-max-round-age-s SLO catches it
        now = deterministic.now()
        base = (self._last_round_at
                if self._last_round_at is not None else self._started_at)
        return {
            "enabled": enabled()
            and bool(self.cluster.knobs.consistency_scan_enabled),
            "round": self._m_rounds.value,
            "progress_pct": progress,
            "cursor": cursor.hex(),
            "batches": self._m_batches.value,
            "keys_scanned": self._m_keys.value,
            "bytes_scanned": self._m_bytes.value,
            "last_round_ms": self._m_round_ms.value,
            "round_age_s": round(now - base, 6),
            "inconsistencies": self._m_inconsistencies.value,
            "reread_saves": self._m_reread_saves.value,
            "last_error": self.last_error,
            "errors": list(self.errors),
        }
