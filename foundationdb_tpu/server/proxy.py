"""Commit proxy: batches client commits through resolution to the log.

Ref parity: fdbserver/CommitProxyServer.actor.cpp commitBatch() — the
pipeline is getVersion → resolve → tlog push → reply. Client commits
accumulate into a batch; the whole batch shares one commit version. The
TPU resolver makes large batches *cheaper* per txn, so the proxy's job is
to keep batches full (the opposite pressure from the reference, whose
resolver cost grows with batch size).
"""

import threading

from foundationdb_tpu.core.commit import CommitRequest  # noqa: F401  (re-export)
from foundationdb_tpu.core.errors import FDBError, err
from foundationdb_tpu.core.mutations import (
    Mutation, Op, substitute_versionstamp,
)
from foundationdb_tpu.core.status import COMMITTED, CONFLICT, TOO_OLD
from foundationdb_tpu.resolver.resolver import ResolverDown
from foundationdb_tpu.resolver.skiplist import TxnRequest
from foundationdb_tpu.server.sequencer import SequencerDown
from foundationdb_tpu.server.tlog import TLogDown
from foundationdb_tpu.utils import deviceprofile
from foundationdb_tpu.utils import heatmap as heatmap_mod
from foundationdb_tpu.utils import lockdep
from foundationdb_tpu.utils import metrics as metrics_mod
from foundationdb_tpu.utils import span as span_mod


class GateTimeout(Exception):
    """A gate turn no one will take (a peer proxy died between its
    grant and its advance): the fleet is wedged and only a txn-system
    recovery — which rebuilds the gates — can unwedge it. Callers map
    this to a retryable 1021 and mark the proxy dead so the failure
    monitor runs that recovery; it must never escape to a client."""


class VersionGate:
    """Version-ordered turnstile for a commit-proxy FLEET (ref: the
    sequencer's prevVersion chaining + the resolvers/tlogs processing
    batches in version order). A batch granted (prev, v) may only pass
    once every earlier grant has passed: ``enter(prev)`` blocks until
    the gate's frontier reaches ``prev``; ``advance(v)`` moves it. Two
    gates order the two stateful pipeline stages independently (resolve
    history; log+storage apply), so proxy B packs and routes while
    proxy A resolves — the fleet pipelines, the state stays serial."""

    def __init__(self, start, timeout=60.0):
        self._v = start
        self.timeout = timeout
        self._cond = lockdep.condition("VersionGate._cond")

    def enter(self, prev, timeout=None):
        with self._cond:
            if not self._cond.wait_for(
                lambda: self._v >= prev,
                self.timeout if timeout is None else timeout,
            ):
                raise GateTimeout(
                    f"version gate stuck at {self._v}, waiting for {prev}"
                )

    def advance(self, v):
        with self._cond:
            if v > self._v:
                self._v = v
            self._cond.notify_all()


class _PipelinedGroup:
    """One backlog group mid-pipeline: versions granted, txns packed,
    resolve dispatched lazily (stage A+B done). ``commit_batches_finish``
    completes stage C. A group that failed in begin carries its
    precomputed ``results_list`` plus whether its grant's gate turns are
    still owed; ``resolve_s``/``apply_s`` are stage-C timings the
    batcher folds into its StageStats."""

    __slots__ = ("request_batches", "metas", "handle", "first_prev",
                 "last_cv", "granted", "results_list", "error",
                 "resolve_s", "apply_s", "trace_ctx", "plans")

    def __init__(self, request_batches):
        self.request_batches = request_batches
        self.metas = None
        # per-batch SchedulePlans (abort-aware scheduling): finish maps
        # position-ordered results back to request order through these
        self.plans = None
        self.handle = None
        self.first_prev = self.last_cv = None
        self.granted = False
        self.results_list = None
        self.error = None
        self.resolve_s = 0.0
        self.apply_s = 0.0
        # the group's first sampled SpanContext, scanned ONCE in begin
        # (the batcher's stage spans reuse it — re-scanning the whole
        # group per stage was a measured hot-path cost)
        self.trace_ctx = None


class CommitProxy:
    def __init__(self, sequencer, resolvers, tlog, storages, knobs,
                 ratekeeper=None, dd=None, change_feeds=None,
                 resolve_gate=None, log_gate=None, metrics=None,
                 heatmap=None, regions=None, fanout_profile=None):
        self.alive = True
        # lane-balance instrument for the legacy host fan-out (clip
        # loop below): per-sub-batch entry counts feed the same
        # lane_skew_pct rollup the mesh router fills at split time.
        # The cluster hands its resolver-0 DeviceProfile so the counts
        # land in the standard device doc even for host (cpu/native)
        # resolver fleets, which carry no profile of their own.
        self._fanout_profile = fanout_profile
        # multi-region replication (server/region.py RegionReplicator):
        # in sync satellite mode the finalize tail pushes each batch to
        # the remote region BEFORE acknowledging it. The cluster swaps
        # this attribute when regions are (de)configured — read fresh
        # per batch, never cached.
        self.regions = regions
        # per-role metrics (ref: Stats.h CounterCollection on the commit
        # proxy). The cluster hands recovery incarnations the SAME
        # registry, so counters survive recruitment without rewinding;
        # abort counters are keyed by error class (_note_abort).
        self.metrics = metrics if metrics is not None \
            else metrics_mod.MetricsRegistry("commit_proxy")
        self._m_committed = self.metrics.counter("txn_committed")
        self._m_batches = self.metrics.counter("commit_batches")
        self._abort_counters = {}
        # workload attribution (utils/heatmap.py): the cluster-owned
        # conflict heatmap this incarnation charges at its abort-
        # fabrication site (None = sampling off), plus lazy per-tag
        # outcome counters in the role registry (tag_committed_x, ...)
        # so recovery absorption carries them like any other counter
        self.conflict_heat = heatmap
        self._tag_counters = {}
        # commit_e2e spans: recorded HERE for bare (sync) deployments;
        # a batching wrapper claims ownership at construction and
        # records the wider submit→settle span instead (queue included)
        self.spans_owned_externally = False
        self._m_e2e = self.metrics.latency("commit_e2e")
        # fleet ordering (None when this proxy is the whole fleet)
        self.resolve_gate = resolve_gate
        self.log_gate = log_gate
        self.sequencer = sequencer
        self.resolvers = resolvers  # list; key-range sharded when >1
        self.tlog = tlog
        self.storages = storages
        self.knobs = knobs
        self.ratekeeper = ratekeeper
        self.dd = dd  # data distribution byte accounting
        self.change_feeds = change_feeds  # ChangeFeedRegistry | None
        self.commit_count = 0
        self.conflict_count = 0
        # commit pack-path observability (ISSUE 3): how many request
        # batches packed columnar vs legacy, and the flat bytes moved —
        # stage_summary()/bench lines report these per run
        self.pack_flat_batches = 0
        self.pack_legacy_batches = 0
        self.pack_bytes_total = 0
        # abort-aware batch scheduling (server/scheduler.py, knob
        # commit_batch_scheduling): plain totals ride stage_summary /
        # bench lines even with the metrics kill switch off; the
        # registry counters feed status rollups
        self.sched_batches = 0
        self.sched_reordered_total = 0
        self.sched_deferred_total = 0
        self._m_sched_reordered = self.metrics.counter("sched_reordered")
        self._m_sched_deferred = self.metrics.counter("sched_deferred")
        # Concurrent client threads may drive the synchronous proxy
        # directly (no batching wrapper): the pipeline mutates shared
        # state (donated resolver buffers, tlog order, storage overlay),
        # so commits serialize here. Reentrant: the lock path re-enters
        # commit_batch for lock-aware sub-batches. Uncontended cost is
        # noise; deterministic sims are single-threaded so ordering is
        # unchanged. (Ref: the proxy's commit path is one actor.)
        self._commit_mu = lockdep.rlock("CommitProxy._commit_mu")
        self._batches_since_pump = 0
        self.pump_interval = 64  # batches between flush + ratekeeper rounds
        self.resolver_bounds = None  # n-1 split keys; None = static split
        self._pool = None  # lazy thread pool for concurrent sub-resolves
        self.update_resolver_ranges(fence=False)

    def _note_abort(self, name, n=1):
        """Per-error-class abort accounting (ref: the reference's
        per-reason txn counters in status json): one counter per error
        name — conflicts, too-old, unknown-result, admission rejects —
        so contention is attributable, not one lump."""
        if n <= 0 or not metrics_mod.enabled():
            return
        c = self._abort_counters.get(name)
        if c is None:
            c = self._abort_counters[name] = self.metrics.counter(
                f"abort_{name}"
            )
        c.inc(n)

    def _note_tags(self, outcome, tags):
        """Per-tag outcome accounting (ref: the per-tag counters
        TagThrottle reads): every tagged commit/abort/conflict lands in
        a ``tag_{outcome}_{tag}`` counter."""
        if not tags or not metrics_mod.enabled():
            return
        for t in tags:
            key = (outcome, t)
            c = self._tag_counters.get(key)
            if c is None:
                c = self._tag_counters[key] = self.metrics.counter(
                    f"tag_{outcome}_{t}"
                )
            c.inc()

    def _charge_conflict(self, req):
        """Charge the conflict heatmap for one rejected transaction at
        its fabrication site. On the flat path the charged bucket keys
        are the client's raw limb ENTRIES sliced straight out of the
        request blobs — order-isomorphic to keys, zero decode (the same
        trick as server/scheduler.py); legacy requests pay one cheap
        entry encode per key, abort path only. The abort's unit weight
        is split across its charged read entries so total heat counts
        ABORTS (the attribution tests' denominator), not read width."""
        hm = self.conflict_heat
        if hm is None or not heatmap_mod.enabled():
            return
        from foundationdb_tpu.core import flatpack

        entries = []
        f = req.flat_conflicts
        if f is not None:
            w = flatpack.entry_width(f.num_limbs)
            blob = f.read_point_blob
            for o in range(0, min(len(blob), 8 * w), w):
                entries.append(blob[o: o + w])
            rblob = f.read_range_blob  # pairs: charge each range BEGIN
            for o in range(0, min(len(rblob), 16 * w), 2 * w):
                entries.append(rblob[o: o + w])
            if not entries:  # read-free: charge the write set instead
                blob = f.write_point_blob
                for o in range(0, min(len(blob), 8 * w), w):
                    entries.append(blob[o: o + w])
        else:
            limbs = self.knobs.key_limbs
            ranges = req.read_conflict_ranges or req.write_conflict_ranges
            for begin, _end in ranges[:8]:
                e = flatpack.encode_entry(begin, limbs)
                if e is not None:  # over-capacity keys stay unsampled
                    entries.append(e)
        if entries:
            wgt = 1.0 / len(entries)
            for e in entries:
                hm.charge(e, wgt)

    def _note_result_errors(self, results):
        """Tally FDBError entries of a finished result list by class."""
        if not metrics_mod.enabled():
            return
        for r in results:
            if isinstance(r, FDBError):
                self._note_abort(r.description)

    def status(self):
        """This role's status RPC payload: liveness + metrics snapshot
        (the per-process leaf of the aggregated status document)."""
        return {"alive": self.alive, "metrics": self.metrics.snapshot()}

    def update_resolver_ranges(self, fence=True):
        """Derive each resolver's key range from the LIVE DD shard map,
        weighting by sampled shard bytes so resolver load tracks actual
        write traffic (ref: the keyResolvers map the proxies maintain
        from keyServers). Falls back to a static first-byte split until
        the map has enough shards to cut n balanced ranges. The cluster
        calls this after every DD rebalance round and at recovery.

        Moving a boundary makes conflict history recorded under the OLD
        split unreachable (a key's writes live in the resolver that used
        to own it), so a bounds change REBUILDS the resolvers fenced at
        the current committed version — in-flight transactions get
        TOO_OLD and retry with fresh reads, exactly like the reference,
        where resolver ranges only change through a fencing recovery.
        ``fence=False`` is for construction, when no history exists yet.
        """
        n = len(self.resolvers)
        if n == 1:
            return
        smap = self.dd.map if self.dd is not None else None
        if smap is None or len(smap) < n:
            new_bounds = None  # static split
        else:
            weights = [s + 1 for s in smap.sizes]  # +1: empty shards count
            total = sum(weights)
            bounds, acc = [], 0
            for i in range(len(smap) - 1):
                acc += weights[i]
                if acc >= (len(bounds) + 1) * total / n and len(bounds) < n - 1:
                    bounds.append(smap.boundaries[i + 1])
            new_bounds = bounds if len(bounds) == n - 1 else None
        if new_bounds != self.resolver_bounds and fence:
            cv = self.sequencer.committed_version
            for i in range(n):
                self.resolvers[i] = self.resolvers[i].respawn(cv)
        self.resolver_bounds = new_bounds

    def commit(self, request):
        """Single-transaction batch (the synchronous client path)."""
        return self.commit_batch([request])[0]

    def commit_batch(self, requests):
        """Resolve and commit a batch; returns per-request (version|FDBError).

        All requests share one commit version, like the reference's
        commitBatch. Mutations of accepted txns are pushed to the tlog in
        batch order and applied to storage before replying, so a
        subsequent GRV observes them (external consistency).
        """
        if not requests:
            return []
        if not self.alive or not self.sequencer.alive:
            # the proxy (or the version authority behind it) is dead:
            # honest 1021 — a request may have been in flight when the
            # process died; clients retry and the failure monitor
            # recruits a new transaction-system generation (ref: proxy
            # death surfacing as broken connections → 1021)
            self._note_abort("commit_unknown_result", len(requests))
            return [
                FDBError.from_name("commit_unknown_result")
                for _ in requests
            ]
        t0 = None if self.spans_owned_externally \
            or not metrics_mod.enabled() else metrics_mod.now()
        try:
            with self._commit_mu:
                return self._commit_batch_locked(requests)
        except GateTimeout:
            return self._gate_wedged(len(requests))
        finally:
            if t0 is not None:
                self._note_e2e(t0, len(requests))

    def _gate_wedged(self, n):
        """A gate turn went unclaimed (peer died between grant and
        advance): this generation of the fleet cannot make progress.
        Mark this proxy dead so the failure monitor's next round runs a
        txn-system recovery (fresh gates), and answer honest 1021s —
        the batch's fate is unknown until the new generation fences."""
        self.kill()
        self._note_abort("commit_unknown_result", n)
        return [
            FDBError.from_name("commit_unknown_result") for _ in range(n)
        ]

    def _partition_rejects(self, requests, reject_fn):
        """Per-request admission gate: ``reject_fn(request)`` returns an
        error name (rejected) or None (passing); passing requests
        commit as a sub-batch. Returns merged results, or None when
        nothing was rejected (caller continues with the full batch)."""
        results = [None] * len(requests)
        passing = []
        for i, r in enumerate(requests):
            bad = reject_fn(r)
            if bad is None:
                passing.append((i, r))
            else:
                self._note_abort(bad)
                results[i] = FDBError.from_name(bad)
        if len(passing) == len(requests):
            return None
        if passing:
            try:
                # sub-batches re-enter past the dedupe: their requests
                # already passed it this very call
                sub = self._commit_batch_admitted([r for _, r in passing])
            except GateTimeout:
                # only the sub-batch's fate is unknown: the definitive
                # rejections already in ``results`` must stand (a known
                # not-committed must never degrade to maybe-committed)
                sub = self._gate_wedged(len(passing))
            for (i, _), res in zip(passing, sub):
                results[i] = res
        return results

    @staticmethod
    def _tenant_mode_violation(mode, mutations):
        """Structural tenant-mode check by KEY RANGE: tenant data lives
        in [\xfd, \xfe), plain user data in [, \xfd) ∪ [\xfe, \xff),
        system (mode-exempt) in [\xff, ...). CLEAR_RANGE is judged by
        its whole [key, param) span — a range straddling the boundary
        violates whichever space the mode forbids."""
        for m in mutations:
            if m.key >= b"\xff":
                continue
            if m.op == Op.CLEAR_RANGE:
                b, e = m.key, min(m.param, b"\xff")
                touches_tenant = b < b"\xfe" and e > b"\xfd"
                touches_plain = b < b"\xfd" or e > b"\xfe"
            else:
                touches_tenant = m.key.startswith(b"\xfd")
                touches_plain = not touches_tenant
            if mode == "required" and touches_plain:
                return "tenant_name_required"
            if mode == "disabled" and touches_tenant:
                return "tenants_disabled"
        return None

    def _idmp_lookup(self, idempotency_id):
        """The committed version recorded for ``idempotency_id``, or
        None. Read from any live storage's system keyspace (replicated
        everywhere) at its latest version — every earlier commit through
        this serialized pipeline is visible there."""
        from foundationdb_tpu.core import systemdata

        key = systemdata.idmp_key(idempotency_id)
        for s in self.storages:
            if s.alive:
                row = s.get(key, s.version)
                return None if row is None else \
                    systemdata.unpack_version(row)
        return None

    def _pin_idmp_rv(self, requests):
        """Assign the lazy read version of read-free id-CARRYING
        requests BEFORE their dedupe lookup runs. The lookup and the
        OCC read conflict on the idmp row (_idmp_point) together cover
        every interleaving with a concurrently-committing original only
        if rv is fixed first: an original visible before the pin is
        caught by the lookup (apply precedes report_committed, so the
        row is readable at rv); one landing after has cv > rv and the
        retry's idmp read range conflicts. Pinning here means these
        requests skip the constrained-budget admission gate's lazy-rv
        charge — acceptable: id-carrying blind writes are rare and the
        alternative is a double-apply window."""
        for reqs in requests:
            for r in reqs:
                if (r.read_version is None
                        and getattr(r, "idempotency_id", None)):
                    r.read_version = self.sequencer.committed_version

    def _dedupe_idempotent(self, requests):
        """Proxy-side exactly-once (ref: IdempotencyId — ours is checked
        AT the proxy, which closes the client-check's resubmit race:
        commits serialize through this pipeline, so by the time a retry
        runs, its original either applied — id row visible — or never
        will; the OCC conflict ranges _idmp_point declares extend the
        guarantee across fleet members and pipeline groups). Returns
        merged results, or None when nothing matched."""
        self._pin_idmp_rv([requests])
        results = [None] * len(requests)
        passing = []
        for i, r in enumerate(requests):
            v = (self._idmp_lookup(r.idempotency_id)
                 if getattr(r, "idempotency_id", None) else None)
            if v is None:
                passing.append((i, r))
            else:
                self.metrics.counter("idmp_dedupe_hits").inc()
                results[i] = v  # the ORIGINAL commit's version: success
        if len(passing) == len(requests):
            return None
        if passing:
            sub = self._commit_batch_admitted([r for _, r in passing])
            for (i, _), res in zip(passing, sub):
                results[i] = res
        return results

    def _commit_batch_locked(self, requests):
        if any(getattr(r, "idempotency_id", None) for r in requests):
            out = self._dedupe_idempotent(requests)
            if out is not None:
                return out
        return self._commit_batch_admitted(requests)

    def _commit_batch_admitted(self, requests):
        """The batch pipeline past the idempotency dedupe (every entry
        route runs the dedupe exactly once before landing here)."""
        rk = self.ratekeeper
        if rk is not None and rk.target_tps < rk.UNLIMITED_TPS:
            # rv-None requests skipped the GRV (read-free fast path);
            # under a CONSTRAINED budget they pay admission here
            # instead — same token bucket, same retryable 1037. The
            # gate assigns the rv on admission, so the sub-batch
            # re-entry through _partition_rejects cannot double-charge.
            rv_now = self.sequencer.committed_version

            def gate(r):
                if r.read_version is not None:
                    return None
                if rk.admit():
                    r.read_version = rv_now
                    return None
                return "process_behind"

            out = self._partition_rejects(requests, gate)
            if out is not None:
                return out
        lock_uid = getattr(self, "lock_uid", None)
        if lock_uid is not None:
            # database locked (ref: lockDatabase / error 1038): only
            # lock-aware transactions pass
            out = self._partition_rejects(
                requests,
                lambda r: None if getattr(r, "lock_aware", False)
                else "database_locked",
            )
            if out is not None:
                return out
        # tenant-mode enforcement (ref: TenantMode in
        # DatabaseConfiguration) — see _tenant_mode_violation
        mode = getattr(self, "tenant_mode", "optional")
        if mode != "optional":
            out = self._partition_rejects(
                requests,
                lambda r: self._tenant_mode_violation(mode, r.mutations),
            )
            if out is not None:
                return out
        try:
            prev, cv = self.sequencer.next_commit_versions(1)[0]
        except SequencerDown:
            # the kill raced past the entry check (TOCTOU): same honest
            # 1021 — a raw exception here would strand batcher futures
            self._note_abort("commit_unknown_result", len(requests))
            return [
                FDBError.from_name("commit_unknown_result")
                for _ in requests
            ]
        window = max(0, cv - self.knobs.max_read_transaction_life_versions)
        # past every admission gate: reorder for fewer self-inflicted
        # aborts (results are mapped back to request order at return)
        requests, plan = self._maybe_schedule(requests)
        try:
            txns = self._build_txns(requests)
        except BaseException:
            # the grant happened but neither gate was consumed: skip
            # both turns or every successor waits on a turn no one
            # will take (advisor r4: a wedged gate never self-heals)
            self._skip_turns_quiet(prev, cv)
            raise
        # ambient trace context for the resolver's scan span: the first
        # sampled member's commit span is the parent (over the wire the
        # context arrived inside the CommitRequest, so the handler
        # thread has no ambient one to inherit)
        rctx = span_mod.first_request_context(requests)
        try:
            if rctx is not None:
                prior_ctx = span_mod.set_current(rctx)
                try:
                    statuses = self._resolve_ordered(txns, cv, window,
                                                     prev)
                finally:
                    span_mod.set_current(prior_ctx)
            else:
                statuses = self._resolve_ordered(txns, cv, window, prev)
        except ResolverDown:
            # resolution never ran: definitively not committed (1020,
            # retryable without 1021 disambiguation); the failure monitor
            # recruits a fenced replacement resolver. The granted version
            # still consumes its log turn or the fleet would deadlock —
            # quietly, so a wedged gate cannot replace this KNOWN
            # outcome with blanket 1021s.
            self._skip_turns_quiet(prev, cv)
            self._note_abort("not_committed", len(requests))
            return [FDBError.from_name("not_committed") for _ in requests]
        except GateTimeout:
            raise
        except BaseException:
            # _resolve blew up mid-flight: the resolve gate's finally
            # already advanced (its quiet skip is a no-op), but the
            # log-gate turn is still owed
            self._skip_turns_quiet(prev, cv)
            raise
        results = self._finalize_batch(requests, txns, statuses, cv,
                                       window, prev,
                                       traced=rctx is not None, plan=plan)
        return plan.restore(results) if plan is not None else results

    def _resolve_ordered(self, txns, cv, window, prev):
        """Resolution in global version order: conflict history is
        stateful, so the fleet's batches enter it exactly in grant
        order (ref: Resolver.actor.cpp queuing requests by sequence)."""
        if self.resolve_gate is None:
            return self._resolve(txns, cv, window)
        self.resolve_gate.enter(prev)
        try:
            return self._resolve(txns, cv, window)
        finally:
            # advance even on failure: the version is consumed either way
            self.resolve_gate.advance(cv)

    def _skip_turns_quiet(self, prev, cv):
        """Consume a failed batch's turns at BOTH gates without doing
        its work: successors must never wait on a turn no one will
        take. Each skip still waits for order (advancing early would
        let a LATER version pass before an EARLIER one logged), but
        QUIETLY — called from failure handlers, a wedged gate must not
        replace the outcome being propagated (a definitive 1020, or a
        root-cause exception that would otherwise be retried as a
        silent 1021 forever) nor abort before the second gate's skip.
        The gate damage heals the same way either way — this proxy
        marks itself dead and the failure monitor's txn-system recovery
        rebuilds fresh gates. Once one gate proves wedged the rest get
        a zero wait: the dead peer never advanced either gate, and a
        second full timeout only delays the root cause (and the
        recovery's quiesce) for nothing."""
        wedged = False
        for gate in (self.resolve_gate, self.log_gate):
            if gate is None:
                continue
            try:
                gate.enter(prev, timeout=0.0 if wedged else None)
                gate.advance(cv)
            except GateTimeout:
                wedged = True
                self.kill()

    def commit_batches(self, request_batches):
        """Commit a BACKLOG of batches: each gets its own commit version,
        resolution for all of them rides one resolver dispatch
        (Resolver.resolve_many's scanned path), then each batch finalizes
        in order. Semantically identical to sequential commit_batch calls
        — this is the throughput path when commits outrun the link to
        the chip (ref: the proxy pipelining resolution across batches)."""
        if (len(self.resolvers) != 1 or not self.alive
                or not self.sequencer.alive):
            # per-batch route: commit_batch records its own spans
            return [self.commit_batch(reqs) for reqs in request_batches]
        t0 = None if self.spans_owned_externally \
            or not metrics_mod.enabled() else metrics_mod.now()
        try:
            return self._commit_batches_outer(request_batches)
        finally:
            if t0 is not None:
                # one span per backlog group: its batches reply together
                self._note_e2e(
                    t0, sum(len(r) for r in request_batches))

    def _note_e2e(self, t0, n_txns):
        """Record the commit_e2e band AND, when tracing is enabled and
        the window outlived ``tracing_slow_commit_ms``, the per-window
        slow-commit promotion span — both from the same stamps (the
        sync-deployment twin of the batcher's _record_span)."""
        end = metrics_mod.now()
        dur = max(0.0, end - t0)
        self._m_e2e.record(dur)
        if (self.knobs.tracing_sample_rate > 0.0
                and dur * 1e3 >= self.knobs.tracing_slow_commit_ms):
            span_mod.slow_window_span(t0, end, txns=n_txns)

    def _commit_batches_outer(self, request_batches):
        try:
            with self._commit_mu:
                if getattr(self, "lock_uid", None) is not None:
                    # checked UNDER the mutex: a lock landing while this
                    # backlog queued must fence it exactly as commit_batch
                    # would (the per-batch path re-checks per batch).
                    # Results accumulate per batch: a wedge part-way
                    # through must not turn KNOWN outcomes (durable
                    # commits, definitive rejections) into 1021s —
                    # only the unprocessed remainder is unknown.
                    out = []
                    try:
                        for reqs in request_batches:
                            out.append(self._commit_batch_locked(reqs))
                    except GateTimeout:
                        for reqs in request_batches[len(out):]:
                            out.append(self._gate_wedged(len(reqs)))
                    return out
                return self._commit_batches_locked(request_batches)
        except GateTimeout:
            return [
                self._gate_wedged(len(reqs)) for reqs in request_batches
            ]

    def _commit_batches_locked(self, request_batches):
        # the pipelined backlog must dedupe too — 1021 retries are MOST
        # likely to arrive on exactly this throughput path. The scan
        # costs one in-memory storage get per id-CARRYING request
        # (id-free traffic pays nothing); a matched id — rare, only a
        # real 1021 retry — drops the backlog to the per-batch route,
        # whose dedupe answers the duplicate its original version.
        # Degrading the whole backlog on a match trades throughput for
        # simplicity exactly once per retry, not steady-state.
        rk = self.ratekeeper
        self._pin_idmp_rv(request_batches)
        if any(getattr(r, "idempotency_id", None)
               and self._idmp_lookup(r.idempotency_id) is not None
               for reqs in request_batches for r in reqs) or (
            # a constrained budget gates rv-None requests at admission
            # (the per-batch path runs that gate); overload throughput
            # is moot, so losing the backlog pipelining there is fine
            rk is not None and rk.target_tps < rk.UNLIMITED_TPS
            and any(r.read_version is None
                    for reqs in request_batches for r in reqs)
        ):
            out = []
            try:
                for reqs in request_batches:
                    out.append(self._commit_batch_locked(reqs))
            except GateTimeout:
                # known per-batch outcomes stand; only the remainder is
                # unknown (same contract as the locked-backlog branch)
                for reqs in request_batches[len(out):]:
                    out.append(self._gate_wedged(len(reqs)))
            return out
        try:
            # the whole backlog's versions in ONE chained grant: no other
            # proxy's batch can land inside this run, so the backlog is
            # contiguous in the global order and one gate span covers it
            pairs = self.sequencer.next_commit_versions(len(request_batches))
        except SequencerDown:
            self._note_abort("commit_unknown_result",
                             sum(len(r) for r in request_batches))
            return [
                [FDBError.from_name("commit_unknown_result") for _ in reqs]
                for reqs in request_batches
            ]
        first_prev, last_cv = pairs[0][0], pairs[-1][1]
        try:
            metas = []
            plans = []
            for reqs, (prev, cv) in zip(request_batches, pairs):
                window = max(
                    0, cv - self.knobs.max_read_transaction_life_versions
                )
                reqs, plan = self._maybe_schedule(reqs)
                plans.append(plan)
                metas.append((reqs, self._build_txns(reqs), cv, window))
        except BaseException:
            # grant made, gates untouched: consume the whole span's
            # turns or the rest of the fleet wedges behind it
            self._skip_turns_quiet(first_prev, last_cv)
            raise
        gctx = span_mod.first_request_context(
            r for reqs in request_batches for r in reqs
        )
        if self.resolve_gate is not None:
            self.resolve_gate.enter(first_prev)
        try:
            prior_ctx = span_mod.set_current(gctx) \
                if gctx is not None else None
            try:
                statuses_list = self.resolvers[0].resolve_many(
                    [(txns, cv, window) for _, txns, cv, window in metas]
                )
            finally:
                if gctx is not None:
                    span_mod.set_current(prior_ctx)
        except ResolverDown:
            self._skip_turns_quiet(first_prev, last_cv)
            self._note_abort("not_committed",
                             sum(len(r) for r in request_batches))
            return [
                [FDBError.from_name("not_committed") for _ in reqs]
                for reqs in request_batches
            ]
        except BaseException:
            # resolve_many itself never touches a gate, so anything here
            # is a resolver-internal root cause: skip the owed log turn
            # quietly and let IT propagate
            self._skip_turns_quiet(first_prev, last_cv)
            raise
        finally:
            if self.resolve_gate is not None:
                self.resolve_gate.advance(last_cv)
        if self.log_gate is not None:
            self.log_gate.enter(first_prev)
        try:
            out = []
            for (reqs, txns, cv, window), statuses, plan in zip(
                    metas, statuses_list, plans):
                res = self._finalize_batch(reqs, txns, statuses, cv,
                                           window, prev=None,
                                           traced=gctx is not None,
                                           plan=plan)
                out.append(plan.restore(res) if plan is not None else res)
            return out
        finally:
            if self.log_gate is not None:
                self.log_gate.advance(last_cv)

    @staticmethod
    def _idmp_point(r):
        """The idmp system row an id-carrying request writes (and must
        read-conflict on), or None. Declaring both conflict ranges on
        that row makes OCC serialize a retry against its own original
        even when the two land on DIFFERENT fleet members (or different
        pipeline groups) concurrently: whichever resolves second sees
        the other's write over its read and gets 1020, retries, and the
        dedupe then answers the original's version (ADVICE r5: a
        read-free id-carrying retry could double-apply)."""
        iid = getattr(r, "idempotency_id", None)
        if not iid:
            return None
        from foundationdb_tpu.core import systemdata

        return systemdata.idmp_key(iid)

    # ── pipelined backlog (server/batcher.py's bounded pipeline) ─────
    # The serial _commit_batches_locked split into stages so the batcher
    # can keep commit_pipeline_depth groups in flight: stage A+B
    # (commit_batches_begin — version grant, host packing, gate-ordered
    # LAZY resolve dispatch) run on the batcher thread while stage C
    # (commit_batches_finish — status sync, tlog push, storage apply)
    # runs on the apply thread for the PREVIOUS group. Ordering
    # invariants are exactly the fleet's: the resolve gate serializes
    # dispatch in grant order (history is stateful), the log gate
    # serializes the apply tail; intra-proxy the batcher's FIFO apply
    # queue provides the same order when no fleet gates exist.

    def pipeline_eligible(self, request_batches):
        """Cheap stage-A admission check: the pipelined path serves the
        common case only. Anything needing per-request partitioning or
        per-batch serialization (database lock, tenant enforcement, a
        constrained ratekeeper charging lazy-rv requests, a dedupe HIT,
        multi-resolver host fan-out, dead roles) routes back to the
        serial commit_batches, which already handles it."""
        rk = self.ratekeeper
        if (len(self.resolvers) != 1 or not self.alive
                or not self.sequencer.alive
                or getattr(self, "lock_uid", None) is not None
                or getattr(self, "tenant_mode", "optional") != "optional"):
            return False
        if (rk is not None and rk.target_tps < rk.UNLIMITED_TPS
                and any(r.read_version is None
                        for reqs in request_batches for r in reqs)):
            return False
        self._pin_idmp_rv(request_batches)
        return not any(
            getattr(r, "idempotency_id", None)
            and self._idmp_lookup(r.idempotency_id) is not None
            for reqs in request_batches for r in reqs
        )

    def commit_batches_begin(self, request_batches):
        """Stages A+B of the pipelined backlog: chained version grant,
        host packing, and the gate-ordered lazy resolve dispatch.
        Always returns a _PipelinedGroup — failures are captured in the
        group (results precomputed, owed gate turns recorded) so the
        caller settles them through commit_batches_finish IN ORDER with
        the rest of the pipeline. Caller contract: begin runs on one
        thread in grant order; finish runs FIFO on one thread."""
        group = _PipelinedGroup(request_batches)
        n_total = sum(len(reqs) for reqs in request_batches)

        def err_1021():
            self._note_abort("commit_unknown_result", n_total)
            return [
                [FDBError.from_name("commit_unknown_result") for _ in reqs]
                for reqs in request_batches
            ]
        try:
            pairs = self.sequencer.next_commit_versions(len(request_batches))
        except SequencerDown:
            group.results_list = err_1021()
            return group
        group.first_prev, group.last_cv = pairs[0][0], pairs[-1][1]
        group.granted = True
        try:
            metas = []
            plans = []
            for reqs, (prev, cv) in zip(request_batches, pairs):
                window = max(
                    0, cv - self.knobs.max_read_transaction_life_versions
                )
                reqs, plan = self._maybe_schedule(reqs)
                plans.append(plan)
                metas.append((reqs, self._build_txns(reqs), cv, window))
            group.plans = plans
        except BaseException as e:
            group.error = e
            group.results_list = err_1021()
            return group
        gctx = group.trace_ctx = span_mod.first_request_context(
            r for reqs in request_batches for r in reqs
        )
        try:
            if self.resolve_gate is not None:
                self.resolve_gate.enter(group.first_prev)
            try:
                prior_ctx = span_mod.set_current(gctx) \
                    if gctx is not None else None
                try:
                    group.handle = self.resolvers[0].resolve_many(
                        [(txns, cv, window)
                         for _, txns, cv, window in metas],
                        lazy=True,
                    )
                finally:
                    if gctx is not None:
                        span_mod.set_current(prior_ctx)
            finally:
                if self.resolve_gate is not None:
                    self.resolve_gate.advance(group.last_cv)
        except GateTimeout:
            # wedged fleet: kill + blanket 1021s; no turn consumption —
            # only a txn-system recovery (fresh gates) unwedges
            group.granted = False
            group.results_list = [
                self._gate_wedged(len(reqs)) for reqs in request_batches
            ]
            return group
        except ResolverDown:
            # definitively not committed; the log turn is still owed
            self._note_abort("not_committed", n_total)
            group.results_list = [
                [FDBError.from_name("not_committed") for _ in reqs]
                for reqs in request_batches
            ]
            return group
        except BaseException as e:
            group.error = e
            group.results_list = err_1021()
            return group
        group.metas = metas
        return group

    def commit_batches_finish(self, group):
        """Stage C of the pipelined backlog: materialize the resolve
        statuses (the one host↔device sync), then the gate-ordered tail
        — tlog push, storage apply, feeds, reporting. Also the
        settlement point for groups that failed in begin: their owed
        gate turns are consumed HERE, in pipeline order, so successors
        never wait on a turn no one will take."""
        import time as _time

        if group.results_list is not None:
            if group.granted:
                self._skip_turns_quiet(group.first_prev, group.last_cv)
            return group.results_list
        t0 = _time.perf_counter()
        try:
            statuses_list = group.handle.wait()
        except BaseException as e:
            # the dispatched kernel faulted at materialization: the
            # device history for these versions is suspect, but both
            # turns must still be consumed (the resolve gate's advance
            # already ran; the skip's enter/advance there are no-ops)
            self._skip_turns_quiet(group.first_prev, group.last_cv)
            group.error = e
            self._note_abort(
                "commit_unknown_result",
                sum(len(reqs) for reqs in group.request_batches),
            )
            return [
                [FDBError.from_name("commit_unknown_result") for _ in reqs]
                for reqs in group.request_batches
            ]
        group.resolve_s = _time.perf_counter() - t0
        t1 = _time.perf_counter()
        with self._commit_mu:
            if not self.alive or not self.sequencer.alive:
                # killed mid-pipeline (txn-system recovery quiesce):
                # nothing may reach the log after the frontier read —
                # consume the owed turns and answer honest 1021s
                self._skip_turns_quiet(group.first_prev, group.last_cv)
                self._note_abort(
                    "commit_unknown_result",
                    sum(len(reqs) for reqs in group.request_batches),
                )
                return [
                    [FDBError.from_name("commit_unknown_result")
                     for _ in reqs]
                    for reqs in group.request_batches
                ]
            try:
                if self.log_gate is not None:
                    self.log_gate.enter(group.first_prev)
            except GateTimeout:
                return [
                    self._gate_wedged(len(reqs))
                    for reqs in group.request_batches
                ]
            try:
                out = []
                for (reqs, txns, cv, window), statuses, plan in zip(
                        group.metas, statuses_list,
                        group.plans or [None] * len(group.metas)):
                    res = self._finalize_batch(
                        reqs, txns, statuses, cv, window, prev=None,
                        traced=group.trace_ctx is not None, plan=plan)
                    out.append(
                        plan.restore(res) if plan is not None else res)
                return out
            finally:
                if self.log_gate is not None:
                    self.log_gate.advance(group.last_cv)
                group.apply_s = _time.perf_counter() - t1

    def _try_build_flat(self, requests):
        """The columnar batch build (core/flatpack.py): when the knob,
        the resolver, and every request agree, concatenate the clients'
        pre-encoded limb blobs into one FlatTxnBatch — no TxnRequest
        objects, no per-range split, no per-key re-parse. None routes
        the batch to the legacy build (mixed/legacy requests, cpu or
        sharded resolvers, over-capacity idempotency keys); both builds
        pack bit-identically (tests/test_packing_flat.py)."""
        res = self.resolvers
        if (getattr(self.knobs, "commit_pack_path", "legacy") != "flat"
                or len(res) != 1
                or not getattr(res[0], "accepts_flat", False)):
            return None
        from foundationdb_tpu.core import flatpack

        return flatpack.build_flat_batch(
            requests, self.knobs.key_limbs, self._idmp_point
        )

    def _maybe_schedule(self, requests):
        """Abort-aware intra-batch scheduling (server/scheduler.py):
        reorder the batch host-side — over the clients' already-encoded
        flat limb blobs, before any packing — so reads resolve before
        the intra-batch writes they overlap. Returns the (possibly
        reordered) request list plus the plan whose ``restore`` maps
        position-ordered results back to request order; (requests,
        None) when the knob is off or the pass declined."""
        if (not getattr(self.knobs, "commit_batch_scheduling", False)
                or len(requests) < 2):
            return requests, None
        from foundationdb_tpu.server import scheduler

        plan = scheduler.schedule(requests)
        if plan is None or plan.identity:
            return requests, None
        self.sched_batches += 1
        self.sched_reordered_total += plan.reordered
        self.sched_deferred_total += plan.deferred
        self._m_sched_reordered.inc(plan.reordered)
        self._m_sched_deferred.inc(plan.deferred)
        return [requests[i] for i in plan.order], plan

    def _build_txns(self, requests):
        rv_assigned = None
        n_lazy = 0
        for r in requests:
            if r.read_version is None:
                # read-free txn (no read conflict ranges): the client
                # skipped its GRV and the proxy assigns the window
                # position — the resolver never compares anything
                # against a read-free txn's rv (see Transaction.
                # _build_commit_request)
                if rv_assigned is None:
                    rv_assigned = self.sequencer.committed_version
                r.read_version = rv_assigned
                n_lazy += 1
        if n_lazy and self.ratekeeper is not None:
            # they bypassed the GRV's admission sampling: feed the
            # busy-tag base or tagged share reads inflated
            self.ratekeeper.note_untagged_admissions(n_lazy)
        flat = self._try_build_flat(requests)
        if flat is not None:
            self.pack_flat_batches += 1
            self.pack_bytes_total += flat.pack_bytes
            return flat
        self.pack_legacy_batches += 1
        if not all(getattr(r_, "wants_point_split", True)
                   for r_ in self.resolvers):
            # host backends: a point IS its tiny range — hand the
            # client's ranges through untouched (both byte strings
            # already exist; the split bought nothing but CPU)
            out = []
            for r in requests:
                ik = self._idmp_point(r)
                extra = [(ik, ik + b"\x00")] if ik is not None else []
                out.append(TxnRequest(
                    read_version=r.read_version,
                    point_reads=(), point_writes=(),
                    range_reads=list(r.read_conflict_ranges) + extra
                    if extra else r.read_conflict_ranges,
                    range_writes=list(r.write_conflict_ranges) + extra
                    if extra else r.write_conflict_ranges,
                ))
            return out
        split = _split_ranges
        out = []
        for r in requests:
            pr, rr = split(r.read_conflict_ranges)
            pw, rw = split(r.write_conflict_ranges)
            ik = self._idmp_point(r)
            if ik is not None:
                pr = pr + [ik]
                pw = pw + [ik]
            out.append(TxnRequest(
                read_version=r.read_version,
                point_reads=pr, point_writes=pw,
                range_reads=rr, range_writes=rw,
            ))
        return out

    def _finalize_batch(self, requests, txns, statuses, cv, window,
                        prev=None, traced=True, plan=None):
        """Everything after resolution: result assembly, DD accounting,
        tlog push (1021 on quorum loss), storage apply, change feeds,
        version reporting, admission + durability pumping. ``prev``
        orders this batch behind the fleet's earlier grants at the log
        gate (None = the caller already holds the order); assembly and
        routing run OUTSIDE the ordered section so a fleet overlaps
        them with another proxy's push."""
        # the batch-level span: parented to the FIRST sampled member's
        # commit span, linking every sampled member (ref: the commit
        # batch span in CommitProxyServer carrying txn tokens); made
        # ambient around the ordered tail so the tlog.push and
        # storage.apply hop spans nest under it. ``traced`` False means
        # the caller already KNOWS no member carries a context — the
        # per-request scan is skipped (a measured per-batch cost).
        bsp = span_mod.batch_span(requests) if traced else span_mod.NULL
        try:
            results = []
            batch_mutations = []
            batch_conflicts = 0
            from foundationdb_tpu.core import systemdata

            for i, (req, st) in enumerate(zip(requests, statuses)):
                if st == COMMITTED:
                    muts = [
                        substitute_versionstamp(m, cv, batch_order=0, txn_order=i)
                        if m.op in (Op.SET_VERSIONSTAMPED_KEY, Op.SET_VERSIONSTAMPED_VALUE)
                        else m
                        for m in req.mutations
                    ]
                    batch_mutations.extend(muts)
                    if getattr(req, "idempotency_id", None):
                        # the id row commits ATOMICALLY with the txn's
                        # mutations — its presence at any later read
                        # version proves this commit applied (ref:
                        # idempotencyIdKeys written in the same batch)
                        batch_mutations.append(Mutation(
                            Op.SET,
                            systemdata.idmp_key(req.idempotency_id),
                            systemdata.pack_version(cv),
                        ))
                    results.append(cv)
                    self._note_tags("committed", getattr(req, "tags", ()))
                elif st == TOO_OLD:
                    results.append(FDBError.from_name("transaction_too_old"))
                    batch_conflicts += 1
                    self._note_tags("too_old", getattr(req, "tags", ()))
                else:
                    self._note_tags("conflicted", getattr(req, "tags", ()))
                    self._charge_conflict(req)
                    e = FDBError.from_name("not_committed")
                    if req.report_conflicting_keys:
                        e.conflicting_key_ranges = self._conflicting_ranges(
                            txns[i]
                        )
                        # the version whose writes rejected this txn:
                        # the client repair engine re-reads ONLY the
                        # conflicting keys at exactly this version —
                        # its non-conflicting reads are resolver-proven
                        # unchanged through it (txn/repair.py)
                        e.conflict_version = cv
                    results.append(e)
                    batch_conflicts += 1

            # expired-id GC rides an ordinary batch (same durability /
            # replication / DR path as the rows themselves): every
            # pump_interval batches, clear ids older than RETENTION —
            # a deliberate multiple of the MVCC window, because a 1021
            # retry carries a FRESH read version and can arrive long
            # after the original's window closed (ref: the idempotency
            # id cleaner retaining ids by AGE, far past the window).
            # Runs on the next batch AFTER the pump, capped per round.
            if self._batches_since_pump == 0 and self.commit_count:
                horizon = max(0, cv - self.IDMP_RETENTION_WINDOWS *
                              self.knobs.max_read_transaction_life_versions)
                batch_mutations.extend(self._idmp_expired(horizon))

            # Route BEFORE the push so the log stores the per-tag split
            # (ref: applyMetadataToCommittedTransactions tagging mutations
            # with storage tags, TLogServer's per-tag streams): storage
            # workers then peek only their own stream. Full replication
            # skips tags — every tag's stream IS the full batch.
            routed = self._route(batch_mutations)
            tags = None
            if (self.dd is not None
                    and self.dd.replication < len(self.storages)):
                tags = dict(enumerate(routed))
        except BaseException:
            # assembly blew up before the ordered section: the version's
            # log turn must still be consumed or successors hang (quiet:
            # the root cause must propagate even if the gate is wedged)
            if prev is not None:
                self._skip_turns_quiet(prev, cv)
            raise
        if prev is not None and self.log_gate is not None:
            self.log_gate.enter(prev)
        try:
            if bsp is span_mod.NULL:
                return self._finalize_ordered(
                    requests, results, batch_mutations, batch_conflicts,
                    routed, tags, cv, window,
                )
            prior_ctx = span_mod.set_current(bsp.context())
            try:
                return self._finalize_ordered(
                    requests, results, batch_mutations, batch_conflicts,
                    routed, tags, cv, window,
                )
            finally:
                span_mod.set_current(prior_ctx)
                if plan is not None:
                    bsp.finish(version=cv, conflicts=batch_conflicts,
                               sched_reordered=plan.reordered,
                               sched_deferred=plan.deferred)
                else:
                    bsp.finish(version=cv, conflicts=batch_conflicts)
        finally:
            if prev is not None and self.log_gate is not None:
                self.log_gate.advance(cv)

    def _finalize_ordered(self, requests, results, batch_mutations,
                          batch_conflicts, routed, tags, cv, window):
        """The version-ordered tail of the pipeline: counters, DD load
        samples, the tlog push, storage apply, feeds, and reporting —
        everything that mutates shared cluster state."""
        self.conflict_count += batch_conflicts
        n_ok = sum(1 for r in results if not isinstance(r, FDBError))
        self.commit_count += n_ok
        self._m_batches.inc()
        self._note_result_errors(results)

        if self.dd is not None:
            for m in batch_mutations:
                if m.key >= b"\xff":
                    continue  # system rows are not user load samples
                if m.op == Op.CLEAR_RANGE:
                    self.dd.note_clear_range(m.key, m.param)
                else:
                    self.dd.note_write(
                        m.key, len(m.key) + len(m.param or b"")
                    )

        # push even empty batches so storage's version advances with cv
        try:
            self.tlog.push(cv, batch_mutations, tags=tags)
        except TLogDown:
            # no durability quorum: the would-be-committed txns are in
            # limbo → honest 1021, nothing applied to storage (ref:
            # proxies dying with an unacked tlog push). Definitive
            # resolver rejections (not_committed / too_old) stand —
            # those clients may retry without 1021 disambiguation.
            self.commit_count -= n_ok
            self._note_abort("commit_unknown_result", n_ok)
            return [
                r if isinstance(r, FDBError)
                else FDBError.from_name("commit_unknown_result")
                for r in results
            ]
        self._m_committed.inc(n_ok)  # monotone: counted only once durable
        # sync satellite mode: the batch reaches the remote region's
        # log before any client sees the ack, so a primary-region
        # disaster after this point loses nothing (ref: satellite TLogs
        # in the commit path). sync_push degrades to a counted miss —
        # never a stall — when the WAN is partitioned or the satellite
        # is down; async mode skips this entirely (the streamer drains
        # on its own cadence and the lag is the measured exposure).
        if (self.regions is not None
                and self.regions.config.satellite_mode == "sync"):
            self.regions.sync_push(cv, batch_mutations)
        for sid, muts in enumerate(routed):
            if not self.storages[sid].alive:
                # a detected-dead storage misses the batch; recruitment
                # replaces it wholesale (re-ingest from live teammates),
                # so skipping cannot strand a partial state
                continue
            try:
                self.storages[sid].apply(cv, muts)
                self.storages[sid].advance_window(window)
            except Exception:  # NOT BaseException: interrupts must escape
                # the batch IS committed — the log is durable — so an
                # apply failure must not fail the commit (a 1021 here
                # would lie: a retry would pass the idempotency dedupe,
                # whose lookup reads applied state, and double-commit
                # into the log). The failed storage's state is suspect
                # (possibly half-applied): declare it dead so
                # recruitment replays the log from its durable version,
                # restoring log↔storage agreement (ref: storage apply
                # being async from the commit point in the reference).
                from foundationdb_tpu.utils.trace import TraceEvent

                TraceEvent("StorageApplyFailed", severity=40).detail(
                    storage=sid, version=cv).log()
                self.storages[sid].kill()
        if self.change_feeds is not None and batch_mutations:
            # after the log has the batch (durable order) and before the
            # version is readable — consumers reading up to a GRV they
            # observed always see the feed entries for it
            self.change_feeds.note_commit(cv, batch_mutations)
        self.sequencer.report_committed(cv)
        if self.ratekeeper is not None:
            self.ratekeeper.observe_commit(len(requests), batch_conflicts)
        self._batches_since_pump += 1
        if self._batches_since_pump >= self.pump_interval:
            self._batches_since_pump = 0
            self._pump_durability(window)
        return results

    def _conflicting_ranges(self, txn):
        """Which of a rejected txn's read ranges conflicted (ref: the
        conflictingKeys reply field of ResolveTransactionBatchReply).
        Exact for host conflict sets; the TPU backend keeps no
        per-range verdicts on device, so it reports every read range —
        conservative, same direction as its false-positive contract."""
        ranges = []
        exact = True
        for r in self.resolvers:
            cset = getattr(r, "cset", None)
            if cset is None or not hasattr(cset, "conflicting_ranges"):
                exact = False
                break
            ranges.extend(cset.conflicting_ranges(txn))
        if exact:
            return sorted(set(ranges))
        return sorted(set(txn.read_ranges()))

    # id rows outlive the MVCC window by this factor (~50s at the
    # default 5s window): the slack a delayed retry has to arrive and
    # still dedupe instead of double-applying
    IDMP_RETENTION_WINDOWS = 10

    def _idmp_expired(self, horizon, cap=1000):
        """CLEAR mutations for idempotency-id rows whose commit version
        fell below the retention horizon (scanned from a live storage's
        system keyspace; empty scan when no idempotent traffic)."""
        from foundationdb_tpu.core import systemdata

        live = next((s for s in self.storages if s.alive), None)
        if live is None:
            return []
        out = []
        for k, v in live.read_range(systemdata.IDMP_PREFIX,
                                    systemdata.IDMP_END, live.version):
            if systemdata.unpack_version(v) < horizon:
                out.append(Mutation(Op.CLEAR, k, None))
                if len(out) >= cap:
                    break
        return out

    def _pump_durability(self, window):
        """Periodic updateStorage analog: fold versions that left the MVCC
        window into the persistent engines, then feed the ratekeeper the
        durability lag (how far the slowest storage is behind the
        flushable frontier — the reference's storage-queue signal).
        The lag is measured BEFORE flushing: it is the backlog this pump
        found, which is what admission control must react to (after a
        synchronous flush it would always read zero)."""
        live = [s for s in self.storages if s.alive]
        if not live:
            return
        lag = max(0, window - min(s.durable_version for s in live))
        for s in live:
            # a versioned (Redwood-role) engine keeps sub-durable reads
            # serveable, so durability can run all the way to the latest
            # version; single-version engines stop at the window floor or
            # reads below the fold would silently lose history
            s.flush(None if s.versioned_engine else window)
        # pop floor includes DEAD storages' frozen durable versions: their
        # recruitment replays the tlog from there, so those records must
        # survive until the replacement catches up (the log grows for at
        # most the detection window)
        self.tlog.pop(min(s.durable_version for s in self.storages))
        if self.ratekeeper is not None:
            self.ratekeeper.update(storage_lag_versions=lag)

    def _route(self, mutations):
        """Bucket mutations by owning storage in one pass (ref:
        applyMetadataToCommittedTransactions tagging mutations with
        storage tags via keyServers). Full replication (every storage on
        every team) short-circuits to the identity. Clear-ranges go to
        every storage whose shards overlap — applying the full range to
        a partial owner is safe, it only clears keys actually held."""
        n = len(self.storages)
        if self.dd is None or self.dd.replication >= n:
            return [mutations] * n
        smap = self.dd.map
        per = [[] for _ in range(n)]
        for m in mutations:
            if m.key >= b"\xff":
                # system keyspace replicates everywhere: recovery must be
                # able to read the shard map from any surviving storage
                # (ref: the system keyspace's wider replication)
                owners = range(n)
            elif m.op == Op.CLEAR_RANGE:
                owners = set()
                for i in smap.shards_overlapping(m.key, m.param):
                    owners.update(smap.teams[i])
            else:
                owners = smap.team_for(m.key)
            for sid in owners:
                per[sid].append(m)
        return per

    def _resolve(self, txns, cv, window):
        if len(self.resolvers) == 1:
            return self.resolvers[0].resolve(txns, cv, window)
        # Key-range sharded resolvers (ref: applyMetadataToCommittedTransactions
        # fan-out): each resolver sees only conflict ranges overlapping its
        # shard; a txn commits iff EVERY resolver accepts it. Because a txn's
        # fate must be agreed, each resolver is also told the full batch
        # structure (masked to its shard) and the proxy ANDs the verdicts.
        # Sub-batches dispatch concurrently: each resolver's work (packing
        # + kernel dispatch, or the GIL-releasing native conflict set) is
        # independent; verdicts join in resolver order, so the result is
        # schedule-independent (deterministic under the sim).
        n = len(self.resolvers)
        shard_batches = []
        for ri in range(n):
            lo, hi = self._resolver_range(ri, n)
            shard_batches.append([
                TxnRequest(
                    read_version=t.read_version,
                    point_reads=_clip_points(t.point_reads, lo, hi),
                    point_writes=_clip_points(t.point_writes, lo, hi),
                    range_reads=_clip(t.range_reads, lo, hi),
                    range_writes=_clip(t.range_writes, lo, hi),
                )
                for t in txns
            ])
        # lane balance on the host fan-out, same instrument the mesh
        # router fills at split time: surviving conflict entries per
        # clipped sub-batch -> lane_skew_pct. The tpu multi-lane backend
        # never reaches here (Cluster builds ONE MeshResolver; its
        # single-dispatch router retires this clip loop), so this covers
        # the cpu/native fleets for before/after skew comparison.
        if deviceprofile.enabled():
            prof = self._fanout_profile or next(
                (r.profile for r in self.resolvers
                 if getattr(r, "profile", None) is not None), None)
            if prof is not None:
                prof.record_lane_counts([
                    sum(len(t.point_reads) + len(t.point_writes)
                        + len(t.range_reads) + len(t.range_writes)
                        for t in batch)
                    for batch in shard_batches
                ])
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(
                max_workers=n, thread_name_prefix="sub-resolve"
            )
        futs = [
            self._pool.submit(res.resolve, batch, cv, window)
            for res, batch in zip(self.resolvers, shard_batches)
        ]
        verdicts = [f.result() for f in futs]
        out = []
        for i in range(len(txns)):
            vs = [v[i] for v in verdicts]
            if any(v == TOO_OLD for v in vs):
                out.append(TOO_OLD)
            elif all(v == COMMITTED for v in vs):
                out.append(COMMITTED)
            else:
                out.append(CONFLICT)
        return out

    def kill(self):
        """Process death: every commit answers 1021 until the failure
        monitor recruits a new transaction-system generation."""
        self.alive = False

    def close(self):
        """Release the sub-resolve thread pool (simulation rebuilds the
        cluster on every injected crash — stranded pools add up)."""
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    def _resolver_range(self, i, n):
        """Resolver i's key range: DD-derived bounds when available,
        else an even first-byte split. The last range's upper bound is
        None = +infinity so no key — including the \\xff system
        keyspace — escapes conflict checking."""
        b = self.resolver_bounds
        if b is not None:
            lo = b[i - 1] if i else b""
            hi = b[i] if i < len(b) else None
            return lo, hi
        lo = bytes([256 * i // n]) if i else b""
        hi = bytes([256 * (i + 1) // n]) if i + 1 < n else None
        return lo, hi


def _split_ranges(ranges):
    """One pass splitting conflict ranges into (points, true_ranges).
    Single-key ranges [k, k+\\x00) go to the resolver's point lanes —
    O(1) hash-table checks on device instead of the range lanes' ring
    scans. The reference makes the same point/range distinction inside
    detectConflicts (SkipList point queries vs range walks); semantics
    are identical either way (a point op IS the tiny range), this is
    purely the fast path. The point test allocates nothing — comparing
    against ``b + b"\\x00"`` built a bytes object per range and was the
    single hottest line of the commit pipeline."""
    points, true_ranges = [], []
    for b, e in ranges:
        if len(e) == len(b) + 1 and e[-1] == 0 and e.startswith(b):
            points.append(b)
        else:
            true_ranges.append((b, e))
    return points, true_ranges


def _clip_points(keys, lo, hi):
    return [k for k in keys if k >= lo and (hi is None or k < hi)]


def _clip(ranges, lo, hi):
    out = []
    for b, e in ranges:
        cb = max(b, lo)
        ce = e if hi is None else min(e, hi)
        if cb < ce:
            out.append((cb, ce))
    return out
