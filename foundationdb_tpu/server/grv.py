"""GRV proxy: hands out read versions, gated by the ratekeeper.

Ref parity: fdbserver/GrvProxyServer.actor.cpp — a read version is the
latest committed version (so reads observe all prior commits: external
consistency), batched across clients; the ratekeeper can delay or reject
under saturation.
"""

from foundationdb_tpu.core.errors import err


class GrvProxy:
    def __init__(self, sequencer, ratekeeper=None):
        self.sequencer = sequencer
        self.ratekeeper = ratekeeper
        self.grv_count = 0

    def get_read_version(self, priority="default"):
        if self.ratekeeper is not None and not self.ratekeeper.admit(priority):
            raise err("process_behind")  # client backs off and retries
        self.grv_count += 1
        return self.sequencer.committed_version
