"""GRV proxy: hands out read versions, gated by the ratekeeper.

Ref parity: fdbserver/GrvProxyServer.actor.cpp — a read version is the
latest committed version (so reads observe all prior commits: external
consistency), batched across clients; the ratekeeper can delay or reject
under saturation.

``BatchingGrvProxy`` is the reference's transaction-start batching loop:
concurrent clients' GRV requests accumulate for a batch window and are
granted from ONE committed-version read; under throttling a request is
DELAYED in the queue until the token bucket refills (the reference's
GRV queue), not bounced — only a request older than ``max_wait_s`` is
rejected (retryable), bounding client latency.
"""

import threading

import time

from foundationdb_tpu.core.errors import err
from foundationdb_tpu.utils import lockdep
from foundationdb_tpu.utils.backoff import Backoff
from foundationdb_tpu.utils import metrics as metrics_mod
from foundationdb_tpu.utils import span as span_mod


class GrvProxy:
    def __init__(self, sequencer, ratekeeper=None, metrics=None):
        self.sequencer = sequencer
        self.ratekeeper = ratekeeper
        self.grv_count = 0
        # persistent across recovery incarnations (the cluster hands the
        # same registry to the replacement): started-txn counters and
        # the grant-latency bands must never rewind
        self.metrics = metrics if metrics is not None \
            else metrics_mod.MetricsRegistry("grv_proxy")
        self._m_grants = self.metrics.counter("grv_grants")
        self._m_throttled = self.metrics.counter("grv_throttled")
        self._m_tag_throttled = self.metrics.counter("grv_tag_throttled")
        self._m_tag_started = {}  # tag -> counter handle (lazy)

    def _note_tag_started(self, tags):
        """Per-tag started counters (workload attribution): the tag
        rollup's denominator. Lives in the role registry so recovery
        absorption carries it like every other counter."""
        for t in tags:
            c = self._m_tag_started.get(t)
            if c is None:
                c = self._m_tag_started[t] = self.metrics.counter(
                    "tag_started_" + t)
            c.inc()

    def get_read_version(self, priority="default", tags=()):
        if not getattr(self.sequencer, "alive", True):
            # version authority dead: stall GRVs retryably until the
            # failure monitor recruits a new generation (ref: GRVs
            # blocking through a master recovery)
            raise err("process_behind")
        if self.ratekeeper is not None:
            ok, reason = self.ratekeeper.admit_with_reason(priority, tags)
            if not ok:
                # tag-throttled (1213) vs cluster-saturated (1037): both
                # retryable, but the client (and its operator) should
                # know WHICH gate closed (ref: GrvProxyTagThrottler)
                if reason == "tag":
                    self._m_tag_throttled.inc()
                    raise err("tag_throttled")
                self._m_throttled.inc()
                raise err("process_behind")
        self.grv_count += 1
        self._m_grants.inc()
        if tags:
            self._note_tag_started(tags)
        v = self.sequencer.committed_version
        # a traced request (in-process ambient context or the wire's
        # tracing frame) gets its grant recorded as a server-side hop
        ctx = span_mod.current()
        if ctx is not None:
            span_mod.emit_span("grv.grant", ctx, version=v,
                               priority=priority)
        return v

    def status(self):
        """This role's status RPC payload (leaf of the status doc)."""
        return {
            "alive": getattr(self.sequencer, "alive", True),
            "metrics": self.metrics.snapshot(),
        }


class BatchingGrvProxy:
    """Cross-client GRV batching with delay-based admission (thread
    deployments; the deterministic simulation keeps the synchronous
    proxy, whose rejects its workloads already ride out)."""

    def __init__(self, inner, interval_s=0.0005, max_wait_s=2.0,
                 start_thread=True):
        # start_thread=False: deterministic harnesses drive
        # _grant_round themselves (no thread, no wall clock)
        self.inner = inner
        self.interval_s = interval_s
        self.max_wait_s = max_wait_s
        self._lock = lockdep.lock("BatchingGrvProxy._lock")
        self._wake = lockdep.condition("BatchingGrvProxy._lock", self._lock)
        # two queues so a starved batch-priority request cannot head-of-
        # line-block default traffic (ref: per-priority GRV queues)
        self._queues = {"default": [], "batch": []}
        self._closed = False
        self._pending = 0  # queued + drained-but-unresolved requests
        self.batches_granted = 0
        self.delayed_count = 0  # requests that waited ≥1 extra window
        self.max_round = 0  # largest single-round grant (batch size seen)
        # grant-latency bands (ref: GrvProxyServer's GRV latency sample):
        # queued requests record their wait at grant; the uncontended
        # fast path is counted (its wait is ~0 by construction) so the
        # bands measure the queue, not a flood of zeros
        self._m_wait = inner.metrics.latency("grv_grant")
        self._m_fast = inner.metrics.counter("grv_fast_grants")
        self._m_queue_depth = inner.metrics.gauge("grv_queue_depth")
        self._thread = None
        if start_thread:
            self._thread = threading.Thread(
                target=self._grant_loop, name="grv-batcher", daemon=True
            )
            self._thread.start()

    def __getattr__(self, name):  # grv_count, sequencer, ... pass through
        return getattr(self.inner, name)

    def get_read_version(self, priority="default", tags=()):
        if not getattr(self.inner.sequencer, "alive", True):
            # dead version authority: stall retryably (1037) — the fast
            # path and grant loop read committed_version directly, so
            # the liveness check must happen here too
            raise err("process_behind")
        if priority == "immediate":
            with self._lock:  # counter consistency with the grant loop
                return self.inner.get_read_version(priority)  # bypass
        rk = self.inner.ratekeeper
        if rk is not None and tags and not rk.tag_gate(tags):
            # tag gates close immediately (1213, retryable) rather than
            # queueing: a throttled tag's requests must not occupy the
            # shared FIFO ahead of well-behaved traffic (ref: the
            # per-tag queues in GrvProxyTagThrottler); the global
            # budget is charged by the grant loop as usual
            raise err("tag_throttled")
        if tags:
            # the batcher's fast path and grant loop are tag-blind (one
            # committed-version read for the whole round): attribute the
            # start HERE, where the tags are still in hand
            self.inner._note_tag_started(tags)
        qkey = "batch" if priority == "batch" else "default"
        fast_v = None
        with self._lock:
            if (
                not self._closed
                and self._pending == 0  # covers drained-but-unresolved too
                and (rk is None or rk.admit(priority))
            ):
                # uncontended fast path: no request is ahead of us in ANY
                # state (queued or mid-round) and the budget has room —
                # grant inline, no thread handoff. Checking _pending
                # rather than the raw queues means a fresh arrival can
                # never steal a refilled token from an older request the
                # grant loop is currently holding.
                self.inner.grv_count += 1
                self.inner._m_grants.inc()
                self._m_fast.inc()
                fast_v = self.inner.sequencer.committed_version
        if fast_v is not None:
            # span emitted OUTSIDE the grant lock (file sinks write)
            ctx = span_mod.current()
            if ctx is not None:
                span_mod.emit_span("grv.grant", ctx, version=fast_v,
                                   priority=priority)
            return fast_v
        # queued: the span opens at ENQUEUE so its duration is the
        # grant-queue wait the latency bands measure
        gsp = span_mod.from_context("grv.grant", span_mod.current())
        fut = self._make_future(priority)
        with self._lock:
            if self._closed:
                raise err("process_behind")
            self._queues[qkey].append(fut)
            self._pending += 1
            self._wake.notify()
        fut["event"].wait()
        if fut["error"] is not None:
            raise fut["error"]
        gsp.finish(version=fut["value"], priority=priority, queued=1)
        return fut["value"]

    def _grant_loop(self):
        # throttled rounds back off exponentially (cap 20ms) instead of
        # hammering the bucket every half millisecond; a granting round
        # resets to the base batch interval. jitter=0: this is a batch
        # cadence, not a retrying fleet — lockstep is harmless and the
        # unjittered schedule keeps thread-mode timing unchanged.
        throttle = Backoff(initial_s=self.interval_s, max_s=0.02,
                           growth=2.0, jitter=0.0)
        while True:
            # acquire via the Condition (it wraps self._lock, so this IS
            # the same mutex): waiting on the object we hold makes the
            # release-while-parked relationship explicit (FL003)
            with self._wake:
                while not (self._queues["default"] or self._queues["batch"]
                           or self._closed):
                    self._wake.wait()
                if self._closed:
                    pending = self._queues["default"] + self._queues["batch"]
                    self._queues = {"default": [], "batch": []}
                    self._pending = 0
                    for fut in pending:
                        fut["error"] = err("process_behind")
                        fut["event"].set()
                    return
            with self._lock:
                n_waiting = len(self._queues["default"]) + len(
                    self._queues["batch"]
                )
            # adaptive batch window (ref: GRV batch interval min/max): a
            # lone request waits briefly for companions; under continuous
            # load the previous round's processing time IS the window —
            # sleeping on top of it would only tax per-client latency
            sleep_s = throttle.current
            if n_waiting < 2 or sleep_s > self.interval_s:
                time.sleep(sleep_s)
            if self._grant_round():
                throttle.reset()
            else:
                throttle.delay()

    @staticmethod
    def _make_future(priority, born=None):
        """The queued-request record _grant_round consumes (one
        construction point, shared with deterministic test drivers)."""
        return {"event": threading.Event(), "value": None, "error": None,
                "born": time.monotonic() if born is None else born,
                "waited": False, "priority": priority}

    def _grant_round(self, now=None):
        """ONE grant round: drain the queues, grant strict-FIFO per
        priority until the first denial, age out over-waited requests,
        requeue the rest. Extracted from the loop so the deterministic
        simulation (and tests) can drive rounds without the thread or
        wall clock (``now`` overrides the aging clock). Returns whether
        anything was granted."""
        with self._lock:
            work = {p: list(self._queues[p]) for p in ("default", "batch")}
            self._queues = {"default": [], "batch": []}
        rk = self.inner.ratekeeper
        if not getattr(self.inner.sequencer, "alive", True):
            # the sequencer died with requests queued: fail them
            # retryably rather than granting a dead authority's
            # frozen version
            with self._lock:
                n = 0
                for qkey in ("default", "batch"):
                    for fut in work[qkey]:
                        fut["error"] = err("process_behind")
                        fut["event"].set()
                        n += 1
                self._pending -= n
            return False
        version = None  # ONE committed-version read per grant round
        granted_any = False
        round_granted = 0
        resolved = 0  # granted + aged-out: leave the _pending count
        for qkey in ("default", "batch"):
            queue = work[qkey]
            # strict FIFO: grant from the head until the first denial
            # (ONE admit call per denial — a denied head means the
            # whole queue behind it waits, so no per-future hammering
            # of the token bucket and no younger request overtaking)
            n_granted = 0
            t_grant = time.monotonic() if now is None else now
            for fut in queue:
                if rk is not None and not rk.admit(fut["priority"]):
                    break
                if version is None:
                    version = self.inner.sequencer.committed_version
                    self.batches_granted += 1
                fut["value"] = version
                self._m_wait.record(max(0.0, t_grant - fut["born"]))
                fut["event"].set()
                n_granted += 1
                granted_any = True
            round_granted += n_granted
            resolved += n_granted
            rest = queue[n_granted:]
            if not rest:
                continue
            t = time.monotonic() if now is None else now
            keep = []
            for fut in rest:
                if t - fut["born"] > self.max_wait_s:
                    fut["error"] = err("process_behind")
                    fut["event"].set()
                    resolved += 1
                else:
                    if not fut["waited"]:
                        fut["waited"] = True
                        self.delayed_count += 1
                    keep.append(fut)
            if keep:
                with self._lock:  # requeue AT FRONT: FIFO preserved
                    self._queues[qkey] = keep + self._queues[qkey]
        with self._lock:
            self.inner.grv_count += round_granted
            self._pending -= resolved
            self.max_round = max(self.max_round, round_granted)
            depth = self._pending
        self.inner._m_grants.inc(round_granted)
        self._m_queue_depth.set(depth)
        return granted_any

    def close(self):
        with self._lock:
            self._closed = True
            self._wake.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
