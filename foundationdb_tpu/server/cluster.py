"""In-process cluster: wires sequencer, GRV/commit proxies, resolver(s),
tlog, and storage into a database.

Ref parity: the role wiring that ClusterController + Master recovery
performs (fdbserver/ClusterController.actor.cpp,
masterserver.actor.cpp). There is no separate process model here — the
"simulation deployment" runs every role in-process, exactly how the
reference's simulation (fdbrpc/sim2) hosts a whole cluster in one
process for deterministic testing.
"""

import dataclasses
import itertools
import threading

from foundationdb_tpu.core.errors import FDBError, err
from foundationdb_tpu.core.options import DEFAULT_KNOBS
from foundationdb_tpu.resolver.resolver import Resolver
from foundationdb_tpu.server.coordination import (
    CoordinationQuorum, CoordinatorDown, GenerationConflict,
)
from foundationdb_tpu.server import consistencyscan as consistencyscan_mod
from foundationdb_tpu.server.datadistribution import DataDistributor
from foundationdb_tpu.server.grv import GrvProxy
from foundationdb_tpu.server import health as health_mod
from foundationdb_tpu.server.proxy import CommitProxy
from foundationdb_tpu.server.ratekeeper import Ratekeeper
from foundationdb_tpu.server.router import StorageRouter
from foundationdb_tpu.server.sequencer import Sequencer
from foundationdb_tpu.server.storage import StorageServer
from foundationdb_tpu.server.tlog import TLog, TLogSystem
from foundationdb_tpu.utils import deviceprofile
from foundationdb_tpu.utils import heatmap as heatmap_mod
from foundationdb_tpu.utils import lockdep
from foundationdb_tpu.utils import metrics as metrics_mod
from foundationdb_tpu.utils import timeseries as timeseries_mod
from foundationdb_tpu.utils.trace import TraceEvent


def _lock_state(uid):
    """One consistent snapshot: locked iff a uid exists (including an
    empty one — an empty uid still fences commits and must not read as
    unlocked)."""
    if uid is None:
        return {"locked": False, "lock_uid": None}
    return {"locked": True, "lock_uid": uid.decode("utf-8", "replace")}


class Cluster:
    def __init__(self, knobs=None, n_resolvers=1, n_storage=1, wal_path=None,
                 version_clock="counter", storage_engines=None,
                 coordination=None, n_coordinators=3, coordination_dir=None,
                 replication=None, commit_pipeline="sync",
                 commit_batch_max=None, commit_flush_after=4,
                 target_tps=None, rk_clock=None, n_tlogs=1, fsync=False,
                 n_commit_proxies=1, regions=None,
                 **knob_overrides):
        if knobs is None:
            knobs = (
                dataclasses.replace(DEFAULT_KNOBS, **knob_overrides)
                if knob_overrides
                else DEFAULT_KNOBS
            )
        self.knobs = knobs
        # Per-role metric registries, keyed (role, index), owned by the
        # CLUSTER so they outlive role incarnations: a txn-system
        # recovery hands the replacement proxies the same registries and
        # no counter ever goes backwards (the reference's status
        # counters survive recoveries the same way — they live in the
        # roles' stats collections aggregated by a long-lived process).
        self._metrics_store = {}
        # Workload-attribution heatmaps, same ownership story: keyed
        # (role, index) and handed to every incarnation of the role, so
        # conflict/read/write heat survives txn-system recoveries,
        # storage recruitment, and configure() shrink (absorbed, never
        # rewound) exactly like the metric registries above.
        self._heatmap_store = {}
        # Device-path execution profiles (utils/deviceprofile.py), the
        # third member of the cluster-owned observability store: keyed
        # ("resolver", index) and re-handed to every resolver
        # incarnation via adopt_profile, so dispatch/pad/fallback
        # accounting survives respawn, recovery, and configure shrink.
        self._device_store = {}
        self.ratekeeper = Ratekeeper(
            target_tps=target_tps if target_tps is not None else 1e9,
            clock=rk_clock,
            tag_busy_threshold=knobs.tag_throttle_busyness,
        )
        if storage_engines is None:
            storage_engines = [None] * n_storage
        elif len(storage_engines) != n_storage:
            if n_storage != 1:
                raise ValueError(
                    f"n_storage={n_storage} but {len(storage_engines)} "
                    "storage_engines given"
                )
            n_storage = len(storage_engines)
        self.storages = [
            StorageServer(
                window_versions=knobs.max_read_transaction_life_versions,
                engine=eng,
            )
            for eng in storage_engines
        ]
        if knobs.workload_sampling:
            for i, s in enumerate(self.storages):
                s.attach_heatmaps(
                    self._role_heatmap("storage_read", i),
                    self._role_heatmap("storage_write", i),
                    knobs.storage_sample_every,
                )
        # ── recovery (ref: Master recovery replaying tlogs into storage) ──
        # Replay WAL records newer than each storage's durable version,
        # then restart the version authority above everything recovered.
        # Conflict history is not persisted; instead the resolvers open
        # with their window starting at the recovered version, so any
        # read version from before the crash is rejected TOO_OLD — the
        # same effect as the reference's recovery fencing in-flight txns.
        # replicated logs recover from the union of surviving replica WALs
        # (ref: recovery reading a quorum of the old tlog generation)
        if wal_path and n_tlogs > 1:
            recovered_records = TLogSystem.recover(wal_path, n_tlogs)
        elif wal_path:
            recovered_records = TLog.recover(wal_path)
        else:
            recovered_records = []
        for s in self.storages:
            for version, mutations in recovered_records:
                if version > s.version:
                    s.apply(version, mutations)
        recovered = max((s.version for s in self.storages), default=0)

        # ── coordinated cluster state (ref: master recovery reading then
        # locking the coordinators' generation before recruiting roles) ──
        self.coordination = coordination or CoordinationQuorum.local(
            n_coordinators, coordination_dir
        )
        # Generation lock is a CAS: read g, commit g+1 expecting g — two
        # concurrent recoveries cannot both win the slot (the loser sees
        # GenerationConflict, re-reads, and bids for the next slot).
        self.generation = self._win_generation(recovered)
        TraceEvent("MasterRecovered").detail(
            generation=self.generation, version=recovered).log()

        # fsync=True: every tlog push reaches the platters before the
        # commit acks (ref: TLog's DiskQueue fsync — the reference's
        # durability default; ours is opt-in because sim/test runs pay
        # ~10ms per commit for it)
        if n_tlogs > 1:
            self.tlog = TLogSystem(n_tlogs, wal_path=wal_path, fsync=fsync)
        else:
            self.tlog = TLog(wal_path=wal_path, fsync=fsync)
        self.tlog._first_version = recovered
        self.sequencer = Sequencer(
            version_clock=version_clock, start_version=recovered
        )
        # Multi-resolver TPU deployments run the fleet as ONE mesh
        # program (hash/bucket-sharded history, psum verdicts over ICI)
        # rather than n host-side resolvers — the proxy drives it through
        # the ordinary single-resolver path, backlog dispatch included.
        # cpu/native backends keep the key-range-sharded host fan-out
        # (the reference's process shape). See resolver/meshresolver.py.
        if knobs.resolver_backend == "tpu" and n_resolvers > 1:
            from foundationdb_tpu.resolver.meshresolver import MeshResolver

            self.resolvers = [MeshResolver(
                knobs, base_version=recovered, n_lanes=n_resolvers,
            )]
        else:
            self.resolvers = [
                Resolver(knobs, base_version=recovered)
                for _ in range(n_resolvers)
            ]
        self._attach_device_profiles()
        # Placement: replication defaults to n_storage (every storage a
        # full replica); replication < n_storage partitions the keyspace
        # into shards owned by teams of that size, with the commit proxy
        # routing writes and the StorageRouter stitching reads. The shard
        # map persists in the \xff/keyServers/ system keyspace (ref:
        # fdbclient/SystemData.cpp) — recovery restores the partitioning
        # instead of resetting to full replication. (The WAL still
        # replays everywhere, so non-owners briefly hold shadow copies of
        # recovered data; routing never reads them and relocations clear
        # before installing.)
        from foundationdb_tpu.core import systemdata
        from foundationdb_tpu.server.datadistribution import ShardMap

        restored_map = None
        arg_replication = replication
        if recovered_records:
            s0 = self.storages[0]
            rows = s0.read_range(
                systemdata.KEY_SERVERS_PREFIX, systemdata.KEY_SERVERS_END,
                s0.version,
            )
            decoded = systemdata.decode_shard_map(rows)
            if decoded is not None:
                restored_map = ShardMap.restore(*decoded)
                rep_row = s0.get(systemdata.CONF_REPLICATION, s0.version)
                if rep_row is not None:
                    replication = int(rep_row)
                # A persisted map can name a DIFFERENT storage fleet than
                # this incarnation has (a DR failover recovers the
                # primary's keyServers rows into the satellite's cluster
                # shape): validate team indices; a mismatched map falls
                # back to full replication, like a decode failure.
                fleet = len(self.storages)
                if any(sid >= fleet for team in restored_map.teams
                       for sid in team) or (replication or 0) > fleet:
                    TraceEvent("ShardMapFleetMismatch", severity=30).detail(
                        shards=len(restored_map),
                        map_replication=replication, fleet=fleet).log()
                    restored_map, replication = None, arg_replication
                else:
                    TraceEvent("ShardMapRestored").detail(
                        shards=len(restored_map),
                        replication=replication).log()
        self.replication = replication or n_storage
        self.dd = DataDistributor(
            self.storages, shard_map=restored_map,
            replication=self.replication,
        )
        self._read_rr = itertools.count()  # round-robin read balancing
        self.router = StorageRouter(self.storages, self.dd.map, self._read_rr)
        from foundationdb_tpu.server.changefeed import ChangeFeedRegistry

        self.change_feeds = ChangeFeedRegistry()
        # ── cross-client batching (ref: CommitProxyServer commitBatcher) ──
        # "thread": a daemon batcher collects concurrent commits into
        # shared-version batches (live deployments / e2e bench).
        # "manual": deterministic batching driven by the sim scheduler.
        # "sync": 1-txn batches, the degenerate pipeline.
        self.commit_pipeline = commit_pipeline
        self._commit_batch_max = commit_batch_max
        self._commit_flush_after = commit_flush_after
        self.recruitments = 0  # roles replaced by the failure monitor
        self.n_commit_proxies = n_commit_proxies
        # serializes txn-system recoveries: configure() arrives on an
        # RPC worker thread while the failure monitor ticks on the main
        # thread — two concurrent _recover_txn_system calls would race
        # the generation CAS and tear the frontend swap
        self._recovery_mu = lockdep.lock("Cluster._recovery_mu")
        # ── cluster doctor (server/health.py) ──
        # clock_advance: the simulation's hook — recovery phase marks
        # call it so a simulated recovery consumes simulated time and
        # same-seed runs agree; None in production (real elapsed time)
        self.clock_advance = None
        self.recovery_timeline = health_mod.RecoveryTimeline()
        self.prober = health_mod.LatencyProber(self)
        # ── metrics history + flight recorder (utils/timeseries.py) ──
        # the fourth member of the cluster-owned observability family
        # (registries, heatmaps, device profiles → history rings): the
        # collector samples the stores above each cadence window, so
        # its windows inherit their survive-recovery/absorb-on-shrink
        # semantics and never rewind
        self.history = timeseries_mod.HistoryCollector(self)
        # ── continuous consistency scan (server/consistencyscan.py) ──
        # the fifth cluster-owned subsystem: the background replica
        # auditor's stats ride a cluster-held registry and its cursor
        # persists in \xff/consistencyScan/, so rounds survive both
        # txn-system recoveries and full restarts
        self.scanner = consistencyscan_mod.ConsistencyScanner(self)
        # multi-region replication (server/region.py): None until a
        # region config attaches; the frontend below reads it, so the
        # attribute must exist before _build_txn_frontend
        self.regions = None
        self.commit_proxy, self.grv_proxy = self._build_txn_frontend()
        if recovered_records:
            self._restore_tenant_config()
            # resume the consistency scan where the old incarnation
            # left it (cursor + round count live beside the shard map
            # in the system keyspace) — a restart must not rewind a
            # round that was minutes from completing
            self.scanner.restore_cursor()
        # region config: constructor argument wins; otherwise a
        # recovered \xff/conf/regions row re-attaches replication (the
        # config persists beside the replication factor — `configure
        # regions=...` survives a full restart). Restored attaches
        # re-seed the satellite from the recovered state; only a NEW
        # config writes the system row.
        region_cfg = regions
        if region_cfg is None and recovered_records:
            s0 = self.storages[0]
            row = s0.get(systemdata.CONF_REGIONS, s0.version)
            if row is not None:
                region_cfg = row
        if region_cfg is not None:
            from foundationdb_tpu.server.region import RegionConfig

            self._attach_regions(RegionConfig.parse(region_cfg),
                                 persist=regions is not None)
        # only thread-mode clusters get the background probe loop; sims
        # and sync deployments drive maybe_probe() from their own
        # schedule so determinism is never perturbed
        if commit_pipeline == "thread" and knobs.health_probe_enabled:
            self.prober.start()
        # the history collector follows the prober's driver split: a
        # daemon loop only in thread mode, sim/manual schedules call
        # maybe_collect() themselves
        if commit_pipeline == "thread" and knobs.history_enabled:
            self.history.start()
        # the scanner too: daemon loop XOR sim pump, never both
        if commit_pipeline == "thread" and knobs.consistency_scan_enabled:
            self.scanner.start()

    def _restore_tenant_config(self):
        """Re-apply persisted tenant mode + quotas + lock state after
        recovery (all live in the system keyspace; enforcement is
        proxy/ratekeeper state that died with the old process)."""
        from foundationdb_tpu.core import systemdata
        from foundationdb_tpu.layers.tenant import (
            TENANT_MODE_KEY, TENANT_QUOTA_PREFIX, tenant_tag,
        )

        s0 = self.storages[0]
        lock_row = s0.get(systemdata.DB_LOCKED, s0.version)
        if lock_row is not None:
            self._commit_target().lock_uid = lock_row
        mode_row = s0.get(TENANT_MODE_KEY, s0.version)
        if mode_row is not None:
            self._commit_target().tenant_mode = mode_row.decode()
        for k, v in s0.read_range(
            TENANT_QUOTA_PREFIX, TENANT_QUOTA_PREFIX + b"\xff", s0.version
        ):
            self.ratekeeper.set_tag_quota(
                tenant_tag(k[len(TENANT_QUOTA_PREFIX):]), float(v)
            )

    def _role_registry(self, role, i=0):
        """The persistent (role, index) metrics registry — created on
        first use, reused by every later incarnation of that role."""
        key = (role, i)
        reg = self._metrics_store.get(key)
        if reg is None:
            reg = self._metrics_store[key] = metrics_mod.MetricsRegistry(
                role, index=i
            )
        return reg

    def _role_registries(self, role):
        return [reg for (r, _), reg in sorted(self._metrics_store.items())
                if r == role]

    def _role_heatmap(self, role, i=0, decode=None):
        """The persistent (role, index) heatmap — created on first use,
        reused by every later incarnation of that role (the registry
        accessor's exact twin)."""
        key = (role, i)
        hm = self._heatmap_store.get(key)
        if hm is None:
            hm = self._heatmap_store[key] = heatmap_mod.KeyRangeHeatmap(
                f"{role}:{i}",
                max_buckets=self.knobs.heatmap_max_buckets,
                half_life_s=self.knobs.heatmap_half_life_s,
                decode=decode,
            )
        return hm

    def _role_heatmaps(self, role):
        return [hm for (r, _), hm in sorted(self._heatmap_store.items())
                if r == role]

    def _role_profile(self, i=0):
        """The persistent ("resolver", index) device profile — created
        on first use, reused by every later incarnation of that
        resolver (the registry/heatmap accessors' exact twin)."""
        key = ("resolver", i)
        prof = self._device_store.get(key)
        if prof is None:
            prof = self._device_store[key] = deviceprofile.DeviceProfile(
                "resolver", index=i
            )
        return prof

    def _attach_device_profiles(self):
        """Hand every resolver its cluster-owned DeviceProfile (first
        boot AND txn-system recovery — the resize branch builds brand-
        new instances that would otherwise start blank). A shrinking
        fleet folds the orphaned indices' device history into member 0
        first: dispatch counters never go backwards."""
        n = max(1, len(self.resolvers))
        for (role, i) in list(self._device_store):
            if i >= n:
                self._role_profile(0).absorb(
                    self._device_store.pop((role, i))
                )
        for i, r in enumerate(self.resolvers):
            if hasattr(r, "adopt_profile"):
                r.adopt_profile(self._role_profile(i))

    def _make_commit_proxy(self, resolve_gate=None, log_gate=None, index=0):
        return CommitProxy(
            self.sequencer, self.resolvers, self.tlog, self.storages,
            self.knobs, self.ratekeeper, dd=self.dd,
            change_feeds=self.change_feeds,
            resolve_gate=resolve_gate, log_gate=log_gate,
            regions=getattr(self, "regions", None),
            fanout_profile=self._role_profile(0),
            metrics=self._role_registry("commit_proxy", index),
            heatmap=(
                self._role_heatmap("commit_proxy", index,
                                   decode=heatmap_mod.entry_key)
                if self.knobs.workload_sampling else None
            ),
        )

    def _build_txn_frontend(self):
        """Build the transaction frontend: one commit proxy + GRV proxy
        (the default; sims and single-threaded deployments), or a FLEET
        of ``n_commit_proxies`` of each with sequencer-chained versions
        and ordered pipeline gates (ref: the reference's proxy fleets;
        see server/fleet.py). Used for first boot AND txn-system
        recovery — the two incarnations must never diverge."""
        # a shrinking fleet folds the orphaned indices' metric history
        # into member 0 so cluster totals never go backwards
        n = max(1, self.n_commit_proxies)
        for (role, i) in list(self._metrics_store):
            if role in ("commit_proxy", "grv_proxy") and i >= n:
                self._role_registry(role, 0).absorb(
                    self._metrics_store.pop((role, i))
                )
        for (role, i) in list(self._heatmap_store):
            if role == "commit_proxy" and i >= n:
                # orphaned members' conflict heat folds into member 0:
                # hot-range snapshots never rewind across a shrink
                self._role_heatmap(
                    role, 0, decode=heatmap_mod.entry_key
                ).absorb(self._heatmap_store.pop((role, i)))
        if self.n_commit_proxies <= 1:
            return self._wire_pipeline(self._make_commit_proxy())
        from foundationdb_tpu.server.fleet import GrvFleet, ProxyFleet
        from foundationdb_tpu.server.proxy import VersionGate

        start = self.sequencer.committed_version
        t = self.knobs.gate_timeout_s
        resolve_gate, log_gate = (
            VersionGate(start, timeout=t), VersionGate(start, timeout=t),
        )
        inners, members, grvs = [], [], []
        for i in range(self.n_commit_proxies):
            inner = self._make_commit_proxy(
                resolve_gate=resolve_gate, log_gate=log_gate, index=i
            )
            wrapped, grv = self._wire_pipeline(inner, index=i)
            inners.append(inner)
            members.append(wrapped)
            grvs.append(grv)
        return ProxyFleet(members, inners), GrvFleet(grvs)

    def _inner_proxies(self):
        cp = self.commit_proxy
        if hasattr(cp, "inners"):
            return list(cp.inners)
        return [getattr(cp, "inner", cp)]

    def _wire_pipeline(self, inner, index=0):
        """Wrap a bare CommitProxy + fresh GrvProxy in the configured
        pipeline (one wiring for first boot AND txn-system recovery —
        the two incarnations must never diverge). "thread" batches GRVs
        too (ref: GrvProxyServer's transaction-start batching); the sim
        keeps the synchronous proxy so admission stays deterministic."""
        proxy = inner
        if self.commit_pipeline != "sync":
            from foundationdb_tpu.server.batcher import BatchingCommitProxy

            proxy = BatchingCommitProxy(
                inner, max_batch=self._commit_batch_max,
                flush_after=self._commit_flush_after,
                mode=self.commit_pipeline,
            )
        grv = GrvProxy(self.sequencer, self.ratekeeper,
                       metrics=self._role_registry("grv_proxy", index))
        if self.commit_pipeline == "thread":
            from foundationdb_tpu.server.grv import BatchingGrvProxy

            grv = BatchingGrvProxy(
                grv, interval_s=self.knobs.grv_batch_interval_s,
            )
        return proxy, grv

    def _win_generation(self, recovered):
        """CAS a new recovery generation at the coordinators: read g,
        commit g+1 expecting g — two concurrent recoveries cannot both
        win a slot (the loser re-reads and bids for the next one)."""
        for _ in range(10):
            prior = self.coordination.read_quorum() or {}
            gen = prior.get("generation", 0) + 1
            try:
                self.coordination.write_quorum(
                    {"generation": gen, "recovered_version": recovered},
                    expect_generation=gen - 1,
                )
                return gen
            except GenerationConflict:
                continue
        raise CoordinatorDown("could not win a recovery generation")

    # ── failure detection + recruitment ──────────────────────────────
    # Ref: fdbserver/ClusterController.actor.cpp failureDetectionServer +
    # workerAvailabilityWatch: the controller notices dead role instances
    # and recruits replacements. In-process there is no network heartbeat
    # to miss; "detection" is observing a killed instance's alive flag on
    # the monitor's next round — the same detect-latency shape, minus
    # packet plumbing. The simulation (or an operator loop) pumps
    # ``detect_and_recruit()``.
    def detect_and_recruit(self):
        """One failure-monitor round; returns [(role, index), ...] of
        recruitments performed."""
        events = []
        # whole-primary-region loss comes FIRST: with the sequencer,
        # proxies, storages, and log tier all dead, the ordinary
        # txn-system recovery below cannot even read a log frontier
        # (TLogDown) — the remote region's satellite log is the only
        # surviving durable state, and promotion replaces every primary
        # role in one recovery (ref: ClusterRecovery choosing a remote
        # region when the primary's logs are unrecoverable). A
        # coordination failure mid-failover leaves the roles dead and
        # the NEXT monitor round retries.
        reg = self.regions
        if reg is not None and reg.should_failover(self):
            with self._recovery_mu:
                if reg.should_failover(self):
                    try:
                        self._region_failover()
                    except CoordinatorDown as e:
                        reg.note_failed_attempt(e)
                        return events
                    events.append(("region-failover", 0))
                    self.recruitments += 1
                    TraceEvent("RolesRecruited").detail(
                        events=events).log()
                    return events
        if not self.sequencer.alive or not self._commit_target().alive:
            # a dead sequencer or commit proxy forces a transaction-
            # system recovery: new generation through the coordination
            # CAS, resolvers fenced, fresh sequencer/proxies — WITHOUT
            # touching storage or the logs (ref: ClusterRecovery
            # recruiting a new txn-system generation). Liveness is
            # re-checked under the recovery mutex: a configure() racing
            # on another thread may already have rebuilt the frontend.
            with self._recovery_mu:
                if (not self.sequencer.alive
                        or not self._commit_target().alive):
                    trigger = ("sequencer_failed"
                               if not self.sequencer.alive
                               else "commit_proxy_failed")
                    self._recover_txn_system(trigger=trigger)
                    events.append(("txn-system", 0))
        if isinstance(self.tlog, TLogSystem):
            for i, log in enumerate(self.tlog.logs):
                if not log.alive and self.tlog.revive(i) is not None:
                    events.append(("tlog", i))
        for i, r in enumerate(self.resolvers):
            if not r.alive:
                # fresh resolver with an empty conflict history MUST fence
                # every pre-death read version (it cannot check them), so
                # its window opens at the current committed version —
                # in-flight txns retry with fresh reads (ref: resolver
                # failure forcing a recovery that fences the old epoch).
                # respawn() recruits the instance's own kind (a mesh
                # fleet recruits a mesh fleet).
                self.resolvers[i] = r.respawn(
                    self.sequencer.committed_version
                )
                events.append(("resolver", i))
        for sid, s in enumerate(self.storages):
            if not s.alive:
                self._recruit_storage(sid)
                events.append(("storage", sid))
        if events:
            self.recruitments += len(events)
            TraceEvent("RolesRecruited").detail(events=events).log()
        return events

    def _recover_txn_system(self, new_resolver_lanes=None,
                            trigger="role_failure"):
        """The recovery state machine for dead sequencer/commit-proxy
        roles (ref: fdbserver/ClusterRecovery.actor.cpp): win a new
        generation at the coordinators (CAS), restart the version
        authority above everything the log acked, fence the resolvers
        (their windows open at the recovery version, so pre-death read
        versions retry TOO_OLD), and recruit fresh proxies over the
        SAME storages/logs — data is not torn down or re-ingested.
        ``new_resolver_lanes`` (configure's resize) swaps the resolver
        fleet shape HERE — after the quiesce, never while in-flight
        commits could still resolve against the old history."""
        import contextlib

        # recovery-state timeline (server/health.py): each phase mark
        # closes the phase that just ran; the record lands in the
        # bounded cluster-owned timeline health_status() reports
        rec = self.recovery_timeline.begin(trigger, self.clock_advance)
        old_proxy = self.commit_proxy
        old_inners = self._inner_proxies()
        # Quiesce: mark both roles dead FIRST (future batches answer
        # 1021 at the entry check / SequencerDown guard), then take
        # EVERY old proxy's commit mutex — in-flight batches that
        # already passed the check finish under the OLD generation
        # before we read the log frontier, so every acked commit is
        # covered by ``recovered`` (no acked-but-invisible writes, no
        # overlapping version grants into the shared tlog).
        for p in old_inners:
            p.kill()
        self.sequencer.kill()
        with contextlib.ExitStack() as stack:
            for p in old_inners:
                stack.enter_context(p._commit_mu)
            recovered = max(
                self.tlog.last_version, self.sequencer.committed_version
            )
        rec.phase("fence")
        gen = self.generation = self._win_generation(recovered)
        rec.phase("cas")
        self.sequencer = Sequencer(
            version_clock=self.sequencer.version_clock,
            start_version=recovered,
        )
        # fence conflict history: in-flight txns retry with fresh reads.
        # A resize builds the new shape directly at the recovery version
        # (building earlier would both race in-flight resolution and be
        # discarded by this very fence).
        if new_resolver_lanes is None:
            for i, r in enumerate(self.resolvers):
                self.resolvers[i] = r.respawn(recovered)
        else:
            if self.knobs.resolver_backend == "tpu" \
                    and new_resolver_lanes > 1:
                from foundationdb_tpu.resolver.meshresolver import (
                    MeshResolver,
                )

                new = [MeshResolver(self.knobs, base_version=recovered,
                                    n_lanes=new_resolver_lanes)]
            else:
                new = [Resolver(self.knobs, base_version=recovered)
                       for _ in range(new_resolver_lanes)]
            # in place: the (old, quiesced) proxies share this list;
            # the new frontend built below re-derives its ranges
            self.resolvers[:] = new
        # every incarnation — respawned or rebuilt — readopts its
        # cluster-owned device profile (shrinks fold orphans first)
        self._attach_device_profiles()
        # the database lock and tenant mode are cluster state, not proxy
        # state: survive the recovery (ref: both living in the system
        # keyspace)
        lock_uid = getattr(old_inners[0], "lock_uid", None)
        tenant_mode = getattr(old_inners[0], "tenant_mode", None)
        old_grv = self.grv_proxy
        self.commit_proxy, self.grv_proxy = self._build_txn_frontend()
        rec.phase("recruit")
        target = self._commit_target()
        if lock_uid is not None:
            target.lock_uid = lock_uid
        if tenant_mode is not None:
            target.tenant_mode = tenant_mode
        target.update_resolver_ranges(fence=False)
        rec.phase("replay")
        if self.commit_pipeline != "sync":
            # queued commits raced the death: resolve them 1021 so
            # their clients retry against the new generation
            old_proxy.fail_pending(err("commit_unknown_result"))
        old_proxy.close()
        if hasattr(old_grv, "close"):
            old_grv.close()
        rec.phase("accept")
        rec.finish(gen, recovered)
        TraceEvent("TxnSystemRecovered").detail(
            generation=gen, version=recovered, trigger=trigger,
            recovery_ms=rec.record["total_ms"]).log()

    def _storage_owns(self, smap, sid, m):
        """Does storage ``sid`` own mutation ``m`` under shard map
        ``smap``? (None = full replication: everyone owns everything;
        the system keyspace replicates everywhere regardless.) Shared
        by storage recruitment and region-failover replay."""
        from foundationdb_tpu.core.mutations import Op

        if smap is None:
            return True
        if m.key >= b"\xff":
            return True  # system keyspace replicates everywhere
        if m.op == Op.CLEAR_RANGE:
            return any(
                sid in smap.teams[i]
                for i in smap.shards_overlapping(m.key, m.param)
            )
        return sid in smap.team_for(m.key)

    def _region_failover(self):
        """Promote the remote region after whole-primary-region loss
        (ref: ClusterRecovery recruiting from a remote region when the
        primary's logs are unrecoverable). The shape is the ordinary
        ``_recover_txn_system`` state machine — same phases, same
        generation CAS, same timeline recorder (trigger
        ``region_failover``) — with two substitutions: the SATELLITE
        log is promoted to be THE log (its frontier, not the dead
        primary tier's, bounds what survives: every acked commit in
        sync satellite mode, acked-minus-measured-lag in async), and
        the storage fleet is rebuilt fresh in the remote region by
        replaying the promoted log from its seed snapshot. Caller holds
        ``_recovery_mu``."""
        import contextlib

        reg = self.regions
        rec = self.recovery_timeline.begin("region_failover",
                                           self.clock_advance)
        old_proxy = self.commit_proxy
        old_inners = self._inner_proxies()
        old_grv = self.grv_proxy
        old_storages = list(self.storages)
        # quiesce (same discipline as _recover_txn_system: dead roles
        # answer 1021 at entry, in-flight batches finish under the old
        # generation before we read the replication frontier)
        for p in old_inners:
            p.kill()
        self.sequencer.kill()
        with contextlib.ExitStack() as stack:
            for p in old_inners:
                stack.enter_context(p._commit_mu)
            frontier = reg.position
        rec.phase("fence")
        # the CAS can raise CoordinatorDown: nothing has been promoted
        # yet, every role is still dead, and the caller counts a failed
        # attempt — the next monitor round retries the whole failover
        gen = self.generation = self._win_generation(frontier)
        rec.phase("cas")
        # the satellite log becomes THE log: full history from the seed
        # snapshot onward, and future commits append to it (after a
        # full process restart the satellite WAL is the durable log)
        self.tlog = reg.promote_log()
        self.sequencer = Sequencer(
            version_clock=self.sequencer.version_clock,
            start_version=frontier,
        )
        # resolvers fence at the frontier exactly like any recovery:
        # pre-disaster read versions retry TOO_OLD
        for i, r in enumerate(self.resolvers):
            self.resolvers[i] = r.respawn(frontier)
        self._attach_device_profiles()
        # fresh storage fleet in the remote region. The primary fleet's
        # engines are LOST with the region (reusing one could carry
        # durable state past the replication frontier); replacements
        # start empty, inherit the cluster-owned metrics/heat so
        # counters never rewind, and swap in place — the dd/router/
        # proxy lists are shared. Fleet shape is unchanged, so the
        # replicated shard map stays valid as-is.
        fresh = []
        for sid, old in enumerate(old_storages):
            new = StorageServer(
                window_versions=(
                    self.knobs.max_read_transaction_life_versions),
            )
            new.region = reg.config.remote
            new.adopt_metrics(old.metrics)
            if self.knobs.workload_sampling:
                new.attach_heatmaps(
                    self._role_heatmap("storage_read", sid),
                    self._role_heatmap("storage_write", sid),
                    self.knobs.storage_sample_every,
                )
            fresh.append(new)
        self.storages[:] = fresh
        for log in (self.tlog.logs if isinstance(self.tlog, TLogSystem)
                    else [self.tlog]):
            log.region = reg.config.remote
        rec.phase("recruit")
        # replay the promoted log from the beginning — record one is
        # the seed snapshot — with the same ownership filter storage
        # recruitment uses, so placement survives the region flip
        smap = self.dd.map if self.replication < len(self.storages) \
            else None
        for sid, new in enumerate(self.storages):
            for version, muts in self.tlog.peek(0):
                if version > new.version:
                    new.apply(
                        version,
                        [m for m in muts
                         if self._storage_owns(smap, sid, m)],
                    )
        self.commit_proxy, self.grv_proxy = self._build_txn_frontend()
        self._commit_target().update_resolver_ranges(fence=False)
        # lock/tenant/quota enforcement re-derives from the replayed
        # system keyspace (the seed + stream carried the rows)
        self._restore_tenant_config()
        rec.phase("replay")
        if self.commit_pipeline != "sync":
            old_proxy.fail_pending(err("commit_unknown_result"))
        old_proxy.close()
        if hasattr(old_grv, "close"):
            old_grv.close()
        for old in old_storages:
            try:
                old.engine.close()
            except Exception as e:
                # a lost region's engine may be gone already, but say so:
                # repeated close failures here would mean leaked redwood
                # files, which the trace is the only way to spot
                TraceEvent("RegionFailoverEngineClose", severity=40).detail(
                    etype=type(e).__name__, error=str(e)[:200]).log()
        # watches parked on dead primary storages wake so clients
        # re-read and re-register against the promoted fleet
        for old in old_storages:
            for key in list(old._watches):
                for w in old._watches.pop(key):
                    w._fire()
        rec.phase("accept")
        rec.finish(gen, frontier)
        reg.note_failover(rec.record["total_ms"])
        TraceEvent("TxnSystemRecovered").detail(
            generation=gen, version=frontier, trigger="region_failover",
            recovery_ms=rec.record["total_ms"]).log()

    def _recruit_storage(self, sid):
        """Replace a dead storage by rebooting onto its durable engine
        and replaying the log from there (ref: a storage process
        rejoining — open the disk store, peek the tlog from the durable
        version). The in-memory MVCC overlay died with the process; the
        tlog covers the gap because the durability pump never pops past a
        dead storage's durable version. The engine object (file handle,
        versioned-ness) carries over, so replacement semantics match its
        peers."""
        old = self.storages[sid]
        new = StorageServer(
            window_versions=self.knobs.max_read_transaction_life_versions,
            engine=old.engine,
        )
        new.adopt_metrics(old.metrics)  # counters survive recruitment
        if self.knobs.workload_sampling:
            # same objects as the dead instance held (cluster-owned):
            # per-shard read/write heat survives recruitment
            new.attach_heatmaps(
                self._role_heatmap("storage_read", sid),
                self._role_heatmap("storage_write", sid),
                self.knobs.storage_sample_every,
            )
        smap = self.dd.map if self.replication < len(self.storages) else None
        for version, muts in self.tlog.peek(new.version):
            new.apply(
                version,
                [m for m in muts if self._storage_owns(smap, sid, m)],
            )
        new.region = getattr(old, "region", None)  # placement tag carries
        self.storages[sid] = new  # lists are shared: router/proxy/dd see it
        # watches parked on the dead instance wake so clients re-read and
        # re-register against the replacement
        for key in list(old._watches):
            for w in old._watches.pop(key):
                w._fire()

    def close(self):
        """Release background machinery (batcher threads, thread pools)
        and durable handles."""
        self.scanner.stop()
        self.prober.stop()
        self.history.stop()
        if self.regions is not None:
            self.regions.close()
        if hasattr(self.grv_proxy, "close"):
            self.grv_proxy.close()
        if hasattr(self.commit_proxy, "close"):
            self.commit_proxy.close()
        for s in self.storages:
            s.engine.close()
        self.tlog.close()

    # v1: single storage team holding the whole keyspace; reads go to [0].
    @property
    def storage(self):
        return self.storages[0]

    def read_storage(self, key=b""):
        """The read-side storage surface: the router resolves each read's
        key (or range) to its shard's team and load-balances across the
        replicas (ref: NativeAPI getKeyLocation + LoadBalance)."""
        return self.router

    # monotone shard-map epoch: bumped on every rebalance so tag-scoped
    # storage workers learn of ownership moves from peek replies instead
    # of polling the map (rpc/storageworker.py)
    shard_epoch = 0

    def rebalance(self):
        """One data-distribution round (splits/merges/moves), then
        persist the new map in the system keyspace and re-derive the
        resolver key ranges from it."""
        moves = self.dd.rebalance()
        self.shard_epoch += 1
        self.persist_shard_map()
        self.commit_proxy.update_resolver_ranges()
        return moves

    def exclude_storage(self, sid):
        """Begin draining a storage (ref: fdbcli exclude → the excluded-
        servers system key → DD relocating its shards). Reads stop
        routing new work there once its last shard moves; poll
        ``storage_drained`` to learn when removal is safe."""
        self.dd.excluded.add(sid)
        return self.rebalance()

    def include_storage(self, sid):
        """Cancel an exclusion (ref: fdbcli include)."""
        self.dd.excluded.discard(sid)

    def list_excluded(self):
        return sorted(self.dd.excluded)

    def connection_string(self):
        """What \\xff\\xff/connection_string reports for an in-process
        cluster (a remote client reports its cluster-file body)."""
        return "local"

    def storage_drained(self, sid):
        return self.dd.storage_owns_nothing(sid)

    def storage_owned_ranges(self, sid):
        """The key ranges storage ``sid``'s tag covers (merged, plus the
        everywhere-replicated system keyspace) — what a tag-scoped
        storage worker bootstraps and serves (ref: the keyServers
        ranges a storage's tag subscribes it to)."""
        end_cap = b"\xff\xff"
        if self.replication >= len(self.storages):
            return [(b"", end_cap)]
        smap = self.dd.map
        owned = []
        for i in range(len(smap)):
            if sid in smap.teams[i]:
                b, e = smap.shard_range(i)
                owned.append((b, e if e is not None else b"\xff"))
        owned.sort()
        merged = []
        for b, e in owned:
            if merged and b <= merged[-1][1]:
                merged[-1][1] = max(merged[-1][1], e)
            else:
                merged.append([b, e])
        merged.append([b"\xff", end_cap])  # system keyspace: everywhere
        return [tuple(r) for r in merged]

    def estimated_range_size_bytes(self, begin, end):
        """Ref: fdb_transaction_get_estimated_range_size_bytes — the
        DD's sampled per-shard byte counts, prorated for the partially
        covered boundary shards (same sampling-based estimate the
        reference serves from storage metrics)."""
        smap = self.dd.map
        total = 0
        for i in smap.shards_overlapping(begin, end):
            sb, se = smap.shard_range(i)
            size = smap.sizes[i]
            if size == 0:
                continue
            if sb >= begin and (se is not None and se <= end):
                total += size  # fully covered
            else:
                # boundary shard: prorate by covered key count in ONE
                # streamed pass (bounded: DD splits shards at
                # max_shard_bytes). Replica choice rides the router's
                # load-balanced pick, raising retryable when the whole
                # team is down like every other read path.
                owner = self.router._pick(smap.teams[i])
                lo = max(begin, sb)
                hi = se if se is not None else b"\xff\xff"
                hi = min(end, hi)
                shard_end = se if se is not None else b"\xff\xff"
                n_all = n_cov = 0
                for k, _ in owner._iter_live(sb, shard_end, owner.version):
                    n_all += 1
                    if lo <= k < hi:
                        n_cov += 1
                total += size * n_cov // max(n_all, 1)
        return total

    def range_split_points(self, begin, end, chunk_size):
        """Ref: fdb_transaction_get_range_split_points — keys splitting
        [begin, end) into chunks of roughly chunk_size bytes, derived
        from an owning storage's actual rows. Returns boundary keys
        including begin and end."""
        if chunk_size <= 0:
            raise err("invalid_option_value")
        if begin > end:
            raise err("inverted_range")
        version = self.sequencer.committed_version
        points = [begin]
        acc = 0
        # stream shard by shard (router-picked live replica each) —
        # never materialize the whole range's rows server-side
        smap = self.dd.map
        for i in smap.shards_overlapping(begin, end):
            sb, se = smap.shard_range(i)
            lo = max(begin, sb)
            hi = min(end, se) if se is not None else end
            owner = self.router._pick(smap.teams[i])
            for k, v in owner._iter_live(lo, hi, min(version, owner.version)):
                acc += len(k) + len(v or b"")
                if acc >= chunk_size and k != points[-1]:
                    points.append(k)  # strictly increasing boundaries
                    acc = 0
        points.append(end)
        return points

    def _commit_target(self):
        """The proxy that actually runs commit_batch (unwrap the
        batching pipeline wrapper) — lock state lives there."""
        return getattr(self.commit_proxy, "inner", self.commit_proxy)

    def resolver_lanes(self):
        return sum(getattr(r, "n_lanes", 1) for r in self.resolvers)

    def configure(self, commit_proxies=None, resolvers=None,
                  regions=None):
        """Live reconfiguration (ref: fdbcli `configure proxies=N
        resolvers=N regions=<json>` → ManagementAPI changeConfig
        forcing a recovery): resizing the commit-proxy fleet, the
        resolver fleet, or the region configuration rides the ordinary
        txn-system recovery — a new generation with the new shape over
        the same storage and logs; in-flight clients ride it out on
        retryable errors. New resolvers open FENCED at the committed
        version (their empty conflict history cannot check older read
        versions), exactly like recovery's respawn. ``regions`` takes a
        RegionConfig / dict / JSON string (validated BEFORE the fencing
        recovery — a typo must not bounce the txn system), or
        ``"off"``/``{}`` to detach replication; the satellite attaches
        AFTER the recovery, against the fresh frontend, and the config
        persists in the \\xff/conf/regions system row."""
        from foundationdb_tpu.server.region import RegionConfig

        for v in (commit_proxies, resolvers):
            if v is not None and int(v) < 1:
                raise err("invalid_option_value")
        region_off = regions in ("off", b"off", "", {})
        new_region_cfg = None
        if regions is not None and not region_off:
            new_region_cfg = RegionConfig.parse(regions)
        with self._recovery_mu:
            changed = False
            lanes = None
            region_change = False
            if (commit_proxies is not None
                    and int(commit_proxies) != self.n_commit_proxies):
                self.n_commit_proxies = int(commit_proxies)
                changed = True
            if resolvers is not None:
                # compare against what was REQUESTED, not what the
                # hardware achieved: the mesh clamps lanes to the
                # device count, and a management loop re-applying its
                # desired config must not force a fencing recovery on
                # every pass
                current = getattr(self, "_requested_resolver_lanes",
                                  None) or self.resolver_lanes()
                if int(resolvers) != current:
                    lanes = int(resolvers)
                    self._requested_resolver_lanes = lanes
                    changed = True
            if regions is not None:
                # same no-op discipline as the resolver compare: a
                # management loop re-applying its desired region config
                # must not re-seed the satellite every pass
                if region_off:
                    region_change = self.regions is not None
                else:
                    region_change = (
                        self.regions is None
                        or self.regions.config != new_region_cfg
                    )
                changed = changed or region_change
            if changed:
                self._recover_txn_system(new_resolver_lanes=lanes,
                                         trigger="configure")
            if region_change:
                if new_region_cfg is None:
                    self._detach_regions()
                else:
                    self._attach_regions(new_region_cfg, persist=True)
        shape = {"commit_proxies": self.n_commit_proxies,
                 "resolver_lanes": self.resolver_lanes()}
        # only a region-touching configure reports the region shape, so
        # proxy/resolver resizes keep their seed-era return contract
        if regions is not None:
            shape["regions"] = (self.regions.config.to_json()
                                if self.regions is not None else None)
        return shape

    def _attach_regions(self, config, persist=True):
        """Install the RegionReplicator for ``config``: satellite log
        at ``<wal_path>.satellite`` (in-memory when the cluster is),
        region tags stamped on the primary's tlog replicas and
        storages, the live proxies handed the replicator for sync-mode
        commit gating, and — in thread pipelines — the continuous
        streamer started. ``persist`` writes the \\xff/conf/regions
        system row (False on restart-restore: the row is already
        durable)."""
        from foundationdb_tpu.server.region import RegionReplicator

        if self.regions is not None:
            self.regions.drop()
            self.regions.close()
        wal = getattr(self.tlog, "wal_path", None)
        self.regions = RegionReplicator(
            self, config,
            wal_path=f"{wal}.satellite" if wal else None,
        )
        # region-tagged placement: every primary role carries the
        # primary region id (the replicator stamped its satellite
        # replicas with the remote id); recruitment carries the tags to
        # replacements
        for s in self.storages:
            s.region = config.primary
        for log in (self.tlog.logs if isinstance(self.tlog, TLogSystem)
                    else [self.tlog]):
            log.region = config.primary
        for p in self._inner_proxies():
            p.regions = self.regions
        if persist:
            self._persist_region_config()
        if self.commit_pipeline == "thread":
            self.regions.start()
        return self.regions

    def _detach_regions(self):
        """``configure regions=off``: release the primary-log pin, stop
        the streamer, close the satellite, clear the placement tags,
        and clear the persisted system row."""
        reg, self.regions = self.regions, None
        if reg is not None:
            reg.drop()
            reg.close()
        for s in self.storages:
            s.region = None
        for log in (self.tlog.logs if isinstance(self.tlog, TLogSystem)
                    else [self.tlog]):
            log.region = None
        for p in self._inner_proxies():
            p.regions = None
        self._persist_region_config()

    def _persist_region_config(self):
        """Write (or clear) the \\xff/conf/regions row through the
        normal commit pipeline — tlog-durable, restored by WAL recovery
        like the shard map, and streamed to the satellite so a promoted
        region knows its own region config. Best-effort like
        persist_shard_map."""
        from foundationdb_tpu.core import systemdata
        from foundationdb_tpu.core.mutations import Mutation, Op
        from foundationdb_tpu.server.proxy import CommitRequest

        if self.regions is not None:
            muts = [Mutation(
                Op.SET, systemdata.CONF_REGIONS,
                self.regions.config.to_json().encode(),
            )]
        else:
            muts = [Mutation(Op.CLEAR, systemdata.CONF_REGIONS)]
        req = CommitRequest(
            read_version=self.sequencer.committed_version,
            mutations=muts, read_conflict_ranges=[],
            write_conflict_ranges=[],
        )
        result = self.commit_proxy.commit(req)
        return not isinstance(result, Exception)

    def lock_database(self, uid=b"lock"):
        """Ref: ManagementAPI lockDatabase — commits from transactions
        without the lock_aware option fail 1038 until unlocked. The uid
        persists as the \\xff/dbLocked system row (ref:
        databaseLockedKey) so the lock survives WAL recovery and rides
        the DR seed/stream; enforcement stays at the proxy."""
        from foundationdb_tpu.core import systemdata

        uid = bytes(uid)

        def txn(tr):
            tr.options.set_lock_aware()
            # ref: lockDatabase reads databaseLockedKey first — locking
            # over ANOTHER operator's lock throws 1038 instead of
            # silently replacing it (same-uid lock is an idempotent
            # no-op); the read's conflict range serializes racing lockers
            held = tr.get(systemdata.DB_LOCKED)
            if held is not None and held != uid:
                raise err("database_locked")
            if held is None:
                tr.set(systemdata.DB_LOCKED, uid)

        self.database().run(txn)
        self._commit_target().lock_uid = uid

    def unlock_database(self):
        from foundationdb_tpu.core import systemdata

        def txn(tr):
            tr.options.set_lock_aware()
            tr.clear(systemdata.DB_LOCKED)

        self.database().run(txn)
        self._commit_target().lock_uid = None

    def lock_uid(self):
        return getattr(self._commit_target(), "lock_uid", None)

    def set_tenant_mode(self, mode):
        """Live enforcement switch (TenantManagement persists the system
        row; this flips the proxy's structural check)."""
        self._commit_target().tenant_mode = mode

    def tenant_mode(self):
        return getattr(self._commit_target(), "tenant_mode", "optional")

    def set_tag_quota(self, tag, tps):
        """Operator per-tag rate limit (tenant quotas ride this)."""
        self.ratekeeper.set_tag_quota(tag, tps)

    # ── distributed tracing config (utils/span.py) ──
    TRACING_DEFAULT_RATE = 0.01  # `tracing on` without an explicit rate

    def tracing_config(self):
        k = self.knobs
        return {"enabled": k.tracing_sample_rate > 0,
                "sample_rate": k.tracing_sample_rate,
                "slow_commit_ms": k.tracing_slow_commit_ms}

    def set_tracing(self, sample_rate=None, enabled=None):
        """Live tracing reconfiguration (fdbcli `tracing`, the
        \\xff\\xff/tracing/ special keys): swaps the cluster's knobs for
        a copy with the new sample rate — the shared DEFAULT_KNOBS
        object is never mutated, and new transactions (which resolve
        knobs per reset through the Database) pick it up immediately."""
        k = self.knobs
        if enabled is not None:
            if enabled:
                sample_rate = (k.tracing_sample_rate
                               if k.tracing_sample_rate > 0
                               else self.TRACING_DEFAULT_RATE)
            else:
                sample_rate = 0.0
        if sample_rate is None:
            return self.tracing_config()
        rate = float(sample_rate)
        if not 0.0 <= rate <= 1.0:
            raise err("invalid_option_value")
        self.knobs = dataclasses.replace(k, tracing_sample_rate=rate)
        # live proxies hold their construction-time knobs reference
        # (slow-window promotion reads the rate there): hand them the
        # new object. Sim fault wrappers shadow this harmlessly — sims
        # configure tracing at construction.
        for p in self._inner_proxies():
            p.knobs = self.knobs
        TraceEvent("TracingConfigured").detail(sample_rate=rate).log()
        return self.tracing_config()

    def consistency_check(self, max_keys_per_shard=None):
        """Replica agreement audit (ref: the ConsistencyCheck workload /
        fdbcli consistencycheck). Returns error strings; [] = clean."""
        from foundationdb_tpu.server.consistency import consistency_check

        return consistency_check(self, max_keys_per_shard)

    def persist_shard_map(self):
        """Write the live shard map to \\xff/keyServers/ through the
        normal commit pipeline — tlog-durable, recovered like user data
        (ref: keyServers commits in SystemData.cpp). Best-effort: a
        failed system commit (fault injection, log quorum loss) leaves
        the previous persisted map; the next round retries."""
        from foundationdb_tpu.core import systemdata
        from foundationdb_tpu.core.mutations import Mutation, Op
        from foundationdb_tpu.server.proxy import CommitRequest

        muts = [Mutation(Op.CLEAR_RANGE, systemdata.KEY_SERVERS_PREFIX,
                         systemdata.KEY_SERVERS_END)]
        muts += [
            Mutation(Op.SET, k, v)
            for k, v in systemdata.encode_shard_map(self.dd.map)
        ]
        muts.append(Mutation(
            Op.SET, systemdata.CONF_REPLICATION,
            str(self.replication).encode(),
        ))
        req = CommitRequest(
            read_version=self.sequencer.committed_version,
            mutations=muts, read_conflict_ranges=[],
            write_conflict_ranges=[],
        )
        result = self.commit_proxy.commit(req)
        return not isinstance(result, Exception)

    def database(self):
        from foundationdb_tpu.txn.database import Database

        return Database(self)

    def _metacluster_status(self):
        """This cluster's metacluster membership (ref: the metacluster
        section of status json): management/data role + name from the
        registration row, or cluster_type "standalone"."""
        import json as _json

        from foundationdb_tpu.layers.metacluster import REGISTRATION_KEY

        s0 = next((s for s in self.storages if s.alive), None)
        if s0 is None:
            # membership is UNREADABLE, not absent — claiming
            # "standalone" with every storage dead would lie to an
            # operator about a registered cluster
            return {"cluster_type": "unknown"}
        try:
            row = s0.get(REGISTRATION_KEY, s0.version)
        except FDBError:
            # a kill raced past the alive check: status() reports
            # chaos as data, it never raises
            return {"cluster_type": "unknown"}
        if row is None:
            return {"cluster_type": "standalone"}
        meta = _json.loads(row)
        return {"cluster_type": f"metacluster_{meta['role']}",
                "name": meta.get("name")}

    def _sum_counter(self, role, name):
        return sum(
            reg.counter(name).value for reg in self._role_registries(role)
        )

    def metrics_status(self):
        """The aggregated metrics section of the status document (ref:
        Status.actor.cpp folding every role's stats into one json):
        cluster-level latency rollups — merged across the role fleets —
        plus hottest-stage attribution for the commit pipeline."""
        commit_regs = self._role_registries("commit_proxy")
        grv_regs = self._role_registries("grv_proxy")
        commit = metrics_mod.merged_bands_ms(
            [r.get_latency("commit_e2e") for r in commit_regs]
        )
        grv = metrics_mod.merged_bands_ms(
            [r.get_latency("grv_grant") for r in grv_regs]
        )
        logs = self.tlog.logs if isinstance(self.tlog, TLogSystem) \
            else [self.tlog]
        push = metrics_mod.merged_bands_ms(
            [l.metrics.get_latency("tlog_push") for l in logs]
        )
        apply_ = metrics_mod.merged_bands_ms(
            [s.metrics.get_latency("storage_apply") for s in self.storages]
        )
        # multiplexed read serving (txn/futures.py ReadBatcher →
        # StorageServer.read_batch): serve-latency bands plus the
        # reads-per-RPC histogram — read_batch_keys records len(ops)/1e3
        # so its ms-scaled bands read back as raw batch sizes
        rbatch = metrics_mod.merged_bands_ms(
            [s.metrics.get_latency("read_batch") for s in self.storages]
        )
        rkeys = metrics_mod.merged_bands_ms(
            [s.metrics.get_latency("read_batch_keys") for s in self.storages]
        )
        read_batches = sum(
            s.metrics.counter("read_batches").value for s in self.storages)
        batched_reads = sum(
            s.metrics.counter("batched_reads").value for s in self.storages)
        # hottest-stage attribution: the commit-pipeline stage with the
        # most TOTAL wall time across the fleet is the critical path an
        # operator should look at first
        stage_totals = {}
        for reg in commit_regs:
            for stage in ("pack", "dispatch", "resolve", "apply"):
                s = reg.get_latency(f"stage_{stage}")
                if s is not None and s.count:
                    stage_totals[stage] = (
                        stage_totals.get(stage, 0.0) + s.total_seconds()
                    )
        hottest = max(stage_totals, key=stage_totals.get) \
            if stage_totals else None
        return {
            "rollups": {
                "commit_latency_p50_ms": commit["p50_ms"],
                "commit_latency_p99_ms": commit["p99_ms"],
                "commit_latency_max_ms": commit["max_ms"],
                "commit_spans": commit["count"],
                "grv_latency_p99_ms": grv["p99_ms"],
                "tlog_push_p99_ms": push["p99_ms"],
                "storage_apply_p99_ms": apply_["p99_ms"],
                # batched-read observability: serve latency, batch-size
                # percentiles (reads-per-RPC), and the coalesce rate
                # (mean reads each batch RPC carried)
                "read_batch_p99_ms": rbatch["p99_ms"],
                "read_batch_size_p50": round(rkeys["p50_ms"], 1),
                "read_batch_size_p99": round(rkeys["p99_ms"], 1),
                "read_batches": read_batches,
                "batched_reads": batched_reads,
                "read_batch_coalesce_rate": round(
                    batched_reads / max(read_batches, 1), 2),
                "hottest_stage": hottest,
                "hottest_stage_totals_s": {
                    k: round(v, 6) for k, v in stage_totals.items()
                },
                # conflict repair + abort-aware scheduling outcomes
                # (txn/repair.py, server/scheduler.py): counted on the
                # commit-proxy registries — client repairs land on the
                # registry of the proxy the client talks to, scheduler
                # decisions on the proxy that reordered the batch
                "repair_attempts": self._sum_counter(
                    "commit_proxy", "repair_attempts"),
                "repair_commits": self._sum_counter(
                    "commit_proxy", "repair_commits"),
                "repair_fallbacks": self._sum_counter(
                    "commit_proxy", "repair_fallbacks"),
                "sched_reordered": self._sum_counter(
                    "commit_proxy", "sched_reordered"),
                "sched_deferred": self._sum_counter(
                    "commit_proxy", "sched_deferred"),
            },
            "commit_latency_bands": commit,
            "grv_latency_bands": grv,
        }

    def _tag_rollup(self):
        """Per-tag outcome totals folded across the role fleets (the
        registries hold ``tag_{outcome}_{tag}`` counters), plus the
        ratekeeper's last-window busyness gauge."""
        out = {}
        scans = (
            ("commit_proxy", "tag_committed_", "committed"),
            ("commit_proxy", "tag_conflicted_", "conflicted"),
            ("commit_proxy", "tag_too_old_", "too_old"),
            ("grv_proxy", "tag_started_", "started"),
        )
        snaps = {
            role: [r.snapshot()["counters"] for r in
                   self._role_registries(role)]
            for role in ("commit_proxy", "grv_proxy")
        }
        for role, prefix, field in scans:
            for counters in snaps[role]:
                for name, v in counters.items():
                    if name.startswith(prefix):
                        row = out.setdefault(name[len(prefix):], {})
                        row[field] = row.get(field, 0) + v
        for tag, busy in self.ratekeeper.tag_busyness.items():
            out.setdefault(tag, {})["busyness"] = busy
        # live admission limits (AIMD + standalone busyness throttle +
        # operator quotas): what GRV is actually enforcing per tag
        for tag, tps in self.ratekeeper.throttled_tags().items():
            out.setdefault(tag, {})["limit_tps"] = round(tps, 2)
        return {t: out[t] for t in sorted(out)}

    def hot_ranges_status(self, top=None):
        """The workload-attribution document (``metrics hot`` RPC /
        \\xff\\xff/metrics/hot_ranges / cluster.workload): fleet-merged
        conflict/read/write hot ranges — each a bounded decayed
        key-range histogram — plus the per-tag rollup. ``top`` keeps
        only the N hottest ranges per dimension."""
        k = self.knobs
        dims = {
            "conflict": heatmap_mod.merged(
                self._role_heatmaps("commit_proxy"), name="conflict",
                max_buckets=k.heatmap_max_buckets,
                half_life_s=k.heatmap_half_life_s,
                decode=heatmap_mod.entry_key,
            ),
            "read": heatmap_mod.merged(
                self._role_heatmaps("storage_read"), name="read",
                max_buckets=k.heatmap_max_buckets,
                half_life_s=k.heatmap_half_life_s,
            ),
            "write": heatmap_mod.merged(
                self._role_heatmaps("storage_write"), name="write",
                max_buckets=k.heatmap_max_buckets,
                half_life_s=k.heatmap_half_life_s,
            ),
        }
        return {
            "sampling": bool(k.workload_sampling) and heatmap_mod.enabled(),
            "hot_ranges": {
                name: hm.snapshot(top=top) for name, hm in dims.items()
            },
            "totals": {
                name: {"heat": round(hm.total_heat(), 4),
                       "charges": hm.charges}
                for name, hm in dims.items()
            },
            "tags": self._tag_rollup(),
        }

    def device_profile_status(self):
        """The device-path execution profile document (``device_profile``
        RPC / \\xff\\xff/metrics/device / cluster.device): per-resolver
        dispatch accounting — pad/bucket occupancy, compile-cache
        events, staging reuse, transfer bytes, per-lane walls — plus a
        cluster aggregate, all from the cluster-owned store so the doc
        survives recoveries and configure()."""
        profs = [p for (_, _), p in sorted(self._device_store.items())]
        return {
            "enabled": deviceprofile.enabled(),
            "resolvers": [p.snapshot() for p in profs],
            "aggregate": deviceprofile.merged_snapshot(profs),
        }

    def health_status(self):
        """The ``cluster.health`` document (``health`` RPC /
        \\xff\\xff/status/health / fdbcli doctor / tools/doctor.py):
        doctor verdict + reasons + FDB-style messages, probe latency
        bands, the recovery timeline, and the lag/saturation rollups —
        a pure read (no probe fires here)."""
        return health_mod.build_health(self)

    def history_status(self):
        """The metrics-history document (``history`` RPC /
        \\xff\\xff/metrics/history / fdbcli history / cluster.history):
        bounded per-metric rings of fixed-cadence windows — counter
        rates, gauge trajectories, latency-band p99 trajectories, heat
        totals, and the verdict timeline — plus the flight recorder's
        summary. A pure read: no window is cut here."""
        return self.history.status()

    def flight_status(self):
        """The flight-recorder document (``flight`` RPC /
        \\xff\\xff/status/flight / tools/flight.py): the black box's
        dump summary plus the newest retained artifact (None until a
        verdict transition, recovery, or probe-SLO breach has fired)."""
        return {**self.history.recorder.summary(),
                "artifact": self.history.recorder.latest()}

    def consistency_scan_status(self):
        """The continuous consistency-scan document
        (``consistency_scan`` RPC / \\xff\\xff/status/consistency_scan
        / fdbcli scan status): round, progress, bytes/keys scanned,
        and confirmed inconsistencies — a pure read (no batch runs
        here)."""
        return self.scanner.status()

    def set_consistency_scan(self, on):
        """Flip the scanner's module kill switch (fdbcli scan on|off /
        the set_consistency_scan RPC). The scan document stays readable
        either way; returns it so callers see the new state."""
        consistencyscan_mod.set_enabled(bool(on))
        return self.consistency_scan_status()

    def _trace_status(self):
        """The trace/span pipeline's own health: per-type suppression
        (satellite of flow/Trace.cpp event suppression) and the tracing
        config + span gauges (utils/span.py)."""
        from foundationdb_tpu.utils import span as span_mod
        from foundationdb_tpu.utils.trace import global_trace_log

        log = global_trace_log()
        return {
            "suppressed_events": log.suppressed_events,
            "suppressed_by_type": dict(log.suppressed_by_type),
            "tracing": self.tracing_config(),
            "spans_sampled": span_mod.spans_sampled(),
            "spans_emitted": span_mod.spans_emitted(),
        }

    def status(self):
        """Cluster status summary (ref: fdbcli status json, Status.actor.cpp
        — processes/roles breakdown, qos, data, recovery state)."""
        rk = self.ratekeeper
        live_storages = sum(1 for s in self.storages if s.alive)
        tlog_info = {"count": 1, "live": 1, "quorum": 1, "replicated": False}
        if isinstance(self.tlog, TLogSystem):
            tlog_info = {
                "count": self.tlog.n,
                "live": self.tlog.live_count,
                "quorum": self.tlog.quorum,
                "replicated": True,
            }
        degraded = (
            live_storages < len(self.storages)
            or tlog_info["live"] < tlog_info["count"]
            or any(not r.alive for r in self.resolvers)
        )
        hot = self.hot_ranges_status()
        return {
            "cluster": {
                "generation": self.generation,
                "coordinators": len(self.coordination.coordinators),
                "data": {
                    "shards": len(self.dd.map),
                    "team_bytes": self.dd.team_bytes(),
                    "replication_factor": self.replication,
                    "moving_data": False,
                },
                "database_available": live_storages > 0,
                "database_lock_state": _lock_state(self.lock_uid()),
                # multi-region replication (server/region.py): config +
                # live replication state, always present so operators
                # and tools never branch on a missing key
                "regions": (self.regions.status()
                            if self.regions is not None
                            else {"configured": False}),
                "metacluster": self._metacluster_status(),
                "change_feeds": len(self.change_feeds),
                "degraded": degraded,
                "recruitments": self.recruitments,
                "qos": {
                    "transactions_per_second_limit": rk.target_tps,
                    "batch_transactions_per_second_limit": (
                        rk.target_tps * rk.batch_priority_fraction
                    ),
                    "throttled_count": rk.throttled_count,
                    "throttled_tags": rk.throttled_tags(),
                    "tag_throttled_count": rk.tag_throttled_count,
                },
                "workload": {
                    # counters come from the cluster-held registries, so
                    # they SURVIVE txn-system recoveries (the live
                    # proxies' own attrs reset with each incarnation)
                    "transactions": {
                        "committed": {"counter": self._sum_counter(
                            "commit_proxy", "txn_committed")},
                        "conflicted": {"counter": self._sum_counter(
                            "commit_proxy", "abort_not_committed")
                            + self._sum_counter(
                                "commit_proxy", "abort_transaction_too_old")},
                        "started": {"counter": self._sum_counter(
                            "grv_proxy", "grv_grants")},
                    },
                    # workload attribution: WHICH keys/tags the traffic
                    # above actually hit (utils/heatmap.py)
                    "hot_ranges": hot["hot_ranges"],
                    "hot_range_totals": hot["totals"],
                    "tags": hot["tags"],
                },
                "metrics": self.metrics_status(),
                # cluster doctor (server/health.py): verdict + reasons +
                # messages + probe bands + recovery timeline + lag
                # rollups — what fdbcli doctor and tools/doctor.py read
                "health": self.health_status(),
                # device-path execution profile (utils/deviceprofile.py):
                # the resolver dispatch layer's pad/bucket/fallback
                # accounting, cluster-owned like metrics/heatmaps above
                "device": self.device_profile_status(),
                # metrics history (utils/timeseries.py): the retention
                # layer's full doc — bounded per-metric windows, the
                # verdict timeline, and the flight-recorder summary —
                # so status-file consumers (tools/doctor.py --trend)
                # see trajectories without a second RPC
                "history": self.history_status(),
                # continuous consistency scan (consistencyscan.py):
                # the background auditor's round/progress/verdict —
                # the machine-checkable "is the data still consistent"
                # instrument the sim swarm and doctor read
                "consistency_scan": self.consistency_scan_status(),
                # observability plumbing health: process-wide (cumulative
                # across incarnations, so kept OUT of the deterministic
                # per-cluster metrics section) — the trace sink's
                # suppression counters and the span pipeline's gauges
                "trace": self._trace_status(),
                "latest_version": self.sequencer.committed_version,
                "oldest_readable_version": self.storage.oldest_version,
                "commit_pipeline": self.commit_pipeline,
                "processes": {
                    "sequencer": {"alive": self.sequencer.alive},
                    "commit_proxy": {"alive": self._commit_target().alive,
                                     "count": self.n_commit_proxies,
                                     "members": [
                                         p.status()
                                         for p in self._inner_proxies()
                                     ]},
                    "grv_proxies": [
                        {"id": reg.index, "metrics": reg.snapshot()}
                        for reg in self._role_registries("grv_proxy")
                    ],
                    "resolvers": [
                        {"id": i, "alive": r.alive,
                         "backend": self.knobs.resolver_backend,
                         "lanes": getattr(r, "n_lanes", 1),
                         # "range" = single-dispatch presharded mesh,
                         # "hash" = replicated-batch mesh, "local" =
                         # single-lane / host resolvers
                         "sharding": getattr(r, "sharding", "local"),
                         "metrics": r.metrics.snapshot()}
                        for i, r in enumerate(self.resolvers)
                    ],
                    "storage_servers": [
                        {
                            "id": i,
                            "alive": s.alive,
                            "durable_version": s.durable_version,
                            "oldest_version": s.oldest_version,
                            "versioned_engine": s.versioned_engine,
                            "metrics": s.status()["metrics"],
                        }
                        for i, s in enumerate(self.storages)
                    ],
                    "logs": {
                        **tlog_info,
                        "replicas": (
                            self.tlog.status()
                            if isinstance(self.tlog, TLogSystem)
                            else [self.tlog.status()]
                        ),
                    },
                    "ratekeeper": self.ratekeeper.status(),
                },
                "resolvers": sum(
                    getattr(r, "n_lanes", 1) for r in self.resolvers
                ),
                "resolver_backend": self.knobs.resolver_backend,
                "storage_servers": len(self.storages),
            }
        }
