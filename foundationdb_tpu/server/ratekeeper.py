"""Ratekeeper: cluster-wide admission control.

Ref parity: fdbserver/Ratekeeper.actor.cpp — computes a transactions-per-
second budget from storage/tlog health and conflict rates; GRV proxies
enforce it by delaying or rejecting read-version grants. Ours keeps the
same two-loop shape:

* a **token bucket** at the GRV edge (``admit``), refilled at the current
  target TPS, with batch-priority txns charged more so they only run on
  spare capacity and immediate-priority (system) txns exempt;
* a **control loop** (``update``, pumped by the cluster or simulation)
  that recomputes the target: storage durability lag (versions the
  storage tier is behind the committed version — the analog of the
  reference's storage-queue spring) squeezes the budget smoothly toward
  a floor, and a high conflict ratio (wasted work under contention)
  trims it, recovering multiplicatively when health returns.
"""

import threading

import time

from foundationdb_tpu.utils import lockdep
from foundationdb_tpu.utils import metrics as metrics_mod


class Ratekeeper:
    # lag (in versions) where the budget starts shrinking / hits the floor
    LAG_SOFT = 1_000_000  # ~1s at 1M versions/sec (the reference's 5s MVCC
    LAG_HARD = 4_000_000  # window leaves ~1s headroom before TOO_OLD pain)
    CONFLICT_TRIM = 0.5  # conflict ratio above which the budget is trimmed
    FLOOR_FRACTION = 0.01
    # ── per-tag auto-throttling (ref: fdbserver/TagThrottler.actor.cpp,
    # GrvProxyTagThrottler.actor.cpp: busy tags get their own rate limit
    # so one abusive workload cannot starve the rest) ──
    TAG_SAMPLE_MIN = 64  # admissions before a tag can auto-throttle
    TAG_BUSY_FRACTION = 0.5  # share of admissions that reads as "busy"
    TAG_RELEASE_FACTOR = 1.5  # limit regrowth per healthy control round

    def __init__(self, target_tps=1e9, batch_priority_fraction=0.5,
                 clock=None, tag_busy_threshold=1.0):
        self.max_tps = target_tps
        self.target_tps = target_tps
        self.batch_priority_fraction = batch_priority_fraction
        # standalone busy-tag policy (knob tag_throttle_busyness, ref:
        # TagThrottler auto-throttling a busy tag without waiting for
        # global pressure): a tag whose admission share exceeds this
        # threshold gets its own limit even while the cluster budget is
        # healthy. 1.0 = off (a share can never exceed 1.0); the
        # under-pressure AIMD path below is always on.
        self.tag_busy_threshold = float(tag_busy_threshold)
        # Injectable clock so the deterministic simulation can drive the
        # token bucket off its step counter instead of wall time (admission
        # results must replay byte-identically under a seed).
        self.clock = clock if clock is not None else time.monotonic
        self._tokens = target_tps
        self._last_refill = self.clock()
        self._recent_txns = 0
        self._recent_conflicts = 0
        self.throttled_count = 0  # GRV requests rejected at the gate
        # per-tag state: sampled admissions per control window, manual
        # quotas (operator), auto limits (control loop), token buckets
        self._tag_counts = {}  # tag -> admissions this window
        self._recent_admits = 0  # all admissions this window (share base)
        self._tag_window_start = self.clock()
        self.tag_quotas = {}  # tag -> tps (manual, sticky)
        self.tag_limits = {}  # tag -> tps (auto, AIMD)
        self._tag_buckets = {}  # tag -> [tokens, last_refill]
        self.tag_throttled_count = 0
        # per-tag busyness (workload attribution, gauge only): the last
        # completed control window's cnt/total share per tag, captured
        # BEFORE _update_tags_locked resets its sample — a future
        # tag-throttle PR turns policy on against exactly this signal
        self.tag_busyness = {}
        # thread-mode clusters admit from many client threads while the
        # batcher thread feeds observe_commit/update: the token bucket's
        # read-modify-write must not interleave
        self._mu = lockdep.lock("Ratekeeper._mu")
        # throttle gauges for the status document (ref: the qos section
        # Ratekeeper feeds in Status.actor.cpp); values are set from the
        # live fields at snapshot time, so admission pays nothing
        self.metrics = metrics_mod.MetricsRegistry("ratekeeper")
        # per-reason denial COUNTERS (not snapshot-time gauges): the
        # registry survives recovery, so throttle causes accumulate
        # across incarnations and show in benchdiff trajectories — the
        # signal the cluster doctor's saturation rollup reads
        self._m_denied_tag = self.metrics.counter("admit_denied_tag")
        self._m_denied_budget = self.metrics.counter("admit_denied_budget")

    # ── GRV-edge enforcement (ref: GrvProxy transaction budgets) ──
    def admit(self, priority="default", tags=()):
        ok, _ = self.admit_with_reason(priority, tags)
        return ok

    # Above this target the bucket cannot practically constrain anything
    # (refill outruns any achievable admission rate), so admission is a
    # foregone conclusion and the lock is pure hot-path overhead.
    UNLIMITED_TPS = 1e8

    def admit_with_reason(self, priority="default", tags=()):
        """→ (admitted, None | "tag" | "budget"). Tag buckets are
        checked before the global bucket so a throttled tag's denial
        never burns global tokens; admissions (not attempts) feed the
        busy-tag sample, or a throttled-but-retrying tag could never
        observe a rate low enough to be released."""
        if priority == "immediate":
            return True, None  # system txns bypass (ref: TransactionPriority::IMMEDIATE)
        if (not tags and not self.tag_quotas and not self.tag_limits
                and not self._tag_counts
                and self.target_tps >= self.UNLIMITED_TPS):
            # unconstrained fast path: no tag rules exist, no tagged
            # traffic has been sampled, and the global bucket is
            # effectively unbounded — admission cannot fail. The racy
            # counter only feeds the tagged-share estimate; requiring an
            # empty _tag_counts keeps untagged increments from racing
            # (and shrinking) the admissions base while tagged txns take
            # the locked path, which would bias TOWARD spurious
            # auto-throttling.
            self._recent_admits += 1
            return True, None
        with self._mu:
            now = self.clock()
            ok, limited = self._tags_check_locked(tags, now)
            if not ok:
                return False, "tag"
            if not self._global_pass_locked(priority, now):
                # tag buckets deliberately NOT charged on a global deny:
                # a tagged client retrying 1037 under saturation must
                # not drain its quota with zero admissions
                return False, "budget"
            for b in limited:
                b[0] -= 1.0
            self._note_admit_locked(tags)
            return True, None

    def note_untagged_admissions(self, n):
        """Read-free commits skip the GRV (rv assigned at the proxy)
        but still belong in the busy-tag sample's admissions BASE:
        without them ``cnt/total`` overstates every tag's share and
        auto-throttling turns against innocent tags (round-5 review).
        Called once per batch, under the lock."""
        with self._mu:
            self._recent_admits += n

    def tag_gate(self, tags):
        """The tag half alone (BatchingGrvProxy closes tag gates before
        queueing so a throttled tag never occupies the shared FIFO; the
        global budget is charged later by the grant loop). Both the tag
        count and the admissions base are sampled here — the grant
        loop's untagged admit() adds to the base again, so tagged share
        is under- (never over-) estimated for batching deployments,
        biasing AWAY from spurious auto-throttling."""
        if not tags:
            return True
        with self._mu:
            now = self.clock()
            ok, limited = self._tags_check_locked(tags, now)
            if not ok:
                return False
            for b in limited:
                b[0] -= 1.0
            self._note_admit_locked(tags)
            return True

    def _tags_check_locked(self, tags, now):
        """All-or-nothing check → (ok, limited_buckets): the CALLER
        charges the returned buckets only once the whole admission
        passes (a multi-tag txn denied by its second tag — or by the
        global budget — must not burn any tag's token)."""
        limited = []
        for tag in tags:
            limit = self.tag_quotas.get(tag, self.tag_limits.get(tag))
            if limit is None:
                continue
            b = self._tag_buckets.get(tag)
            if b is None:
                b = self._tag_buckets[tag] = [limit, now]
            b[0] = min(limit, b[0] + (now - b[1]) * limit)
            b[1] = now
            if b[0] < 1.0:
                self.tag_throttled_count += 1
                self._m_denied_tag.inc()
                return False, []
            limited.append(b)
        return True, limited

    def _global_pass_locked(self, priority, now):
        need = 1.0
        if priority == "batch":
            # batch priority only runs when spare capacity exists
            need = 1.0 / max(self.batch_priority_fraction, 1e-6)
        self._tokens = min(
            self.target_tps,
            self._tokens + (now - self._last_refill) * self.target_tps,
        )
        self._last_refill = now
        if self._tokens >= need:
            self._tokens -= need
            return True
        self.throttled_count += 1
        self._m_denied_budget.inc()
        return False

    def _note_admit_locked(self, tags):
        self._recent_admits += 1
        for tag in tags:
            self._tag_counts[tag] = self._tag_counts.get(tag, 0) + 1

    def observe_commit(self, txns, conflicts):
        """Both arguments are per-batch increments."""
        with self._mu:
            self._recent_txns += txns
            self._recent_conflicts += conflicts

    # ── control loop (ref: Ratekeeper::updateRate) ──
    def update(self, storage_lag_versions=0):
        """Recompute target TPS from tier health; returns the new target.

        ``storage_lag_versions``: committed version minus the slowest
        storage's durable version (the cluster computes it; simulation
        pumps this deterministically).
        """
        with self._mu:
            return self._update_locked(storage_lag_versions)

    def _update_locked(self, storage_lag_versions):
        floor = self.max_tps * self.FLOOR_FRACTION
        # storage spring: full rate below LAG_SOFT, linear squeeze to the
        # floor at LAG_HARD (the reference's smoothed storage queue term)
        if storage_lag_versions <= self.LAG_SOFT:
            lag_target = self.max_tps
        elif storage_lag_versions >= self.LAG_HARD:
            lag_target = floor
        else:
            frac = (storage_lag_versions - self.LAG_SOFT) / (
                self.LAG_HARD - self.LAG_SOFT
            )
            lag_target = self.max_tps - frac * (self.max_tps - floor)

        # conflict trim: mostly-wasted work means admitting more txns only
        # manufactures retries; shed a third, recover gradually when healthy.
        # Sub-threshold samples decay 25% per round instead of hard
        # resetting: a sustained storm accumulates to the 100-txn sample
        # even at low per-round volume (equilibrium 3x the per-round
        # count), while a one-off burst fades within a few rounds and
        # cannot trim a later, healthy period.
        target = min(lag_target, self.max_tps)
        total = self._recent_txns
        if total >= 100:
            ratio = self._recent_conflicts / total
            if ratio > self.CONFLICT_TRIM:
                target = max(floor, min(target, self.target_tps * (2 / 3)))
            self._recent_txns = 0
            self._recent_conflicts = 0
        else:
            self._recent_txns = self._recent_txns * 3 // 4
            self._recent_conflicts = self._recent_conflicts * 3 // 4
        if target > self.target_tps:
            # recover at most 10% per round so oscillation damps out
            target = min(target, max(self.target_tps * 1.1, floor))
        self.target_tps = max(floor, target)
        self._update_tags_locked()
        return self.target_tps

    def _update_tags_locked(self):
        """Busy-tag auto-throttling (ref: TagThrottler::autoThrottleTag):
        while the cluster is shedding load, a tag responsible for more
        than TAG_BUSY_FRACTION of admissions gets its own limit at half
        its observed rate (multiplicative decrease); healthy rounds
        regrow the limit until it clears the tag's demand, then release
        it. Manual quotas (tag_quotas) are operator-sticky and never
        auto-released.

        The STANDALONE policy (tag_busy_threshold < 1.0) additionally
        throttles a tag whose admission share exceeds the threshold
        even WITHOUT global pressure — and holds the limit (no regrow)
        while the tag stays over-threshold, so one abusive workload is
        capped the moment it dominates admissions rather than only
        after it saturates the cluster."""
        now = self.clock()
        elapsed = max(now - self._tag_window_start, 1e-9)
        total = self._recent_admits
        if self._tag_counts:
            # retain the window's per-tag admission share as a gauge
            # (the throttle-policy hook documented in analysis/README):
            # captured here because the sample resets below
            self.tag_busyness = {
                tag: round(cnt / max(total, 1), 4)
                for tag, cnt in sorted(self._tag_counts.items())
            }
        under_pressure = self.target_tps < self.max_tps * 0.9
        # visit limited-but-silent tags too: a tag that stopped sending
        # must have its limit regrown/released, not kept forever
        for tag in set(self._tag_counts) | set(self.tag_limits):
            cnt = self._tag_counts.get(tag, 0)
            rate = cnt / elapsed
            busy = (
                cnt >= self.TAG_SAMPLE_MIN
                and total > 0
                and cnt / total > self.TAG_BUSY_FRACTION
            )
            standalone = (
                self.tag_busy_threshold < 1.0
                and cnt >= self.TAG_SAMPLE_MIN
                and total > 0
                and cnt / total > self.tag_busy_threshold
            )
            limit = self.tag_limits.get(tag)
            if (under_pressure and busy) or standalone:
                new_limit = max(rate / 2, 1.0)
                self.tag_limits[tag] = (
                    min(limit, new_limit) if limit is not None else new_limit
                )
            elif limit is not None and not under_pressure:
                grown = limit * self.TAG_RELEASE_FACTOR
                if grown > rate * 2:
                    del self.tag_limits[tag]
                    self._tag_buckets.pop(tag, None)
                else:
                    self.tag_limits[tag] = grown
        # drop buckets for stale released tags; reset the sample window
        for tag in list(self._tag_buckets):
            if tag not in self.tag_limits and tag not in self.tag_quotas:
                del self._tag_buckets[tag]
        self._tag_counts = {}
        self._recent_admits = 0
        self._tag_window_start = now

    def set_tag_quota(self, tag, tps):
        """Operator-set per-tag rate limit (ref: the tag quota system);
        ``tps=None`` clears it."""
        with self._mu:
            if tps is None:
                self.tag_quotas.pop(tag, None)
                if tag not in self.tag_limits:
                    self._tag_buckets.pop(tag, None)
            else:
                self.tag_quotas[tag] = float(tps)

    def throttled_tags(self):
        """Snapshot for status json: tag -> effective tps limit."""
        with self._mu:
            out = dict(self.tag_limits)
            out.update(self.tag_quotas)
            return out

    def set_target_tps(self, tps):
        self.max_tps = float(tps)
        self.target_tps = min(self.target_tps, self.max_tps)

    def history_sample(self):
        """Point-in-time admission gauges for the history collector
        (utils/timeseries.py): the trajectory inputs ROADMAP item 4's
        admission control will trend on. Unlike ``status()`` this
        mutates nothing — sampling a window must not dirty the
        registry gauges other readers snapshot."""
        with self._mu:
            return {
                "target_tps": round(self.target_tps, 2),
                "saturation": round(
                    1.0 - self.target_tps / max(self.max_tps, 1e-9), 4),
                "throttled": self.throttled_count,
                "tag_throttled": self.tag_throttled_count,
            }

    def status(self):
        """This role's status RPC payload: the throttle gauges (leaf of
        the status doc). Gauges are refreshed here rather than on every
        admission — the hot path stays untouched."""
        m = self.metrics
        m.gauge("target_tps").set(self.target_tps)
        m.gauge("max_tps").set(self.max_tps)
        m.gauge("throttled").set(self.throttled_count)
        m.gauge("tag_throttled").set(self.tag_throttled_count)
        m.gauge("throttled_tags").set(len(self.throttled_tags()))
        m.gauge("saturation").set(
            round(1.0 - self.target_tps / max(self.max_tps, 1e-9), 4)
        )
        doc = {"alive": True, "metrics": m.snapshot()}
        with self._mu:
            if self.tag_busyness:
                doc["tag_busyness"] = dict(self.tag_busyness)
        return doc
