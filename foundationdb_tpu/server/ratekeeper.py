"""Ratekeeper: cluster-wide admission control.

Ref parity: fdbserver/Ratekeeper.actor.cpp — computes a transactions-per-
second budget from storage/tlog lag and conflict rates; GRV proxies
enforce it by delaying read-version grants. Here the budget is a token
bucket refilled from a smoothed target rate, adjusted down when commit
latency or conflict ratio spikes.
"""

import time


class Ratekeeper:
    def __init__(self, target_tps=1e9, batch_priority_fraction=0.5):
        self.target_tps = target_tps
        self.batch_priority_fraction = batch_priority_fraction
        self._tokens = target_tps
        self._last_refill = time.monotonic()
        self._recent_txns = 0
        self._recent_conflicts = 0

    def admit(self, priority="default"):
        now = time.monotonic()
        self._tokens = min(
            self.target_tps, self._tokens + (now - self._last_refill) * self.target_tps
        )
        self._last_refill = now
        need = 1.0
        if priority == "batch":
            # batch priority only runs when spare capacity exists
            need = 1.0 / max(self.batch_priority_fraction, 1e-6)
        elif priority == "immediate":
            return True  # system txns bypass (ref: TransactionPriority::IMMEDIATE)
        if self._tokens >= need:
            self._tokens -= need
            return True
        return False

    def observe_commit(self, txns, conflicts):
        """Both arguments are per-batch increments."""
        self._recent_txns += txns
        self._recent_conflicts += conflicts

    def set_target_tps(self, tps):
        self.target_tps = float(tps)
