"""Ratekeeper: cluster-wide admission control.

Ref parity: fdbserver/Ratekeeper.actor.cpp — computes a transactions-per-
second budget from storage/tlog health and conflict rates; GRV proxies
enforce it by delaying or rejecting read-version grants. Ours keeps the
same two-loop shape:

* a **token bucket** at the GRV edge (``admit``), refilled at the current
  target TPS, with batch-priority txns charged more so they only run on
  spare capacity and immediate-priority (system) txns exempt;
* a **control loop** (``update``, pumped by the cluster or simulation)
  that recomputes the target: storage durability lag (versions the
  storage tier is behind the committed version — the analog of the
  reference's storage-queue spring) squeezes the budget smoothly toward
  a floor, and a high conflict ratio (wasted work under contention)
  trims it, recovering multiplicatively when health returns.
"""

import threading
import time


class Ratekeeper:
    # lag (in versions) where the budget starts shrinking / hits the floor
    LAG_SOFT = 1_000_000  # ~1s at 1M versions/sec (the reference's 5s MVCC
    LAG_HARD = 4_000_000  # window leaves ~1s headroom before TOO_OLD pain)
    CONFLICT_TRIM = 0.5  # conflict ratio above which the budget is trimmed
    FLOOR_FRACTION = 0.01

    def __init__(self, target_tps=1e9, batch_priority_fraction=0.5, clock=None):
        self.max_tps = target_tps
        self.target_tps = target_tps
        self.batch_priority_fraction = batch_priority_fraction
        # Injectable clock so the deterministic simulation can drive the
        # token bucket off its step counter instead of wall time (admission
        # results must replay byte-identically under a seed).
        self.clock = clock if clock is not None else time.monotonic
        self._tokens = target_tps
        self._last_refill = self.clock()
        self._recent_txns = 0
        self._recent_conflicts = 0
        self.throttled_count = 0  # GRV requests rejected at the gate
        # thread-mode clusters admit from many client threads while the
        # batcher thread feeds observe_commit/update: the token bucket's
        # read-modify-write must not interleave
        self._mu = threading.Lock()

    # ── GRV-edge enforcement (ref: GrvProxy transaction budgets) ──
    def admit(self, priority="default"):
        if priority == "immediate":
            return True  # system txns bypass (ref: TransactionPriority::IMMEDIATE)
        need = 1.0
        if priority == "batch":
            # batch priority only runs when spare capacity exists
            need = 1.0 / max(self.batch_priority_fraction, 1e-6)
        with self._mu:
            now = self.clock()
            self._tokens = min(
                self.target_tps,
                self._tokens + (now - self._last_refill) * self.target_tps,
            )
            self._last_refill = now
            if self._tokens >= need:
                self._tokens -= need
                return True
            self.throttled_count += 1
            return False

    def observe_commit(self, txns, conflicts):
        """Both arguments are per-batch increments."""
        with self._mu:
            self._recent_txns += txns
            self._recent_conflicts += conflicts

    # ── control loop (ref: Ratekeeper::updateRate) ──
    def update(self, storage_lag_versions=0):
        """Recompute target TPS from tier health; returns the new target.

        ``storage_lag_versions``: committed version minus the slowest
        storage's durable version (the cluster computes it; simulation
        pumps this deterministically).
        """
        with self._mu:
            return self._update_locked(storage_lag_versions)

    def _update_locked(self, storage_lag_versions):
        floor = self.max_tps * self.FLOOR_FRACTION
        # storage spring: full rate below LAG_SOFT, linear squeeze to the
        # floor at LAG_HARD (the reference's smoothed storage queue term)
        if storage_lag_versions <= self.LAG_SOFT:
            lag_target = self.max_tps
        elif storage_lag_versions >= self.LAG_HARD:
            lag_target = floor
        else:
            frac = (storage_lag_versions - self.LAG_SOFT) / (
                self.LAG_HARD - self.LAG_SOFT
            )
            lag_target = self.max_tps - frac * (self.max_tps - floor)

        # conflict trim: mostly-wasted work means admitting more txns only
        # manufactures retries; shed a third, recover gradually when healthy.
        # Sub-threshold samples decay 25% per round instead of hard
        # resetting: a sustained storm accumulates to the 100-txn sample
        # even at low per-round volume (equilibrium 3x the per-round
        # count), while a one-off burst fades within a few rounds and
        # cannot trim a later, healthy period.
        target = min(lag_target, self.max_tps)
        total = self._recent_txns
        if total >= 100:
            ratio = self._recent_conflicts / total
            if ratio > self.CONFLICT_TRIM:
                target = max(floor, min(target, self.target_tps * (2 / 3)))
            self._recent_txns = 0
            self._recent_conflicts = 0
        else:
            self._recent_txns = self._recent_txns * 3 // 4
            self._recent_conflicts = self._recent_conflicts * 3 // 4
        if target > self.target_tps:
            # recover at most 10% per round so oscillation damps out
            target = min(target, max(self.target_tps * 1.1, floor))
        self.target_tps = max(floor, target)
        return self.target_tps

    def set_target_tps(self, tps):
        self.max_tps = float(tps)
        self.target_tps = min(self.target_tps, self.max_tps)
