"""Pluggable persistent storage engines beneath the storage server.

Ref parity: fdbserver/IKeyValueStore.h and its implementations —
KeyValueStoreMemory.actor.cpp (in-RAM tree + operation log for
durability) and KeyValueStoreSQLite.actor.cpp (B-tree file). The storage
server (server/storage.py) keeps the MVCC window as an in-memory overlay
and flushes versions leaving the window down into one of these engines,
advancing its *durable version* behind the *latest version* exactly like
the reference.

Engines are single-version: they store the state as of the durable
version. ``commit(version)`` makes everything written so far durable and
records the version (recovered by ``stored_version()`` after restart).
"""

import os
import pickle
import sqlite3
import struct
import zlib

try:
    from sortedcontainers import SortedDict
except ImportError:  # container without the dep: the in-repo shim
    from foundationdb_tpu.utils.sorteddict import SortedDict

_META_VERSION_KEY = b"\xff\xff/kvstore_version"


class WalEngineBase:
    """Shared durability plumbing: length+CRC-framed op WAL with periodic
    snapshot compaction and torn-tail-tolerant recovery (ref: the
    DiskQueue + snapshot pattern both memory-backed reference engines
    use). Subclasses implement ``_apply_record`` (replay one op),
    ``_snapshot_state`` / ``_load_snapshot`` (full-state serialization).
    """

    def __init__(self, path=None, fsync=False, snapshot_every_ops=50_000):
        self._version = 0
        self.path = path
        self.fsync = fsync
        self._ops_since_snapshot = 0
        self._snapshot_every = snapshot_every_ops
        self._wal = None
        if path is not None:
            self._recover()
            self._wal = open(self._wal_path, "ab")

    @property
    def _snap_path(self):
        return self.path + ".snap"

    @property
    def _wal_path(self):
        return self.path + ".oplog"

    def _log(self, op):
        if self._wal is None:
            return
        payload = pickle.dumps(op, protocol=4)
        self._wal.write(struct.pack(">II", len(payload), zlib.crc32(payload)) + payload)
        self._ops_since_snapshot += 1

    def commit(self, version):
        self._commit_version(version)
        self._log(("v", version, None))
        if self._wal is not None:
            self._wal.flush()
            if self.fsync:
                os.fsync(self._wal.fileno())
            if self._ops_since_snapshot >= self._snapshot_every:
                self.compact()

    def _commit_version(self, version):
        self._version = version

    def compact(self):
        """Snapshot the full state and truncate the op log so recovery
        replay stays bounded."""
        if self.path is None:
            return
        tmp = self._snap_path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(self._snapshot_state(), f, protocol=4)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snap_path)
        if self._wal is not None:
            self._wal.close()
        self._wal = open(self._wal_path, "wb")
        self._ops_since_snapshot = 0

    def _recover(self):
        if os.path.exists(self._snap_path):
            with open(self._snap_path, "rb") as f:
                self._load_snapshot(pickle.load(f))
        try:
            with open(self._wal_path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return
        off = 0
        while off + 8 <= len(raw):
            ln, crc = struct.unpack_from(">II", raw, off)
            if off + 8 + ln > len(raw):
                break  # torn tail
            payload = raw[off + 8 : off + 8 + ln]
            if zlib.crc32(payload) != crc:
                break
            kind, a, b = pickle.loads(payload)
            if kind == "v":
                self._commit_version(a)
            else:
                self._apply_record(kind, a, b)
            off += 8 + ln
        self._ops_since_snapshot = 0

    def close(self):
        if self._wal is not None:
            self._wal.flush()
            self._wal.close()
            self._wal = None


class KeyValueStoreMemory(WalEngineBase):
    """Ordered in-RAM map, optionally durable via snapshot + op WAL.

    Ref: KeyValueStoreMemory — every mutation is logged to a DiskQueue;
    a periodic snapshot bounds replay. Recovery = load snapshot, replay
    the op log, tolerate a torn tail.
    """

    def __init__(self, path=None, fsync=False, snapshot_every_ops=50_000):
        self._data = SortedDict()
        super().__init__(path, fsync, snapshot_every_ops)

    # ── reads ──
    def get(self, key):
        return self._data.get(key)

    def get_range(self, begin, end, limit=0, reverse=False):
        out = []
        for kv in self.iter_range(begin, end, reverse=reverse):
            out.append(kv)
            if limit and len(out) >= limit:
                break
        return out

    def iter_range(self, begin, end, reverse=False):
        """Lazy ordered (key, value) iteration — the storage server merges
        this under its overlay without materializing the range."""
        for k in self._data.irange(begin, end, inclusive=(True, False), reverse=reverse):
            yield k, self._data[k]

    def stored_version(self):
        return self._version

    def __len__(self):
        return len(self._data)

    # ── writes ──
    def set(self, key, value):
        self._data[key] = value
        self._log(("s", key, value))

    def clear_range(self, begin, end):
        for k in list(self._data.irange(begin, end, inclusive=(True, False))):
            del self._data[k]
        self._log(("c", begin, end))

    # ── WalEngineBase hooks ──
    def _snapshot_state(self):
        return (self._version, dict(self._data))

    def _load_snapshot(self, state):
        self._version, data = state
        self._data = SortedDict(data)

    def _apply_record(self, kind, a, b):
        if kind == "s":
            self._data[a] = b
        elif kind == "c":
            for k in list(self._data.irange(a, b, inclusive=(True, False))):
                del self._data[k]


class KeyValueStoreSQLite:
    """B-tree file engine on the stdlib sqlite3 (ref: KeyValueStoreSQLite —
    the reference embeds the same sqlite B-tree, via its own pager)."""

    def __init__(self, path, fsync=False):
        self.path = path
        # check_same_thread=False: in thread-mode batching the batcher
        # thread flushes into an engine the client thread opened; the
        # storage server's mutation lock serializes all access
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute(f"PRAGMA synchronous={'FULL' if fsync else 'NORMAL'}")
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS kv (k BLOB PRIMARY KEY, v BLOB) WITHOUT ROWID"
        )
        self._conn.execute("CREATE TABLE IF NOT EXISTS meta (k BLOB PRIMARY KEY, v BLOB)")

    def get(self, key):
        row = self._conn.execute("SELECT v FROM kv WHERE k = ?", (key,)).fetchone()
        return None if row is None else bytes(row[0])

    def get_range(self, begin, end, limit=0, reverse=False):
        q = "SELECT k, v FROM kv WHERE k >= ? AND k < ? ORDER BY k"
        if reverse:
            q += " DESC"
        if limit:
            q += f" LIMIT {int(limit)}"
        return [
            (bytes(k), bytes(v))
            for k, v in self._conn.execute(q, (begin, end)).fetchall()
        ]

    def iter_range(self, begin, end, reverse=False):
        q = "SELECT k, v FROM kv WHERE k >= ?"
        args = [begin]
        if end is not None:
            q += " AND k < ?"
            args.append(end)
        q += " ORDER BY k DESC" if reverse else " ORDER BY k"
        for k, v in self._conn.execute(q, args):  # lazy cursor
            yield bytes(k), bytes(v)

    def stored_version(self):
        row = self._conn.execute(
            "SELECT v FROM meta WHERE k = ?", (_META_VERSION_KEY,)
        ).fetchone()
        return 0 if row is None else struct.unpack(">q", row[0])[0]

    def __len__(self):
        return self._conn.execute("SELECT COUNT(*) FROM kv").fetchone()[0]

    def set(self, key, value):
        self._conn.execute("INSERT OR REPLACE INTO kv VALUES (?, ?)", (key, value))

    def clear_range(self, begin, end):
        if end is None:
            self._conn.execute("DELETE FROM kv WHERE k >= ?", (begin,))
        else:
            self._conn.execute(
                "DELETE FROM kv WHERE k >= ? AND k < ?", (begin, end)
            )

    def commit(self, version):
        self._conn.execute(
            "INSERT OR REPLACE INTO meta VALUES (?, ?)",
            (_META_VERSION_KEY, struct.pack(">q", version)),
        )
        self._conn.commit()

    def compact(self):
        self._conn.execute("PRAGMA wal_checkpoint(TRUNCATE)")

    def close(self):
        self._conn.commit()
        self._conn.close()


def open_engine(kind, path=None, **kw):
    if kind == "memory":
        return KeyValueStoreMemory(path, **kw)
    if kind == "versioned":
        return KeyValueStoreVersioned(path, **kw)
    if kind == "redwood":
        if path is None:
            raise ValueError("redwood engine requires a path")
        return KeyValueStoreVersionedDisk(path, **kw)
    if kind == "sqlite":
        if path is None:
            raise ValueError("sqlite engine requires a path")
        return KeyValueStoreSQLite(path, **kw)
    raise ValueError(f"unknown storage engine {kind!r}")


class KeyValueStoreVersioned(WalEngineBase):
    """Versioned durable store — the Redwood-role engine.

    Ref parity: fdbserver/VersionedBTree.actor.cpp (Redwood) — the
    reference's flagship engine stores MULTIPLE versions per key in a
    copy-on-write B-tree, so the storage server's MVCC window can extend
    into the durable tier instead of ending at the in-memory overlay.
    Ours keeps the same contract with a different shape (no point
    translating a paged COW tree into Python): per-key version chains in
    an ordered map, an append-only WAL with snapshot compaction for
    durability, and ``prune()`` garbage-collecting history that left the
    retention window.

    The storage server detects ``versioned = True`` and (a) flushes every
    overlay version down instead of folding to the newest, (b) serves
    reads below the durable version from ``get_at`` / ``iter_range_at``,
    and (c) stops force-advancing its read floor at flush time.
    """

    versioned = True

    def __init__(self, path=None, fsync=False, snapshot_every_ops=50_000):
        # key -> [(version, value|None), ...] ascending; None = tombstone
        self._chains = SortedDict()
        self._oldest = 0  # oldest version with full history retained
        # keys prune() must visit: chain length > 1, or a lone tombstone
        # (so prune stays O(prunable), not O(total keys) — it runs on the
        # commit path under the storage mutation lock)
        self._prunable = set()
        super().__init__(path, fsync, snapshot_every_ops)

    # ── versioned reads ──
    @staticmethod
    def _at(chain, version):
        """Newest value at-or-below ``version`` (None = absent/tombstone)."""
        val = None
        for v, x in chain:
            if v <= version:
                val = x
            else:
                break
        return val

    def get_at(self, key, version):
        chain = self._chains.get(key)
        return self._at(chain, version) if chain else None

    def iter_range_at(self, begin, end, version, reverse=False):
        for k in self._chains.irange(begin, end, inclusive=(True, False),
                                     reverse=reverse):
            val = self._at(self._chains[k], version)
            if val is not None:
                yield k, val

    def iter_chains(self, begin, end):
        """Full (key, version-chain) pairs in [begin, end) — shard export
        needs the engine-held history, not just the durable view."""
        for k in list(self._chains.irange(begin, end, inclusive=(True, False))):
            yield k, list(self._chains[k])

    # ── single-version facade (durable view — engine interface compat) ──
    def get(self, key):
        return self.get_at(key, self._version)

    def iter_range(self, begin, end, reverse=False):
        yield from self.iter_range_at(begin, end, self._version, reverse=reverse)

    def get_range(self, begin, end, limit=0, reverse=False):
        out = []
        for kv in self.iter_range(begin, end, reverse=reverse):
            out.append(kv)
            if limit and len(out) >= limit:
                break
        return out

    def stored_version(self):
        return self._version

    @property
    def oldest_retained(self):
        return self._oldest

    def __len__(self):
        return sum(1 for _ in self.iter_range(b"", None))

    # ── writes ──
    def set_versioned(self, key, version, value):
        """Record ``value`` (None = tombstone) for key at version.
        Versions per key arrive ascending (flush order)."""
        self._apply_set_versioned(key, version, value)
        self._log(("sv", key, (version, value)))

    def _apply_set_versioned(self, key, version, value):
        chain = self._chains.get(key)
        if chain is None:
            chain = []
            self._chains[key] = chain
        if chain and chain[-1][0] == version:
            chain[-1] = (version, value)
        else:
            chain.append((version, value))
        if len(chain) > 1 or value is None:
            self._prunable.add(key)

    def set(self, key, value):
        # single-version compat (restore paths); records at the current
        # durable version
        self.set_versioned(key, self._version, value)

    def clear_range(self, begin, end):
        for k in list(self._chains.irange(begin, end, inclusive=(True, False))):
            if self._at(self._chains[k], self._version) is not None:
                self.set_versioned(k, self._version, None)

    def erase_range(self, begin, end):
        """Physically delete all chains in [begin, end) — history and all.

        This is NOT a clear (a clear is a tombstone write at a version);
        shard ingest uses it to evict a stale pre-move copy so the
        source's authoritative history can be installed without
        interleaving out-of-order versions into surviving chains."""
        self._apply_erase(begin, end)
        self._log(("e", begin, end))

    def _apply_erase(self, begin, end):
        for k in list(self._chains.irange(begin, end, inclusive=(True, False))):
            del self._chains[k]
            self._prunable.discard(k)

    def prune(self, before_version):
        """Drop history below ``before_version``: each chain keeps its
        newest entry at-or-below it (the base any admissible read needs)
        and everything newer (ref: Redwood trimming old page versions).
        Visits only chains that can shrink (the _prunable set)."""
        if before_version <= self._oldest:
            return
        self._apply_prune(before_version)
        self._log(("p", before_version, None))

    def _apply_prune(self, before_version):
        for k in list(self._prunable):
            chain = self._chains.get(k)
            if chain is None:
                self._prunable.discard(k)
                continue
            base_idx = -1
            for i, (v, _) in enumerate(chain):
                if v <= before_version:
                    base_idx = i
                else:
                    break
            if base_idx > 0:
                del chain[:base_idx]
            if len(chain) == 1:
                if chain[0][0] <= before_version and chain[0][1] is None:
                    # a tombstone base below the horizon drops entirely
                    del self._chains[k]
                    self._prunable.discard(k)
                elif chain[0][1] is not None:
                    self._prunable.discard(k)  # nothing left to prune
        self._oldest = before_version

    # ── WalEngineBase hooks ──
    def _commit_version(self, version):
        self._version = max(self._version, version)

    def _snapshot_state(self):
        return (self._version, self._oldest, dict(self._chains))

    def _load_snapshot(self, state):
        self._version, self._oldest, chains = state
        self._chains = SortedDict({k: list(c) for k, c in chains.items()})
        self._prunable = {
            k for k, c in self._chains.items()
            if len(c) > 1 or c[-1][1] is None
        }

    def _apply_record(self, kind, a, b):
        if kind == "sv":
            version, value = b
            self._apply_set_versioned(a, version, value)
        elif kind == "e":
            self._apply_erase(a, b)
        elif kind == "p":
            self._apply_prune(a)


class KeyValueStoreVersionedDisk:
    """DISK-RESIDENT versioned store — the Redwood role at Redwood scale.

    Ref parity: fdbserver/VersionedBTree.actor.cpp (Redwood) serves
    versioned reads from a copy-on-write B-tree ON DISK, so the MVCC
    window extends into datasets far beyond RAM. ``KeyValueStoreVersioned``
    keeps every chain in a Python dict — correct, but RAM-bounded (the
    round-3/4 verdicts' open item). This engine keeps the same contract
    with the history IN the B-tree: sqlite rows keyed ``(key, version)``
    (``WITHOUT ROWID`` — the table IS the B-tree, clustered by the
    composite key, so a version chain is physically contiguous), a NULL
    value as the tombstone, visibility resolved by an indexed
    max-version-at-or-below probe, and ``prune()`` garbage-collecting
    history below the retention horizon with SQL deletes. Working-set
    memory is the sqlite page cache (bounded by PRAGMA cache_size), not
    the data size.

    Crash safety rides sqlite's WAL: everything since the last
    ``commit(version)`` rolls back atomically, so recovery resumes from
    the durable version exactly like the reference's engines.
    """

    versioned = True

    # ~4MB page cache: big enough for hot-path index pages, small enough
    # that a past-RAM store provably doesn't ride in memory
    CACHE_KB = 4096

    def __init__(self, path, fsync=False):
        self.path = path
        # check_same_thread=False: thread-mode batchers flush from a
        # different thread; the storage server's mutation lock serializes
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute(
            f"PRAGMA synchronous={'FULL' if fsync else 'NORMAL'}")
        self._conn.execute(f"PRAGMA cache_size=-{self.CACHE_KB}")
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS kvv ("
            " k BLOB NOT NULL, v INTEGER NOT NULL, val BLOB,"
            " PRIMARY KEY (k, v)) WITHOUT ROWID"
        )
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS meta (k BLOB PRIMARY KEY, v BLOB)")
        self._version = self._meta_int(b"version", 0)
        self._oldest = self._meta_int(b"oldest", 0)
        # keys written since the last prune — bounds the steady-state
        # prune to recently-touched chains; pre-crash history is swept by
        # one full-table prune on the first call after open
        self._prunable = set()
        self._full_prune_pending = True

    def _meta_int(self, key, default):
        row = self._conn.execute(
            "SELECT v FROM meta WHERE k = ?", (key,)).fetchone()
        return default if row is None else struct.unpack(">q", row[0])[0]

    def _meta_set(self, key, value):
        self._conn.execute("INSERT OR REPLACE INTO meta VALUES (?, ?)",
                           (key, struct.pack(">q", value)))

    # ── versioned reads ──
    def get_at(self, key, version):
        row = self._conn.execute(
            "SELECT val FROM kvv WHERE k = ? AND v <= ?"
            " ORDER BY v DESC LIMIT 1", (key, version),
        ).fetchone()
        if row is None or row[0] is None:
            return None
        return bytes(row[0])

    def iter_range_at(self, begin, end, version, reverse=False):
        # bare-column-with-MAX: sqlite guarantees ``val`` comes from the
        # max-v row of each group (documented since 3.7.11) — one
        # index-ordered pass instead of a correlated probe per key
        q = "SELECT k, val, MAX(v) FROM kvv WHERE k >= ?"
        args = [begin]
        if end is not None:
            q += " AND k < ?"
            args.append(end)
        q += " AND v <= ? GROUP BY k ORDER BY k"
        args.append(version)
        if reverse:
            q += " DESC"
        for k, val, _ in self._conn.execute(q, args):
            if val is not None:
                yield bytes(k), bytes(val)

    def iter_chains(self, begin, end):
        """Full (key, version-chain) pairs in [begin, end) — shard export
        carries engine-held history (same contract as the RAM engine).
        ``end=None`` (the last shard's open upper bound) omits the end
        clause — ``k < NULL`` matches nothing in SQL."""
        chain_key, chain = None, []
        q = "SELECT k, v, val FROM kvv WHERE k >= ?"
        args = [begin]
        if end is not None:
            q += " AND k < ?"
            args.append(end)
        cur = self._conn.execute(q + " ORDER BY k, v", args)
        for k, v, val in cur:
            k = bytes(k)
            if k != chain_key:
                if chain:
                    yield chain_key, chain
                chain_key, chain = k, []
            chain.append((v, None if val is None else bytes(val)))
        if chain:
            yield chain_key, chain

    # ── single-version facade (durable view — engine interface compat) ──
    def get(self, key):
        return self.get_at(key, self._version)

    def iter_range(self, begin, end, reverse=False):
        yield from self.iter_range_at(begin, end, self._version,
                                      reverse=reverse)

    def get_range(self, begin, end, limit=0, reverse=False):
        out = []
        for kv in self.iter_range(begin, end, reverse=reverse):
            out.append(kv)
            if limit and len(out) >= limit:
                break
        return out

    def stored_version(self):
        return self._version

    @property
    def oldest_retained(self):
        return self._oldest

    def __len__(self):
        return sum(1 for _ in self.iter_range(b"", None))

    # ── writes ──
    def set_versioned(self, key, version, value):
        """Record ``value`` (None = tombstone) for key at version (same
        re-write-at-same-version replace semantics as the RAM chains)."""
        self._conn.execute("INSERT OR REPLACE INTO kvv VALUES (?, ?, ?)",
                           (key, version, value))
        self._prunable.add(key)

    def set(self, key, value):
        # single-version compat (restore paths): records at the current
        # durable version
        self.set_versioned(key, self._version, value)

    def clear_range(self, begin, end):
        # tombstone every key LIVE at the durable version (a clear is a
        # versioned write, not physical deletion — history stays
        # readable below it); end=None = open-ended, like iter_range_at
        q = "SELECT k, val, MAX(v) FROM kvv WHERE k >= ?"
        args = [begin]
        if end is not None:
            q += " AND k < ?"
            args.append(end)
        args.append(self._version)
        rows = self._conn.execute(q + " AND v <= ? GROUP BY k",
                                  args).fetchall()
        for k, val, _ in rows:
            if val is not None:
                self.set_versioned(bytes(k), self._version, None)

    def erase_range(self, begin, end):
        """Physically delete all chains in [begin, end) — history and
        all (shard ingest evicting a stale pre-move copy; NOT a clear).
        ``end=None`` erases the open-ended tail, matching the RAM
        engine's irange semantics."""
        q = "DELETE FROM kvv WHERE k >= ?"
        args = [begin]
        if end is not None:
            q += " AND k < ?"
            args.append(end)
        self._conn.execute(q, args)

    def prune(self, before_version):
        """Drop history below the horizon: each chain keeps its newest
        entry at-or-below it plus everything newer; lone tombstone bases
        below the horizon drop entirely (ref: Redwood trimming old page
        versions). Steady state visits only chains written since the
        last prune; the first prune after open sweeps the whole table
        (pre-crash history has no in-memory prunable record)."""
        if before_version <= self._oldest and not self._full_prune_pending:
            return
        if self._full_prune_pending:
            self._prune_sql(before_version, None)
            self._prunable = self._shrinkable(None)
            self._full_prune_pending = False
        elif self._prunable:
            # keep keys that can STILL shrink under a later horizon
            # (multi-version chains, or a tombstone awaiting its drop) —
            # discarding them would freeze their history forever once
            # writes stop (the RAM engine's _prunable has the same rule)
            keys = list(self._prunable)
            self._prunable = set()
            for i in range(0, len(keys), 500):
                chunk = keys[i:i + 500]
                self._prune_sql(before_version, chunk)
                self._prunable |= self._shrinkable(chunk)
        self._oldest = max(self._oldest, before_version)
        self._meta_set(b"oldest", self._oldest)

    def _shrinkable(self, keys):
        scope = "" if keys is None else \
            f" WHERE k IN ({','.join('?' * len(keys))})"
        q = ("SELECT k FROM kvv" + scope +
             " GROUP BY k HAVING COUNT(*) > 1 OR SUM(val IS NULL) > 0")
        return {bytes(r[0])
                for r in self._conn.execute(q, list(keys or []))}

    def _prune_sql(self, before_version, keys):
        scope = "" if keys is None else \
            f" AND k IN ({','.join('?' * len(keys))})"
        args = [] if keys is None else list(keys)
        # 1) rows strictly below their chain's base at the horizon
        self._conn.execute(
            "DELETE FROM kvv WHERE v < ?" + scope +
            " AND v < (SELECT MAX(v) FROM kvv b WHERE b.k = kvv.k"
            "          AND b.v <= ?)",
            [before_version] + args + [before_version],
        )
        # 2) lone tombstone bases below the horizon
        self._conn.execute(
            "DELETE FROM kvv WHERE v <= ? AND val IS NULL" + scope +
            " AND NOT EXISTS (SELECT 1 FROM kvv b WHERE b.k = kvv.k"
            "                 AND b.v > kvv.v)",
            [before_version] + args,
        )

    # ── durability ──
    def commit(self, version):
        self._version = max(self._version, version)
        self._meta_set(b"version", self._version)
        self._conn.commit()

    def compact(self):
        self._conn.execute("PRAGMA wal_checkpoint(TRUNCATE)")

    def close(self):
        self._conn.commit()
        self._conn.close()
