"""Horizontally scaled transaction frontend: commit-proxy + GRV fleets.

Ref parity: the reference runs a FLEET of commit proxies and GRV proxies
(fdbserver/CommitProxyServer.actor.cpp, GrvProxyServer.actor.cpp), with
the sequencer chaining each batch's version to the one granted before it
(masterserver.actor.cpp getVersion prevVersion) so batches from
different proxies interleave into one serial order. Here the chaining
lives in ``Sequencer.next_commit_versions`` and two ``VersionGate``s
order the stateful pipeline stages (resolve history; log+storage apply)
across the fleet — see ``server/proxy.py``. These facades give the fleet
the same surface a single proxy has, so the client stack, status json,
recovery, and management paths are fleet-agnostic:

- ``ProxyFleet``: round-robins client commits across members, fans
  management state (database lock, tenant mode) out to every member,
  and aggregates counters.
- ``GrvFleet``: round-robins read-version requests across GRV proxies.
"""

import itertools


class ProxyFleet:
    """``members`` are the client-facing proxies (batching wrappers in
    thread pipelines, the bare proxies otherwise); ``inners`` are the
    bare ``CommitProxy`` instances the members drive."""

    def __init__(self, members, inners):
        self.members = members
        self.inners = inners
        self._rr = itertools.count()

    def _pick(self):
        return self.members[next(self._rr) % len(self.members)]

    # ── client surface (round-robined) ──
    def commit(self, request):
        return self._pick().commit(request)

    def submit(self, request):
        return self._pick().submit(request)

    def commit_batch(self, requests):
        return self._pick().commit_batch(requests)

    def commit_batches(self, request_batches):
        return self.inners[next(self._rr) % len(self.inners)].commit_batches(
            request_batches
        )

    # ── management surface ──
    @property
    def inner(self):
        # _commit_target() unwraps batching pipelines via .inner; the
        # fleet IS its own management target (state fans out below)
        return self

    @property
    def alive(self):
        return all(p.alive for p in self.inners)

    def kill(self):
        for p in self.inners:
            p.kill()

    @property
    def lock_uid(self):
        return getattr(self.inners[0], "lock_uid", None)

    @lock_uid.setter
    def lock_uid(self, uid):
        # every member enforces the lock: a commit through ANY proxy of
        # a locked database must fail 1038
        for p in self.inners:
            p.lock_uid = uid

    @property
    def tenant_mode(self):
        return getattr(self.inners[0], "tenant_mode", "optional")

    @tenant_mode.setter
    def tenant_mode(self, mode):
        for p in self.inners:
            p.tenant_mode = mode

    def update_resolver_ranges(self, fence=True):
        """One member derives (and, on a boundary move, fences) the
        resolver ranges; the rest copy the bounds — re-deriving per
        member would fence the shared resolvers once per proxy."""
        self.inners[0].update_resolver_ranges(fence=fence)
        for p in self.inners[1:]:
            p.resolver_bounds = self.inners[0].resolver_bounds

    # ── lifecycle / pipeline plumbing ──
    def flush(self):
        for m in self.members:
            if hasattr(m, "flush"):
                m.flush()

    def pump(self, step):
        for m in self.members:
            if hasattr(m, "pump"):
                m.pump(step)

    def fail_pending(self, error):
        for m in self.members:
            if hasattr(m, "fail_pending"):
                m.fail_pending(error)

    def close(self):
        for m in self.members:
            if hasattr(m, "close"):
                m.close()
        for p in self.inners:
            p.close()

    # ── aggregated counters (status json, bench) ──
    @property
    def commit_count(self):
        return sum(p.commit_count for p in self.inners)

    @property
    def conflict_count(self):
        return sum(p.conflict_count for p in self.inners)

    @property
    def txns_batched(self):
        return sum(getattr(m, "txns_batched", 0) for m in self.members)

    @property
    def batches_committed(self):
        return sum(getattr(m, "batches_committed", 0) for m in self.members)

    @property
    def max_batch_seen(self):
        return max(
            (getattr(m, "max_batch_seen", 0) for m in self.members),
            default=0,
        )

    @property
    def _backlog_target(self):
        # the most-throttled member's depth: the honest contention signal
        return min(
            (getattr(m, "_backlog_target", 1) for m in self.members),
            default=1,
        )

    def metrics_snapshots(self):
        """Per-member metric snapshots (the status doc's commit-proxy
        members section; each member shares its inner proxy's registry
        so batcher spans and proxy counters land in one document)."""
        return [p.metrics.snapshot() for p in self.inners]

    def stage_summary(self):
        """Fleet view of the members' commit-pipeline stage timings:
        means across members, worst-case configured depth."""
        sums = [m.stage_summary() for m in self.members
                if hasattr(m, "stage_summary")]
        if not sums:
            return {}
        out = {}
        for k in sums[0]:
            vals = [s[k] for s in sums]
            if k == "pipeline_depth":
                out[k] = max(vals)
            elif k in ("pack_path", "resolver_sharding"):
                # the members' dominant value; "mixed" when they differ
                out[k] = vals[0] if len(set(vals)) == 1 else "mixed"
            elif k == "resolver_lanes":
                # every member fronts the same resolver fleet
                out[k] = max(vals)
            elif k in ("pack_flat_batches", "pack_legacy_batches"):
                out[k] = sum(vals)
            else:
                out[k] = round(sum(vals) / len(vals), 3)
        return out

    def __len__(self):
        return len(self.inners)


class GrvFleet:
    def __init__(self, members):
        self.members = members
        self._rr = itertools.count()

    def get_read_version(self, priority="default", tags=()):
        return self.members[next(self._rr) % len(self.members)] \
            .get_read_version(priority, tags)

    @property
    def grv_count(self):
        return sum(m.grv_count for m in self.members)

    def metrics_snapshots(self):
        return [m.metrics.snapshot() for m in self.members]

    def close(self):
        for m in self.members:
            if hasattr(m, "close"):
                m.close()

    def __getattr__(self, name):  # sequencer, ratekeeper, ... pass through
        return getattr(self.members[0], name)

    def __len__(self):
        return len(self.members)
