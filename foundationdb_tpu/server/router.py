"""Storage router: the client-side view of a partitioned storage tier.

Ref parity: what NativeAPI's key-range → storage-server-interface cache
plus LoadBalance do for the reference client (fdbclient/NativeAPI
getKeyLocation / fdbrpc/LoadBalance.actor.h): every read names a key or
range, the shard map names the owning team, and the request goes to one
replica of that team — with range reads and key-selector walks stitched
across shard boundaries in key order.

The router exposes the same read surface as a single StorageServer —
selector resolution and range reads come from the shared
RangeReadInterface (storage.py) over a cross-shard merged iterator —
so the transaction layer is placement-agnostic: full replication is
just the one-shard case.
"""

from foundationdb_tpu.core.errors import FDBError, err
from foundationdb_tpu.server.storage import RangeReadInterface


class StorageRouter(RangeReadInterface):
    def __init__(self, storages, shard_map, rr_counter):
        self.storages = storages
        self.map = shard_map
        self._rr = rr_counter  # shared round-robin counter (cluster-owned)

    def _pick(self, team):
        """One LIVE replica of a team (ref: LoadBalance — spread reads,
        route around detected-dead interfaces). With every replica dead
        the read fails retryable; recruitment brings one back."""
        live = [sid for sid in team if self.storages[sid].alive]
        if not live:
            raise err("process_behind")
        return self.storages[live[next(self._rr) % len(live)]]

    def storage_for(self, key):
        return self._pick(self.map.team_for(key))

    # ── single-storage invariants preserved across the tier ──
    def _check_version(self, version):
        """Cheap global bounds; the authoritative floor check is per
        consulted storage inside _iter_live, because floors diverge the
        moment a joiner ingests a shard (its floor rises to the source's)
        — a read between two floors must fail TOO_OLD on the raised-floor
        shard, never silently omit its keys."""
        live = [s for s in self.storages if s.alive]
        if not live:
            raise err("process_behind")
        if version < min(s.oldest_version for s in live):
            raise err("transaction_too_old")
        if version > max(s.version for s in live):
            raise err("future_version")

    @property
    def version(self):
        return min(s.version for s in self.storages)

    # ── point ops ──
    def get(self, key, version):
        return self.storage_for(key).get(key, version)

    def read_batch(self, ops):
        """Multiplexed multi-op serve across the tier: point gets
        group per owning storage (one lock crossing per storage per
        batch — StorageServer.read_batch), ranges/selectors serve
        per-op (they may stitch shards). Per-op FDBError slots, never
        batch-fatal — a dead replica fails only its own keys."""
        out = [None] * len(ops)
        groups = {}  # team -> [(index, op)] — ONE replica pick per
        # team per batch (picking per key would round-robin a team's
        # replicas and split the batch into singletons)
        for i, op in enumerate(ops):
            if op[0] == "g":
                try:
                    team = self.map.team_for(op[1])
                except FDBError as e:
                    out[i] = e
                    continue
                groups.setdefault(tuple(team), []).append((i, op))
            else:
                out[i] = self._serve_one(op)
        for team, members in groups.items():
            try:
                st = self._pick(team)
            except FDBError as e:
                for i, _ in members:
                    out[i] = e
                continue
            slots = st.read_batch([op for _, op in members])
            for (i, _), slot in zip(members, slots):
                out[i] = slot
        return out

    def _serve_one(self, op):
        try:
            if op[0] == "r":
                return [
                    (k, v) for k, v in self.get_range(
                        op[1], op[2], op[3], limit=op[4], reverse=op[5]
                    )
                ]
            if op[0] == "s":
                return self.resolve_selector(op[1], op[2])
            raise err("client_invalid_operation")
        except FDBError as e:
            return e

    def watch(self, key, seen_value):
        """Registered on the key's current owner. A shard relocation
        fires affected watches spuriously (the mover's analog of the
        reference erroring watches with wrong_shard_server), so watchers
        re-read rather than hang on a storage that stopped receiving
        the key's mutations."""
        return self.storage_for(key).watch(key, seen_value)

    # ── cross-shard merged iteration (feeds RangeReadInterface) ──
    def _iter_live(self, begin, end, version, reverse=False):
        idxs = self.map.shards_overlapping(begin, end)
        if reverse:
            idxs = list(reversed(idxs))
        for i in idxs:
            sb, se = self.map.shard_range(i)
            b = max(begin, sb)
            if end is None:
                e = se
            elif se is None:
                e = end
            else:
                e = min(end, se)
            storage = self._pick(self.map.teams[i])
            storage._check_version(version)
            yield from storage._iter_live(b, e, version, reverse=reverse)
