"""Data distribution: shard map, splits/merges, and team rebalancing.

Ref parity: fdbserver/DataDistribution.actor.cpp + DDTracker/DDQueue —
the reference divides the keyspace into contiguous shards, tracks each
shard's size via storage-server byte samples, splits shards that grow
past the split threshold, merges runs of small shards, and enqueues
RelocateShard moves so every storage team carries a fair share.

Ours is the same control loop, host-side (this is metadata work — it
does not belong on the TPU): a ``ShardMap`` of boundary → team, byte
accounting fed by the commit proxy, and a ``rebalance()`` step the
cluster pumps periodically (simulation pumps it deterministically).
Replication: a shard's team is a list of storage ids; moves copy the
shard's data to the destination before flipping the map, so reads at
old versions keep working (the reference's fetchKeys + TSS-free path).
"""

import bisect

from foundationdb_tpu.utils.trace import TraceEvent


class ShardMap:
    """Contiguous partition of the keyspace: boundaries[i] owns
    [boundaries[i], boundaries[i+1]). boundaries[0] is always b"".

    Per-shard byte accounting lives here (not beside it) so splits and
    merges — wherever they are invoked from — can never desync the
    metadata from the boundaries.

    Ref: keyServers / shardBoundaries in the system keyspace.
    """

    def __init__(self, teams=None):
        self.boundaries = [b""]
        self.teams = [list(teams[0]) if teams else [0]]
        self.sizes = [0]  # sampled bytes per shard
        self.last_keys = [None]  # most recent write per shard

    @classmethod
    def restore(cls, boundaries, teams, sizes=None):
        """Rebuild from persisted system-keyspace rows (ref: reading
        keyServers at recovery)."""
        m = cls()
        m.boundaries = list(boundaries)
        m.teams = [list(t) for t in teams]
        m.sizes = list(sizes) if sizes else [0] * len(boundaries)
        m.last_keys = [None] * len(boundaries)
        return m

    def team_for(self, key):
        return self.teams[bisect.bisect_right(self.boundaries, key) - 1]

    def shard_index(self, key):
        return bisect.bisect_right(self.boundaries, key) - 1

    def shard_range(self, i):
        end = self.boundaries[i + 1] if i + 1 < len(self.boundaries) else None
        return self.boundaries[i], end

    def shards_overlapping(self, begin, end):
        """Indices of shards intersecting [begin, end)."""
        i = self.shard_index(begin)
        out = []
        while i < len(self.boundaries):
            b = self.boundaries[i]
            if end is not None and b >= end:
                break
            out.append(i)
            i += 1
        return out

    def split(self, i, at):
        b, e = self.shard_range(i)
        if not (b < at and (e is None or at < e)):
            raise ValueError(f"split point {at!r} outside shard [{b!r}, {e!r})")
        self.boundaries.insert(i + 1, at)
        self.teams.insert(i + 1, list(self.teams[i]))
        half = self.sizes[i] // 2
        self.sizes[i] -= half
        self.sizes.insert(i + 1, half)
        self.last_keys.insert(i + 1, self.last_keys[i])

    def merge(self, i):
        """Merge shard i+1 into shard i (teams must match)."""
        if i + 1 >= len(self.boundaries):
            raise ValueError("no right neighbor to merge")
        if self.teams[i] != self.teams[i + 1]:
            raise ValueError("cannot merge shards on different teams")
        del self.boundaries[i + 1]
        del self.teams[i + 1]
        self.sizes[i] += self.sizes.pop(i + 1)
        self.last_keys.pop(i + 1)

    def assign(self, i, team):
        self.teams[i] = list(team)

    def __len__(self):
        return len(self.boundaries)


class DataDistributor:
    """The DD control loop over a cluster's storage servers.

    The commit proxy calls ``note_write(key, nbytes)`` per mutation
    (the analog of storage byte sampling); ``rebalance()`` runs one
    round of split / merge / move decisions and returns the moves it
    performed, each as (shard_range, old_team, new_team).
    """

    def __init__(self, storages, shard_map=None, replication=1,
                 max_shard_bytes=250_000, min_shard_bytes=10_000):
        self.storages = storages
        self.replication = min(replication, len(storages))
        self.map = shard_map or ShardMap(
            teams=[list(range(self.replication))]
        )
        self.max_shard_bytes = max_shard_bytes
        self.min_shard_bytes = min_shard_bytes
        self.excluded = set()  # storages being drained (ref: fdbcli exclude)

    def storage_owns_nothing(self, sid):
        """True when no shard's team includes sid — safe to remove."""
        return all(sid not in team for team in self.map.teams)

    def drain_excluded(self):
        """Relocate every shard off excluded storages (ref: DD honoring
        the excluded-servers list: exclusion drains, then the operator
        removes the process). Returns the moves performed this round;
        callers poll storage_owns_nothing to learn when a drain is done."""
        moves = []
        for i, team in enumerate(list(self.map.teams)):
            bad = [s for s in team if s in self.excluded]
            if not bad:
                continue
            load = self.team_bytes()
            candidates = sorted(
                (
                    s for s in range(len(self.storages))
                    if s not in team and s not in self.excluded
                    and self.storages[s].alive
                ),
                key=load.__getitem__,
            )
            if len(candidates) < len(bad):
                continue  # not enough healthy storages; drain stalls
            new_team = [
                s if s not in self.excluded else candidates.pop(0)
                for s in team
            ]
            if self._relocate(i, team, new_team):
                moves.append((self.map.shard_range(i), team, new_team))
        return moves

    def note_write(self, key, nbytes):
        i = self.map.shard_index(key)
        self.map.sizes[i] += nbytes
        self.map.last_keys[i] = key

    def note_clear_range(self, begin, end):
        for i in self.map.shards_overlapping(begin, end):
            self.map.sizes[i] = max(0, self.map.sizes[i] // 2)

    def team_bytes(self):
        out = [0] * len(self.storages)
        for size, team in zip(self.map.sizes, self.map.teams):
            for s in team:
                out[s] += size
        return out

    def rebalance(self):
        moves = []
        self._split_large()
        self._merge_small()
        moves.extend(self.drain_excluded())
        moves.extend(self._move_for_balance())
        return moves

    # ── splits (ref: shardSplitter) ──
    def _split_large(self):
        i = 0
        while i < len(self.map):
            if self.map.sizes[i] > self.max_shard_bytes:
                at = self._split_point(i)
                if at is not None:
                    self.map.split(i, at)
                    TraceEvent("DDShardSplit").detail(
                        index=i, at=at, bytes=self.map.sizes[i] * 2).log()
                    i += 1
            i += 1

    def _split_point(self, i):
        """Median key of the shard from a LIVE owning storage's data."""
        b, e = self.map.shard_range(i)
        team = self.map.teams[i]
        live = [s for s in team if self.storages[s].alive]
        if not live:
            return None  # split waits until recruitment revives an owner
        storage = self.storages[live[0]]
        keys = [k for k, _ in storage.read_range(
            b, e, storage.version, limit=1001)]
        if len(keys) < 2:
            return None
        at = keys[len(keys) // 2]
        return at if b < at else None

    # ── merges (ref: shardMerger) ──
    def _merge_small(self):
        # hysteresis: whatever the configured floor, never merge two
        # shards whose combined size would immediately re-trip the split
        # threshold's neighborhood — otherwise one rebalance() round
        # splits and the next line merges it back, forever
        threshold = min(self.min_shard_bytes, self.max_shard_bytes // 4)
        i = 0
        while i + 1 < len(self.map):
            if (
                self.map.sizes[i] + self.map.sizes[i + 1] < threshold
                and self.map.teams[i] == self.map.teams[i + 1]
            ):
                self.map.merge(i)
            else:
                i += 1

    # ── moves (ref: BgDDMountainChopper / ValleyFiller) ──
    def _move_for_balance(self):
        if len(self.storages) < 2:
            return []
        moves = []
        for _ in range(2):  # bounded moves per round, like DD's queue
            load = self.team_bytes()
            hot = max(range(len(load)), key=load.__getitem__)
            # coldest NON-excluded candidate: a draining storage reads 0
            # bytes and would otherwise be the global min forever,
            # stalling balancing for every healthy storage
            eligible = [
                s for s in range(len(load)) if s not in self.excluded
            ]
            if len(eligible) < 2:
                break
            cold = min(eligible, key=load.__getitem__)
            diff = load[hot] - load[cold]
            if diff < self.max_shard_bytes:
                break
            # biggest shard on `hot` but not `cold` that strictly improves
            # balance (size < diff, else the move just flips the skew)
            cands = [
                i for i, team in enumerate(self.map.teams)
                if hot in team and cold not in team and self.map.sizes[i] < diff
            ]
            if not cands:
                break
            i = max(cands, key=self.map.sizes.__getitem__)
            old_team = list(self.map.teams[i])
            new_team = [cold if s == hot else s for s in old_team]
            if not self._relocate(i, old_team, new_team):
                break  # dead participant: retry after recruitment
            moves.append((self.map.shard_range(i), old_team, new_team))
        return moves

    def _relocate(self, i, old_team, new_team):
        """Copy shard data to joining storages, then flip the map entry
        (ref: fetchKeys then the keyServers commit). Refuses (returns
        False, map untouched) when no live source exists or a joiner is
        dead — exporting a corpse's frozen overlay would install stale
        data under the new map, and a dead joiner's ingest dies with it
        at recruitment."""
        b, e = self.map.shard_range(i)
        live_src = [s for s in old_team if self.storages[s].alive]
        joining = [s for s in new_team if s not in old_team]
        leaving = [s for s in old_team if s not in new_team]
        if not live_src or any(not self.storages[s].alive for s in joining):
            return False
        src = self.storages[live_src[0]]
        if joining:
            export = src.export_shard(b, e)  # one snapshot, k joiners
            for sid in joining:
                self.storages[sid].ingest_shard(b, e, export)
        self.map.assign(i, new_team)
        for sid in leaving:
            # wake watchers parked on the departing replica; they re-read
            # and re-register via the router against the new owner
            self.storages[sid].fire_watches_in_range(b, e)
        TraceEvent("DDRelocateShard").detail(
            begin=b, end=e, old=old_team, new=new_team).log()
        return True
